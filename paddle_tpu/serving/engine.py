"""Serving engine: fixed-shape jitted steps over the paged KV pool.

Four compiled step shapes serve every request mix (the continuous-
batching contract — the device never recompiles as traffic changes):

  * chunked prefill  — B=1, T=prefill_chunk: one prompt chunk streams
    through the model, its K/V landing in the sequence's pool pages;
  * batched decode   — B=max_batch_size, T=1: every RUNNING request
    advances one token in ONE dispatch;
  * batched verify   — B=max_batch_size, T=spec_k+1 (only with
    speculative decoding, spec_k > 0): each greedy request carries its
    n-gram-proposed draft tokens as extra ragged query rows — the same
    causal-within-sequence masking chunked prefill uses — and the
    accept-longest-agreeing-prefix rule plus a bonus token advances a
    request up to spec_k+1 tokens per dispatch, token-identical to the
    one-token path;
  * fused decode     — B=max_batch_size, k=fused_k iterations of the
    decode step rolled into ONE dispatch via lax.scan (only with
    fused_k > 1): the carry holds the sampled token, per-row seq_len,
    eos/budget done-mask and the paged KV pool, so the host fetches
    sampled ids once per k tokens instead of once per token. Engaged
    per dispatch only when the scheduler is quiescent for the window
    (no waiting work, no mid-window admit/retire hazard, no degrade
    transition due) and every row's k-token page reservation fits;
    otherwise the engine falls back to the [B, 1] step. Tokens are
    IDENTICAL to serial decode for greedy and sampled rows alike: the
    sampling key is folded per (request ordinal, absolute position),
    never per dispatch.

Prefix caching (ISSUE 9) rides in the pool: prompts sharing a prefix
map the same physical pages (kv_pool.py refcounts + hash-chained
index), so cache hits skip whole prefill chunks and TTFT drops to the
uncached tail's cost.

Both run `GPTModel.forward_paged` (ragged paged attention +
`write_kv_pages` scatter) under `jit` with the KV pool donated, sample
the next token ON DEVICE (greedy argmax or temperature/top-k via
jax.random), and fetch only the sampled token ids — the single
per-token host round-trip. Idle decode slots ride along with q_len=0:
their K/V writes are dropped by the scatter and their outputs ignored,
so occupancy is a pure scheduling concern.

Scheduling (admit / chunk order / preempt-youngest) lives in
scheduler.py; page accounting in kv_pool.py; ptpu_serve_* metrics in
metrics.py; per-request lifecycle tracing in request_trace.py — every
host-side scheduling decision the engine makes lands in the request's
journal and the scheduler timeline, with zero extra device syncs.
docs/serving.md covers tuning the knobs.
"""
import math
import os
import time

import numpy as np

from .kv_pool import KVPagePool, PoolExhausted, _np_dtype
from .scheduler import (AdmissionRejected, DegradeLadder, Request,
                        RequestState, Scheduler, SchedulerTimeline,
                        TenantTable)
from .request_trace import (ENGINE_REQ, RequestTracer,
                            build_serve_report, write_serve_report)
from . import metrics as _metrics
from .ledger import ServeLedger
from ..core import monitor as _monitor
from ..core.async_step import HostGapMonitor, unregister_monitor
from ..profiler import RecordEvent


def _host_fetch(x):
    """Every host sync the engine performs (the per-step sampled-token
    fetch) funnels through this hook so tests can count them — the
    PR-3 numerics._host_fetch convention. Tracing must not add calls
    here (asserted in tests/test_serving_trace.py)."""
    return np.asarray(x)


class ServingConfig:
    """Knobs (docs/serving.md#tuning):

    page_size        tokens per KV page (TPU lane-friendly: >= 8)
    max_batch_size   decode slots = max concurrent requests
    num_pages        pool capacity; default fits every slot at
                     max_pages_per_seq (no preemption pressure)
    max_pages_per_seq  page-table width; default covers max_seq_len
    prefill_chunk    prompt tokens per prefill dispatch
    kv_dtype         pool dtype (default: model param dtype).
                     'int8' stores block-paged K/V as int8 with one
                     abs-max fp32 scale per (token slot, head) in
                     sibling scale buffers; attention dequantizes
                     inside the paged-attention kernel, so the pool
                     holds ~4x (vs fp32) / ~2x (vs bf16) more tokens
                     per byte (docs/serving.md#quantized-kv)
    weight_dtype     None (default) or 'int8': weight-only-quantized
                     decode — matmul weights (ndim >= 2, embeddings
                     excluded) are stored int8 with per-out-channel
                     abs-max scales and dequantized inside the jitted
                     step (XLA fuses the scale multiply into the
                     matmul's operand upcast), reusing
                     quantization.quantize_to_int8. NOTE: the engine
                     does not own the model, so the model's full-
                     precision weights stay resident beside the int8
                     copies — the win is the step's weight-read
                     bandwidth, not total HBM; drop the model's params
                     yourself (or load via load_quantized_predictor)
                     to reclaim the memory
    prefix_cache     copy-on-write prefix sharing over the paged pool
                     (default on): requests whose prompts share a
                     prefix map the same physical pages and skip the
                     prefill compute for them; granularity is one page
                     (page_size tokens) — docs/serving.md#prefix-cache
    spec_k           speculative decoding draft length (default 0 =
                     off): an n-gram proposer drafts up to k tokens
                     per greedy request and a third compiled step
                     shape [max_batch, spec_k+1] verifies them all in
                     ONE dispatch (accept-longest-agreeing-prefix +
                     bonus token; greedy output is token-identical to
                     spec_k=0 — docs/serving.md#speculative-decode)
    spec_ngram       proposer match length: the trailing n-gram looked
                     up in the request's own token history (prompt +
                     generated) to source draft continuations
    fused_k          decode iterations fused into one dispatch
                     (default: $PTPU_SERVE_FUSED_K, else 1 = off): a
                     fourth compiled shape scans k decode steps on
                     device and fetches sampled ids once per window,
                     cutting the per-token host round-trip k-fold at
                     small batch. Token-identical to fused_k=1; falls
                     back to the [B, 1] step whenever the scheduler
                     is not quiescent for a full window, draft
                     proposals exist this dispatch (spec verify wins),
                     or a row's k-token page reservation doesn't fit.
                     Ladder stage 1+ sheds it before spec_k
                     (docs/serving.md#fused-decode)
    seed             device sampling stream seed
    trace            per-request lifecycle journal on/off (host-only
                     bookkeeping; default on — docs/serving.md)
    trace_events_per_request / trace_requests   journal caps
    timeline_capacity  scheduler-timeline ring size (iterations)
    request_deadline_s stalled-request watchdog deadline (None = off):
                       a request older than this produces a
                       serve_report artifact
    deadline_action  'report' (default) or 'abort' (also drop it)
    report_dir       serve_report directory (default:
                     $PTPU_SERVE_REPORT_DIR, then $FLEET_LOG_DIR)
    clock            monotonic clock for ALL request timing
                     (tests inject a deterministic one)
    disaggregate     prefill/decode disaggregation (ISSUE 11, default
                     off): chunked prefill runs on a dedicated prefill
                     engine whose finished KV pages STREAM into the
                     decode engine's pool, where the request is
                     adopted into a decode slot
                     (serving/cluster/disagg.py,
                     docs/serving.md#disaggregated-serving)
    prefill_slots    prefill-engine slot count under disaggregation
    stream_chunk_pages  pages per streamed copy op (0 = one shot) —
                     bounds the handoff's staging footprint like the
                     PR-10 chunked collectives
    tenants          multi-tenant policy map (ISSUE 15, default None):
                     {tenant_id: {priority, quota_tokens_per_s,
                     burst_tokens, weight}}. priority (int, larger =
                     more important) orders admission and bounds
                     preemption; quota_tokens_per_s feeds a refillable
                     token bucket debited at admit (over-quota tenants
                     DEFER, never drop); weight drives stage-3
                     prefix-cache eviction. Unknown/anonymous tenants
                     get priority 0, no quota, weight 1.0. With no
                     tenants declared scheduling is IDENTICAL to the
                     untenanted engine (docs/serving.md#multi-tenant)
    degrade          graceful-degradation ladder: None (default) =
                     auto (on exactly when `tenants` is set), or an
                     explicit bool. Stages under sustained pressure:
                     1 sheds speculative decoding, 2 halves the
                     prefill chunk, 3 evicts prefix-cache subtrees by
                     tenant weight; walks back down hysteretically
    degrade_window   pressure-signal window (iterations)
    degrade_up       stage up-thresholds (windowed mean pressure)
    degrade_down     stage down-thresholds (must sit below their
                     up-threshold — the hysteresis band)
    degrade_hold     consecutive calm iterations before stepping down
    """

    def __init__(self, page_size=16, max_batch_size=4, num_pages=None,
                 max_pages_per_seq=None, prefill_chunk=32,
                 kv_dtype=None, weight_dtype=None, prefix_cache=True,
                 spec_k=0, spec_ngram=2, fused_k=None, seed=0,
                 trace=True,
                 trace_events_per_request=512, trace_requests=512,
                 timeline_capacity=2048, request_deadline_s=None,
                 deadline_action='report', report_dir=None, clock=None,
                 disaggregate=False, prefill_slots=2,
                 stream_chunk_pages=0, tenants=None, degrade=None,
                 degrade_window=8, degrade_up=(0.85, 0.92, 0.97),
                 degrade_down=(0.60, 0.70, 0.80), degrade_hold=4,
                 host_tier_pages=0, spill_watermark=0.92,
                 spill_chunk_pages=0, spill_window=2):
        if page_size <= 0 or max_batch_size <= 0 or prefill_chunk <= 0:
            raise ValueError("page_size, max_batch_size and "
                             "prefill_chunk must be positive")
        if spec_k < 0 or spec_ngram < 1:
            raise ValueError("spec_k must be >= 0 and spec_ngram >= 1")
        if fused_k is None:
            fused_k = int(os.environ.get('PTPU_SERVE_FUSED_K', '1'))
        if int(fused_k) < 1:
            raise ValueError("fused_k must be >= 1 (1 = per-token "
                             "decode, k > 1 = fused k-step windows)")
        if deadline_action not in ('report', 'abort'):
            raise ValueError("deadline_action must be 'report' or "
                             "'abort'")
        self.page_size = int(page_size)
        self.max_batch_size = int(max_batch_size)
        self.num_pages = num_pages
        self.max_pages_per_seq = max_pages_per_seq
        self.prefill_chunk = int(prefill_chunk)
        self.kv_dtype = kv_dtype
        if weight_dtype is not None and \
                _np_dtype(weight_dtype) != np.int8:
            raise ValueError("weight_dtype must be None or 'int8', got "
                             f"{weight_dtype!r}")
        self.weight_dtype = weight_dtype
        self.prefix_cache = bool(prefix_cache)
        self.spec_k = int(spec_k)
        self.spec_ngram = int(spec_ngram)
        self.fused_k = int(fused_k)
        self.seed = int(seed)
        self.trace = bool(trace)
        self.trace_events_per_request = int(trace_events_per_request)
        self.trace_requests = int(trace_requests)
        self.timeline_capacity = int(timeline_capacity)
        self.request_deadline_s = request_deadline_s
        self.deadline_action = deadline_action
        self.report_dir = report_dir
        self.clock = clock
        self.disaggregate = bool(disaggregate)
        self.prefill_slots = int(prefill_slots)
        self.stream_chunk_pages = int(stream_chunk_pages)
        if tenants is not None and not isinstance(tenants, dict):
            raise ValueError("tenants must be a {tenant_id: policy} "
                             "dict or None")
        self.tenants = dict(tenants) if tenants is not None else None
        if degrade not in (None, True, False):
            raise ValueError("degrade must be None (auto), True or "
                             "False")
        self.degrade = degrade
        self.degrade_window = int(degrade_window)
        self.degrade_up = tuple(degrade_up)
        self.degrade_down = tuple(degrade_down)
        self.degrade_hold = int(degrade_hold)
        # host-RAM KV tier (ISSUE 20): 0 host pages = no tier — the
        # engine then keeps PR-19's compiled shapes, host-sync count
        # and gauge set exactly (asserted in test_serving_kvtier.py)
        if int(host_tier_pages) < 0:
            raise ValueError("host_tier_pages must be >= 0 (0 = no "
                             "host tier)")
        if not (0.0 < float(spill_watermark) <= 1.0):
            raise ValueError("spill_watermark must be in (0, 1]")
        self.host_tier_pages = int(host_tier_pages)
        self.spill_watermark = float(spill_watermark)
        self.spill_chunk_pages = int(spill_chunk_pages)
        self.spill_window = int(spill_window)

    @property
    def degrade_enabled(self):
        """The ladder's effective switch: explicit bool wins, None
        means on exactly when tenants are declared — the untenanted
        default must keep today's behavior (and compiled step shapes)
        bit-for-bit."""
        if self.degrade is None:
            return self.tenants is not None
        return self.degrade


class ServingEngine:
    """Continuous-batching inference over a GPTForCausalLM.

    `mesh`: an optional replica-local jax Mesh with an 'mp' axis — the
    mp-sharded serving route (ISSUE 11): attention heads (and the KV
    pool's pages) split over 'mp' exactly like the training flash
    route, so one replica can span several chips when the model's KV
    doesn't fit one. The model must have been built under a fleet hcg
    whose mp degree equals the mesh's 'mp' size (mp_layers then mark
    qkv/out/vocab params with their split axes and emit the Megatron
    collectives inside the traced step). docs/serving.md#mp-sharding.
    """

    def __init__(self, model, config=None, mesh=None, ledger_site=None,
                 **cfg_kw):
        import jax
        import jax.numpy as jnp
        if config is None:
            config = ServingConfig(**cfg_kw)
        elif cfg_kw:
            raise ValueError("pass either config or knobs, not both")
        if config.disaggregate:
            # the flag selects a DIFFERENT engine class — silently
            # serving unified under a disaggregate config would lie
            raise ValueError(
                "config.disaggregate=True needs the disaggregated "
                "engine: build via serving.cluster.build_engine(...) "
                "or serving.cluster.DisaggregatedEngine(...) "
                "(docs/serving.md#disaggregated-serving)")
        self.model = model
        self.config = config
        mcfg = model.config
        ps = config.page_size
        self.max_pages_per_seq = int(
            config.max_pages_per_seq
            or math.ceil(mcfg.max_seq_len / ps))
        num_pages = int(config.num_pages
                        or config.max_batch_size * self.max_pages_per_seq)
        attn0 = model.gpt.layers[0].attn
        dtype = (config.kv_dtype
                 or model.gpt.embeddings.word_embeddings.weight.dtype)
        self.mesh = mesh
        self._mp = int(mesh.shape['mp']) if (
            mesh is not None and 'mp' in mesh.shape) else 1
        if self._mp > 1:
            if attn0.world_size != self._mp:
                raise ValueError(
                    f"mesh mp={self._mp} but the model was built with "
                    f"mp degree {attn0.world_size} — fleet.init (or a "
                    f"minimal hcg) with model-parallel degree "
                    f"{self._mp} BEFORE constructing the model")
            if config.weight_dtype is not None:
                raise ValueError(
                    "weight_dtype='int8' is not supported on the "
                    "mp-sharded serving route yet (per-out-channel "
                    "scales would need their own split specs)")
        # the pool holds GLOBAL heads; under mp the arrays are sharded
        # on the trailing heads*hd axis so each shard owns its local
        # heads' pages — the same layout the column-sharded qkv writes
        self.pool = KVPagePool(
            num_pages, ps, num_layers=mcfg.num_layers,
            num_heads=attn0.local_heads * self._mp,
            head_dim=attn0.head_dim,
            dtype=dtype, prefix_cache=config.prefix_cache)
        self._kv_sharding = None
        if self._mp > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            self._kv_sharding = NamedSharding(mesh, P(None, None, 'mp'))
        self.pool.materialize(sharding=self._kv_sharding)
        # host-RAM KV tier (ISSUE 20): pinned host buffers + one
        # background transfer thread under the pool. Spills are
        # proactive (watermark in _observe_spill_pressure) or the
        # pool's own synchronous exhaustion fallback; resurrection
        # happens inside match_and_map on the prefill path. Disabled
        # (the default) the attribute stays None and every tier hook
        # below is a single falsy check.
        self._host_tier = None
        self._tier_spilled_seen = 0
        if config.host_tier_pages > 0:
            from .host_tier import HostTier
            self._host_tier = self.pool.attach_host_tier(HostTier(
                config.host_tier_pages,
                chunk_pages=config.spill_chunk_pages,
                window=config.spill_window))
        self._clock = config.clock or time.perf_counter
        self.scheduler = Scheduler(config.max_batch_size,
                                   clock=self._clock)
        # request observatory: lifecycle journals + iteration timeline
        # (host-only bookkeeping on data the scheduler already holds)
        self.tracer = RequestTracer(
            capacity_requests=config.trace_requests,
            events_per_request=config.trace_events_per_request,
            clock=self._clock) if config.trace else None
        self.timeline = SchedulerTimeline(config.timeline_capacity)
        self.last_serve_report = None
        self._stall_reported = set()        # req ids already reported
        self._params = {n: p.data for n, p in model.named_parameters()}
        # weight-only-quantized decode (ISSUE 7): matmul weights live
        # on device as int8 + per-out-channel abs-max scales; the
        # jitted step dequantizes at trace time so XLA fuses the scale
        # multiply into the matmul operand upcast. Embeddings (and the
        # tied LM head) stay full precision — logit ordering is the
        # product, don't round it.
        self._qparam_dtypes = {}
        if config.weight_dtype is not None:
            from ..quantization import quantize_to_int8
            for n, a in list(self._params.items()):
                # 2-D matmul weights only (per-out-channel scales);
                # GPT serving has no convs — higher-rank params keep
                # full precision rather than guessing a channel axis
                if a.ndim != 2 or 'embed' in n or \
                        not jnp.issubdtype(a.dtype, jnp.floating):
                    continue
                q, s = quantize_to_int8(
                    np.asarray(jax.device_get(a), np.float32),
                    quant_axis=a.ndim - 1)
                self._params[n] = {'q': jnp.asarray(q),
                                   's': jnp.asarray(s)}
                self._qparam_dtypes[n] = a.dtype
        # mp-sharded params: split specs from the mp_layers marks
        # (split_axis over 'mp', everything else replicated); placed
        # once here so the jitted step never reshards weights
        self._param_specs = None
        if self._mp > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            specs = {}
            for n, p in model.named_parameters():
                spec = [None] * len(p.data.shape)
                if getattr(p, 'is_distributed', False):
                    spec[p.split_axis] = 'mp'
                specs[n] = P(*spec)
            self._param_specs = specs
            self._params = {
                n: jax.device_put(a, NamedSharding(mesh, specs[n]))
                for n, a in self._params.items()}
        self._step_fns = {}
        # CONSTANT base sampling key: per-row keys are derived inside
        # the step as fold_in(fold_in(base, request_ordinal),
        # absolute_position), so the token sampled at position p of
        # request o is a pure function of (seed, o, p) — the invariant
        # that makes fused-k, serial decode, spec verify and
        # preempt/resume re-prefill all emit IDENTICAL sampled tokens
        self._key = jax.random.key(config.seed)
        # engine-local submission ordinal feeding that fold (NOT the
        # process-global Request.id, which would couple sampled output
        # to unrelated engines constructed earlier in the process)
        self._next_sample_ord = 0
        self._jnp = jnp
        self._jax = jax
        # lifetime accounting for stats()/metrics
        self._decode_time = 0.0
        self._decode_tokens = 0
        self._decode_steps = 0
        self._occupancy_sum = 0.0
        self._util_sum = 0.0
        self._prefill_tokens = 0
        self._prefill_chunks = 0
        # speculative decoding accounting (draft tokens proposed by
        # the n-gram proposer vs accepted by the verify step)
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_steps = 0
        # fused-decode accounting (ISSUE 19): windows dispatched, the
        # device iterations they ran, and the tokens they delivered
        self._fused_windows = 0
        self._fused_iterations = 0
        self._fused_tokens = 0
        # per-step handoff from _fused_decode_window to step() so the
        # timeline/ledger record one entry per fused ITERATION (the
        # router occupancy tiebreak and staleness alerting consume
        # per-iteration signals, not per-dispatch ones)
        self._fused_last = None
        self._submitted = 0
        self._completed = 0
        self._aborted = 0
        self._ttfts_s = []
        self._new_ttfts_s = []
        # per-retire SLO samples pending the next histogram publish
        self._new_slo = {'queue_wait_s': [], 'tpot_s': [], 'e2e_s': [],
                         'preemptions': []}
        self._last_publish = 0.0
        # WALL-clock twin of _last_publish: the periodic publish path
        # keys staleness-relevant cadence to the monitor's time source
        # (the same one gauge last_update stamps and `metrics_stale`
        # alert rules read), so a deterministic injected config.clock —
        # or a fused window that retires k tokens between steps — can
        # never starve gauge freshness (ISSUE 19 satellite)
        self._last_publish_wall = 0.0
        # multi-tenant SLO layer (ISSUE 15): policy table (priority /
        # quota buckets / eviction weights), the degradation ladder,
        # and per-tenant lifetime accounting. All None/zero when no
        # tenants are configured — the default engine pays one
        # attribute check per sweep and nothing else.
        self._tenants = (TenantTable(config.tenants, clock=self._clock)
                         if config.tenants is not None else None)
        self._ladder = (DegradeLadder(
            window=config.degrade_window, up=config.degrade_up,
            down=config.degrade_down, hold=config.degrade_hold,
            clock=self._clock) if config.degrade_enabled else None)
        self._quota_deferrals = 0
        self._preemptions_charged = 0
        self._deadline_rejects = 0
        # pools co-armed with self.pool on stage-3 transitions: under a
        # SHARED ladder (disaggregated prefill+decode) whichever engine
        # observes the transition must arm/disarm weighted eviction on
        # BOTH pools, not just its own (ISSUE 16 satellite)
        self._stage3_pools = ()
        self._deadline_misses = 0
        self._tenant_stats = {}
        # per-tenant SLO samples pending the next histogram publish
        # (tenant-labeled ptpu_serve_tenant_* histograms)
        self._new_tenant_slo = {}
        # deadline-aware admission switch: the disaggregated facade
        # turns it OFF on its prefill engine (whose local backlog and
        # decode rate misrepresent the pipeline) and checks the
        # combined estimate itself before forwarding the submit
        self.deadline_admission = True
        # serving ledger + host-gap observatory (ISSUE 17): the
        # sampled-token fetch is this engine's only host sync, so a
        # registered HostGapMonitor over the step loop turns its wait
        # into a real host_bound_fraction; the ServeLedger carries the
        # wall decomposition, the goodput account and the decode
        # bytes-moved roofline. Both unregister at shutdown().
        self.ledger_site = ledger_site or 'serve'
        self._gap = HostGapMonitor(site=self.ledger_site)
        param_bytes = 0
        for a in self._params.values():
            if isinstance(a, dict):     # int8 weight: q + scales
                param_bytes += int(a['q'].nbytes) + int(a['s'].nbytes)
            else:
                param_bytes += int(getattr(a, 'nbytes', 0) or 0)
        n_params = sum(int(getattr(p.data, 'size', 0) or 0)
                       for _n, p in model.named_parameters())
        self.ledger = ServeLedger(
            engine=self.ledger_site, gap=self._gap,
            n_params=n_params, layers=mcfg.num_layers,
            hidden=mcfg.hidden_size, param_bytes=param_bytes,
            kv_bytes_per_token=self.pool.bytes_per_token())
        # per-iteration phase accumulators step() resets and
        # _prefill_chunk_step/_decode_step feed (host perf_counter
        # segments — never a device sync)
        self._it_compute = 0.0
        self._it_fetch = 0.0
        self._it_decode_s = 0.0
        self._it_kv_read_tokens = 0
        self._it_prefill_tokens = 0
        self._it_prefill_s = 0.0
        self._it_prefill_ctx = 0

    # followers a budget-blocked queue head tolerates being admitted
    # past it before the admission sweep reverts to blocking at the
    # head (head-of-line fairness with a starvation bound)
    HOL_BYPASS_LIMIT = 8

    # seconds between periodic gauge publishes on a busy engine —
    # publishing rebuilds stats and touches ~20 monitor gauges, which
    # is host work the per-token decode path shouldn't pay every step
    # (retire and drain always publish immediately)
    PUBLISH_INTERVAL_S = 0.5

    # -- tenancy helpers -----------------------------------------------------
    @staticmethod
    def _blank_tstat():
        return {'submitted': 0, 'completed': 0, 'aborted': 0,
                'quota_deferrals': 0, 'preemptions_charged': 0,
                'charge_tokens': 0, 'deadline_rejects': 0,
                'deadline_misses': 0, 'tokens_billed': 0}

    def _tstat(self, tenant_id):
        """Per-tenant lifetime accounting row (created on first use —
        WRITE paths only; read paths use _tenant_stats.get so a stats
        call never materializes rows for traffic that never came)."""
        tid = str(tenant_id)
        st = self._tenant_stats.get(tid)
        if st is None:
            st = self._tenant_stats[tid] = self._blank_tstat()
        return st

    def decode_rate(self):
        """Observed decode throughput (generated tokens/sec), 0.0 until
        the first measured decode step."""
        return (self._decode_tokens / self._decode_time
                if self._decode_time else 0.0)

    def pending_tokens(self):
        """Tokens of work already accepted but not yet computed:
        un-prefilled prompt + remaining generation budget across the
        queue and the slots — the backlog a new request queues behind
        (the replica status() math, shared with deadline admission)."""
        reqs = ([r for r in self.scheduler.slots if r is not None]
                + list(self.scheduler.waiting))
        return sum(max(r.max_new_tokens - len(r.generated), 0)
                   + max(len(r.tokens) - r.prefilled, 0)
                   for r in reqs)

    def _estimate_completion_s(self, extra_tokens):
        """Estimated seconds until a request of `extra_tokens` total
        work would complete behind the current backlog — the PR-11
        router deadline_bound_s math moved down into the engine. None
        while no decode rate has been observed (a cold engine admits;
        rejecting on zero data would refuse the first request)."""
        rate = self.decode_rate()
        if rate <= 0.0:
            return None
        return (self.pending_tokens() + extra_tokens) / rate

    def degrade_stage(self):
        return self._ladder.stage if self._ladder is not None else 0

    def _effective_spec_k(self):
        """Ladder stage 1+ sheds speculative decoding — a pure-
        throughput optimization whose draft verify columns cost pool
        pages and step FLOPs the overloaded engine needs elsewhere
        (outputs are spec-invariant by the PR-9 bar, so shedding is
        invisible in tokens)."""
        if self._ladder is not None and self._ladder.stage >= 1:
            return 0
        return self.config.spec_k

    def _effective_prefill_chunk(self):
        """Ladder stage 2+ halves the prefill chunk (floor: one page):
        new requests trade TTFT for the running set's TPOT — each
        sweep spends less of the step on prefill FLOPs. A distinct
        compiled shape (1, chunk//2), warmed on first use and gauged
        via the stage transition."""
        C = self.config.prefill_chunk
        if self._ladder is not None and self._ladder.stage >= 2:
            # never LARGER than the configured chunk: with page_size >
            # prefill_chunk the floor would otherwise grow the chunk
            # (and compile a never-warmed bigger shape) mid-overload
            return min(C, max(self.pool.page_size, C // 2))
        return C

    def _effective_fused_k(self):
        """Ladder stage 1+ sheds the fused window FIRST, ahead of
        spec_k in the same stage's use-site ordering: the window is a
        pure latency-amortization whose k-token page reservations and
        held retire slots are exactly the flexibility an overloaded
        scheduler needs back. Outputs are fused-invariant by the ISSUE
        19 bar, so shedding is invisible in tokens."""
        if self._ladder is not None and self._ladder.stage >= 1:
            return 1
        return self.config.fused_k

    def _fused_ok(self, k):
        """Quiescence gate for a k-iteration fused window: the
        scheduler must have no decision due (Scheduler.quiescent) and
        the degrade ladder no stage transition reachable within k
        observations of the CURRENT pressure (DegradeLadder.
        would_transition) — a window the ladder would interrupt
        mid-flight must not be dispatched at all."""
        if not self.scheduler.quiescent():
            return False
        if self._ladder is not None:
            p = DegradeLadder.pressure_of(
                self.pool.utilization(), len(self.scheduler.waiting),
                self.config.max_batch_size,
                spill=self._spill_pressure())
            if self._ladder.would_transition(p, k):
                return False
        return True

    def ladder_history(self):
        """Stage-transition events [{t, from, to, pressure}] — the
        bench leg's ladder timeline."""
        return list(self._ladder.history) if self._ladder else []

    # -- request intake ------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=32, eos_token_id=None,
               temperature=1.0, top_k=0, tenant_id=None, priority=None,
               deadline_s=None):
        """Queue one request. `tenant_id`/`priority`/`deadline_s` are
        the multi-tenant knobs (ISSUE 15): priority defaults to the
        tenant's policy class (explicit values override), and a
        deadline the backlog already makes unmeetable REJECTS here with
        a structured AdmissionRejected (retry_after_s hint) instead of
        queueing to certain failure."""
        if priority is None:
            priority = (self._tenants.priority_of(tenant_id)
                        if self._tenants is not None else 0)
        req = Request(prompt_ids, max_new_tokens=max_new_tokens,
                      eos_token_id=eos_token_id, temperature=temperature,
                      top_k=top_k, tenant_id=tenant_id,
                      priority=priority, deadline_s=deadline_s)
        total = len(req.prompt) + req.max_new_tokens
        if total > self.max_pages_per_seq * self.pool.page_size:
            raise ValueError(
                f"request needs {total} tokens; page table holds "
                f"{self.max_pages_per_seq} pages of {self.pool.page_size}")
        if self.pool.pages_for(total) > self.pool.num_pages:
            # reject NOW: admission's page budget would skip it forever
            # (no amount of preemption frees pages the pool doesn't have)
            raise PoolExhausted(
                f"KV pool ({self.pool.num_pages} pages x "
                f"{self.pool.page_size}) cannot hold one request of "
                f"{total} tokens — raise num_pages")
        if total > self.model.config.max_seq_len:
            raise ValueError(
                f"prompt({len(req.prompt)}) + max_new_tokens"
                f"({req.max_new_tokens}) exceeds max_seq_len"
                f"({self.model.config.max_seq_len})")
        if req.deadline_s is not None and self.deadline_admission:
            est = self._estimate_completion_s(total)
            if est is not None and est > req.deadline_s:
                self._deadline_rejects += 1
                if req.tenant_id is not None:
                    self._tstat(req.tenant_id)['deadline_rejects'] += 1
                raise AdmissionRejected(
                    'deadline_unmet',
                    retry_after_s=est - req.deadline_s,
                    estimated_s=est, deadline_s=req.deadline_s)
        # sampling ordinal: engine-local, assigned in submission order
        # so identically-seeded engines fed the same prompts derive
        # identical per-position sampling keys (the fused-vs-serial
        # and disaggregated-vs-unified token-identity bar). Adopted
        # requests (disaggregation) carry the ordinal their submitting
        # engine assigned.
        if req.sample_ord is None:
            req.sample_ord = self._next_sample_ord
            self._next_sample_ord += 1
        self.scheduler.submit(req)
        self._submitted += 1
        if req.tenant_id is not None:
            self._tstat(req.tenant_id)['submitted'] += 1
        fields = {}
        if req.tenant_id is not None:
            fields['tenant_id'] = req.tenant_id
        if req.priority:
            fields['priority'] = req.priority
        if req.deadline_s is not None:
            fields['deadline_s'] = req.deadline_s
        self._trace(req, 'submit', t=req.submit_time,
                    prompt_tokens=len(req.prompt),
                    max_new_tokens=req.max_new_tokens, **fields)
        return req

    def _trace(self, req, event, t=None, **fields):
        if self.tracer is not None:
            self.tracer.record(req.id, event, t=t, **fields)

    def generate(self, prompts, max_new_tokens=32, eos_token_id=None,
                 temperature=1.0, top_k=0, max_steps=None):
        """Batch convenience: submit every prompt, drive step() until
        drained, return per-prompt token lists (prompt + generated) in
        submission order."""
        reqs = [self.submit(p, max_new_tokens=max_new_tokens,
                            eos_token_id=eos_token_id,
                            temperature=temperature, top_k=top_k)
                for p in prompts]
        guard = max_steps or 16 * (max_new_tokens + 4) * max(
            1, math.ceil(len(reqs) / self.config.max_batch_size))
        steps = 0
        while self.scheduler.has_work:
            self.step()
            steps += 1
            if steps > guard:
                raise RuntimeError(
                    f"serving loop did not drain in {guard} steps")
        return [r.output_ids() for r in reqs]

    # -- engine iteration ----------------------------------------------------
    def step(self):
        """One scheduler iteration: admit waiting requests, advance one
        prefill chunk per prefilling request, then one batched decode
        step for the running set. Records a timeline entry, runs the
        stalled-request watchdog, publishes metrics."""
        completed_before = self._completed
        preempt_before = self.scheduler.preemptions
        t_begin = self._gap.dispatch_begin()
        self._it_compute = 0.0
        self._it_fetch = 0.0
        self._it_decode_s = 0.0
        self._it_kv_read_tokens = 0
        self._it_prefill_tokens = 0
        self._it_prefill_s = 0.0
        self._it_prefill_ctx = 0
        t_sched = time.perf_counter()
        with RecordEvent('serve::schedule', event_type='serve'):
            self._check_stalled()
            admitted = self._admit()
        sched_dt = time.perf_counter() - t_sched
        prefilling = [r for r in self.scheduler.slots
                      if r is not None and r.state == RequestState.PREFILL]
        prefill_tokens = 0
        for req in prefilling:
            with RecordEvent('serve::prefill_chunk', event_type='serve',
                             req=req.id):
                prefill_tokens += self._prefill_chunk_step(req)
        running = [r for r in self.scheduler.slots
                   if r is not None and r.state == RequestState.RUNNING]
        decode_slots = decode_tokens = 0
        if running:
            with RecordEvent('serve::decode', event_type='serve'):
                # POST-preemption counts: _decode_step may preempt
                # members of `running` under pool pressure; slots are
                # the surviving rows, tokens what they emitted (> slots
                # when speculative decoding accepts drafts)
                decode_slots, decode_tokens = self._decode_step()
        # one observability record per decode ITERATION: a fused
        # window runs n_iter device iterations inside one dispatch,
        # and the timeline / ladder / ledger must see the same per-
        # iteration stream serial decode produces (k entries, each
        # with that iteration's row occupancy; wall and phase segments
        # amortized across the window) — otherwise every downstream
        # consumer of these signals (router occupancy tiebreaks, alert
        # rules, ledger decode throughput) would read a kx-slower
        # engine. Admissions/preemptions/prefill attribute to the
        # first entry only: they happened once, before the window.
        fused = self._fused_last
        self._fused_last = None
        n_iter = fused['iters'] if fused else 1
        wall = time.perf_counter() - t_begin
        for j in range(n_iter):
            first = (j == 0)
            self._observe_pressure()
            entry = dict(
                t=self._clock(),
                decode_slots_occupied=(fused['rows'][j] if fused
                                       else decode_slots),
                decode_slots=self.config.max_batch_size,
                prefill_tokens=prefill_tokens if first else 0,
                decode_tokens=(fused['rows'][j] if fused
                               else decode_tokens),
                admissions=admitted if first else 0,
                preemptions=(self.scheduler.preemptions - preempt_before
                             if first else 0),
                waiting=len(self.scheduler.waiting),
                pool_pages_in_use=self.pool.pages_in_use,
                pool_pages_total=self.pool.num_pages,
                degrade_stage=self.degrade_stage())
            if fused:
                entry['fused'] = True
                entry['fused_k'] = fused['k']
            self.timeline.record(**entry)
            # ledger close-out: the iteration wall and its measured
            # phase segments. Under a fused window the one host fetch
            # amortizes over the window's iterations — the per-window
            # host-fetch attribution that makes host_bound_fraction
            # drop k-fold instead of misreading the window as one
            # giant iteration.
            self.ledger.observe_iteration(
                wall=wall / n_iter,
                compute=self._it_compute / n_iter,
                host_fetch=self._it_fetch / n_iter,
                schedule=sched_dt / n_iter,
                decode_seconds=self._it_decode_s / n_iter,
                kv_read_tokens=self._it_kv_read_tokens // n_iter,
                prefill_tokens=self._it_prefill_tokens if first else 0,
                prefill_seconds=self._it_prefill_s if first else 0.0,
                prefill_ctx_tokens=self._it_prefill_ctx if first else 0)
        # host-tier close-out (ISSUE 20): transfer wall accumulated by
        # spill/fetch since the last step folds into the ledger's
        # page_stream component (the disagg-handoff attribution point),
        # and newly spilled pages since the last step emit one engine-
        # scope `spill` trace event. One falsy check when tierless; no
        # host sync either way (the tier counts on the transfer thread).
        if self._host_tier is not None:
            tier_wall = self._host_tier.take_wall()
            if tier_wall > 0.0:
                self.ledger.note_page_stream(tier_wall)
            spilled = self._host_tier.spilled_pages
            if spilled > self._tier_spilled_seen:
                if self.tracer is not None:
                    self.tracer.record(
                        ENGINE_REQ, 'spill',
                        pages=spilled - self._tier_spilled_seen,
                        host_used_pages=self._host_tier.used_slots)
                self._tier_spilled_seen = spilled
        # gap-monitor span close: dispatch_end BEFORE note_gating —
        # dispatch_end zeroes the pending gating attribution, and the
        # fetch wait belongs to the span that just closed (it is
        # consumed by the NEXT dispatch_begin).
        self._gap.dispatch_end(depth=1)
        if self._it_fetch > 0.0:
            self._gap.note_gating(self._it_fetch)
        # publish cadence: retire and drain publish immediately; the
        # periodic path keys to the MONITOR's wall clock (the same
        # source gauge last_update stamps and staleness alert rules
        # read), never to config.clock — an injected deterministic
        # clock, or fused windows retiring k tokens per step, must not
        # let gauge freshness lapse into `metrics_stale` alerts.
        if (self._completed != completed_before
                or not self.scheduler.has_work
                or (_monitor._time_fn() - self._last_publish_wall
                    >= self.PUBLISH_INTERVAL_S)):
            self.publish_metrics()

    def _observe_pressure(self):
        """Feed the degradation ladder this iteration's pressure and
        apply any stage transition: gauge set immediately, an engine-
        scope `degrade_stage` trace event, and the stage-3 weighted-
        eviction lever armed/disarmed on the pool. Stage 1 (spec shed)
        and 2 (prefill shrink) act through _effective_spec_k /
        _effective_prefill_chunk at their use sites."""
        self._observe_spill_pressure()
        if self._ladder is None:
            return
        ev = self._ladder.observe(self.pool.utilization(),
                                  len(self.scheduler.waiting),
                                  self.config.max_batch_size,
                                  spill=self._spill_pressure())
        if ev is None:
            return
        _metrics.publish_degrade_stage(self._ladder.stage,
                                       self._ladder.pressure())
        if self.tracer is not None:
            self.tracer.record(
                ENGINE_REQ, 'degrade_stage', t=ev['t'],
                from_stage=ev['from'], stage=ev['to'],
                stage_name=DegradeLadder.STAGE_NAMES[ev['to']],
                pressure=ev['pressure'])
        if ev['to'] >= 3 and self._tenants is not None:
            weights = self._tenants.eviction_weights()
            for pool in (self.pool, *self._stage3_pools):
                pool.set_eviction_weights(weights)
        elif ev['from'] >= 3 > ev['to']:
            for pool in (self.pool, *self._stage3_pools):
                pool.set_eviction_weights(None)

    def _spill_pressure(self):
        """Host-tier occupancy in [0, 1] — the ladder's spill input
        (ISSUE 20): while the tier has room, spilling absorbs pool
        pressure and the ladder need not escalate to stage-3 weighted
        eviction; a saturating tier pushes pressure back up so the
        eviction lever arms only once the second tier is spent. 0.0
        without a tier — the ladder then sees exactly PR-19's signal."""
        t = self._host_tier
        return t.used_slots / t.host_pages if t is not None else 0.0

    def _observe_spill_pressure(self):
        """The proactive spiller: pool utilization past the spill
        watermark kicks an ASYNC spill of LRU-parked cached subtrees
        (bounded by the transfer window) so the free list restocks off
        the critical path — allocation's synchronous spill fallback is
        for when this didn't keep up. A falsy check without a tier."""
        if self._host_tier is None:
            return
        if self.pool.utilization() >= self.config.spill_watermark \
                and self.pool.cached_pages > 0:
            self.pool.spill_lru(
                max_pages=max(self.pool.num_pages // 8, 1))

    def _admit(self):
        """Admit waiting requests one at a time against a free-page
        budget: each admission reserves its FIRST chunk's pages (the
        pool doesn't allocate until the prefill step runs, so the
        budget, not pool.free_pages, is what shrinks here) — admitting
        more than the pool can first-chunk just manufactures
        preemption churn.

        Prefix-cache hits shrink the bill (ISSUE 9 satellite: the
        PR-5 estimate over-counted and refused admissible requests):
        pages a live sibling already maps cost the budget NOTHING,
        and cached-resurrect pages cost a page but no prefill compute
        — so the need is the first chunk's page-table size minus the
        live-shared pages.

        Head-of-line fairness (ISSUE 11 satellite): a head whose first
        chunk exceeds this sweep's budget no longer blocks the sweep —
        the scan continues down the queue and admits any follower that
        DOES fit (FCFS order among the admissible). The skipped head
        keeps its queue position; and so that a stream of small
        requests can't starve it forever (every retire's freed pages
        going straight to a new follower), each follower admitted past
        it counts against HOL_BYPASS_LIMIT — once spent, the sweep
        reverts to blocking at the head, freed pages accumulate across
        sweeps, and the head admits as soon as they cover its chunk.

        Tenancy (ISSUE 15): the sweep runs in priority-then-FCFS
        order (scheduler.admission_order — arrival order when no
        tenants are configured), and a quota'd tenant's request debits
        its whole token bill from the tenant bucket at FIRST admit.
        Insufficient quota DEFERS the request (skipped this sweep, a
        `quota_defer` trace event on the defer edge) — it admits once
        the bucket refills; the defer does not spend the HOL bypass
        bound (quota is the tenant's own backpressure, not page
        starvation). Resume after preemption never re-debits."""
        sched = self.scheduler
        budget = self.pool.free_pages
        n_admitted = 0
        n_bypassed = 0          # admissions AFTER the head blocked —
                                # only those are bypasses (a request
                                # admitted while it was itself the
                                # head passed nobody)
        blocked_head = None
        skipped_before = False  # "req is the live queue head" ⟺ every
                                # earlier entry of the sweep admitted —
                                # the order-list twin of the old
                                # `req is waiting[0]` check
        for req in sched.admission_order():
            victim = None
            if None not in sched.slots:
                # slot-pressure preemption (tenancy only): a waiting
                # request strictly ABOVE some running tenant's class
                # displaces the youngest of the lowest class below it
                # — the admitting request's victim rule — instead of
                # waiting out the victim's whole decode. Charged like
                # any preemption; the victim re-queues at the front of
                # its class and, being lower-priority, cannot churn
                # back in. Untenanted engines break here exactly as
                # before (FCFS never preempts for admission).
                if self._tenants is None:
                    break
                victim = sched.preempt_victim(
                    below_priority=req.priority)
                if victim is None:
                    break       # order is priority-sorted: nobody
                                # later outranks the running set either
            # host-resurrect pages (ISSUE 20) bill the page budget one
            # allocatable page each, same as device-resurrect — but
            # their cost is a host→device TRANSFER, not prefill
            # compute: the cached span still skips the prefill chunks,
            # and the fetch wall lands in the ledger's page_stream
            # component instead of compute
            cached, live, _resv, _host = self.pool.peek_prefix(
                req.tokens, limit=len(req.tokens) - 1)
            need = max(self.pool.pages_for(
                min(len(req.tokens),
                    cached + self._effective_prefill_chunk())) - live,
                0)
            # feasibility BEFORE any side effect: nothing is billed
            # and no victim's work is destroyed for an admit the page
            # budget still wouldn't cover (a victim whose pages are
            # all shared reclaims nothing — count only what its
            # release would actually free)
            avail = budget + (self.pool.reclaimable_pages(victim.id)
                              if victim is not None else 0)
            if avail < need:
                if not skipped_before:
                    if req.admit_bypasses >= self.HOL_BYPASS_LIMIT:
                        break       # starvation bound reached: stop
                                    # bypassing, let pages accumulate
                    blocked_head = req
                skipped_before = True
                continue        # oversized for THIS sweep's budget:
                                # skip, keep scanning for a fit
            if not self._try_debit_quota(req):
                skipped_before = True
                continue        # over quota: deferred, not dropped
            if victim is not None:
                budget += self._charge_and_preempt(req, victim)
            if sched.admit_request(req) is None:
                skipped_before = True
                continue
            req.quota_deferred = False
            budget -= need
            n_admitted += 1
            if blocked_head is not None:
                n_bypassed += 1
            self._trace(req,
                        'resume' if req.preemptions else 'admit',
                        t=(req.admit_time
                           if not req.preemptions else None),
                        slot=sched.slot_of(req),
                        waiting=len(sched.waiting))
        if blocked_head is not None:
            blocked_head.admit_bypasses += n_bypassed
        return n_admitted

    def _try_debit_quota(self, req):
        """Debit req's token bill (prompt + generation budget) from
        its tenant's bucket at first admit. True = admit may proceed
        (no tenancy / no quota / already charged / debit succeeded);
        False = defer this sweep. The defer EDGE (not every deferred
        sweep) counts in the quota_deferrals gauges and emits one
        quota_defer trace event carrying the bucket's own retry
        estimate."""
        if self._tenants is None or req.quota_charged:
            return True
        bucket = self._tenants.bucket(req.tenant_id)
        if bucket is None:
            return True
        bill = len(req.prompt) + req.max_new_tokens
        if bucket.try_debit(bill):
            req.quota_charged = True
            if req.tenant_id is not None:
                self._tstat(req.tenant_id)['tokens_billed'] += bill
            return True
        if not req.quota_deferred:
            req.quota_deferred = True
            req.quota_defers += 1
            self._quota_deferrals += 1
            self._tstat(req.tenant_id)['quota_deferrals'] += 1
            self._trace(req, 'quota_defer', tenant_id=req.tenant_id,
                        bill_tokens=bill,
                        retry_after_s=bucket.seconds_until(bill))
        return False

    def adopt_request(self, req):
        """Adopt a request prefilled ELSEWHERE (prefill→decode
        disaggregation, serving/cluster/disagg.py): its KV pages were
        already allocated in this engine's pool under req.id and their
        contents streamed in, its first token is already in
        req.generated — it goes straight to a RUNNING decode slot.
        Returns False when no slot is free (caller keeps it pending).
        The streamed pages join this pool's prefix index so decode-side
        siblings share them like locally-prefilled ones."""
        if self.scheduler.adopt(req) is None:
            return False
        req.prefilled = len(req.tokens)
        self._submitted += 1
        # everything but the newest token has K/V resident (the next
        # decode step writes that one) — same invariant _decode_step
        # maintains
        self.pool.register_prefix(req.id, req.tokens,
                                  req.context_len - 1,
                                  owner=req.tenant_id)
        self._trace(req, 'admit', slot=self.scheduler.slot_of(req),
                    handoff=True,
                    pages=len(self.pool.page_table(req.id)))
        if req.done:
            self._retire(req)
        return True

    def _ensure_or_preempt(self, req, n_tokens):
        """Grow req's pages, preempting other in-flight requests until
        the allocation fits. Refcount-aware: a victim's release only
        reclaims pages no live sibling still maps — a victim whose
        pages are all shared frees nothing, so the loop keeps
        preempting (older victims) rather than spinning on one, and a
        sharer's prefix is never yanked out from under it.

        Victim choice (ISSUE 15): with tenants configured the victim
        is the youngest request of the lowest priority class STRICTLY
        below req's — falling back to req's own class (youngest peer,
        the untenanted rule restricted to <= req.priority) only when
        nobody below holds a slot, so the engine never deadlocks on a
        same-priority pool squeeze but also never preempts upward.
        When every OTHER slot-holder outranks req, req YIELDS instead
        (its own pages release and it re-queues at the front of its
        class, returning False) — the untenanted engine would have
        preempted upward here; raising would crash the serve loop on
        a recoverable pressure condition. Every tenancy-mode
        preemption is CHARGED to the preemptor's quota bucket (the
        victim's prefilled tokens — the work the preemption destroys
        and the pool must recompute), so a high-priority tenant can't
        churn the pool for free. Returns True when capacity was
        ensured, False when req itself was preempted (the caller must
        not touch its pages this sweep)."""
        sched = self.scheduler
        while True:
            try:
                self.pool.ensure_capacity(req.id, n_tokens)
                return True
            except PoolExhausted:
                if self._tenants is not None:
                    victim = sched.preempt_victim(
                        exclude=req, below_priority=req.priority)
                    if victim is None:
                        victim = sched.preempt_victim(
                            exclude=req,
                            below_priority=req.priority + 1)
                else:
                    victim = sched.preempt_victim(exclude=req)
                if victim is None:
                    if (self._tenants is not None
                            and req in sched.slots
                            and any(r is not None and r is not req
                                    for r in sched.slots)):
                        released = self.pool.release(req.id)
                        sched.preempt(req)
                        self._trace(
                            req, 'preempt', pages_released=released,
                            tokens_generated=len(req.generated),
                            reason='yield_to_higher_priority')
                        return False
                    raise PoolExhausted(
                        f"KV pool ({self.pool.num_pages} pages x "
                        f"{self.pool.page_size}) cannot hold one request "
                        f"of {n_tokens} tokens — raise num_pages")
                self._charge_and_preempt(req, victim)

    def _charge_and_preempt(self, req, victim):
        """Preempt `victim` on behalf of `req`: charge the victim's
        destroyed prefill work to req's tenant bucket (tenancy mode),
        release the victim's pages and re-queue it at the front of its
        class. Returns the pages released (the admission sweep's
        budget gain). One body for both preemption sites — pool
        exhaustion (_ensure_or_preempt) and slot pressure (_admit) —
        so the charging rule can't drift between them."""
        charge = 0
        if self._tenants is not None:
            charge = max(victim.prefilled, 1)
            bucket = self._tenants.bucket(req.tenant_id)
            if bucket is not None:
                bucket.charge(charge)
            self._preemptions_charged += 1
            if req.tenant_id is not None:
                st = self._tstat(req.tenant_id)
                st['preemptions_charged'] += 1
                st['charge_tokens'] += charge
        released = self.pool.release(victim.id)
        self.scheduler.preempt(victim)
        self._trace(victim, 'preempt', pages_released=released,
                    for_req=req.id,
                    tokens_generated=len(victim.generated),
                    **({'charged_to': req.tenant_id,
                        'charge_tokens': charge}
                       if self._tenants is not None else {}))
        return released

    # -- jitted steps --------------------------------------------------------
    def _step_fn(self, B, T, sample, verify=False):
        """sample=False compiles a greedy-argmax step — the common
        serving mode must not pay _device_sample's full-vocab sort on
        every decode dispatch (top_ks is traced, XLA can't elide it).
        verify=True compiles the speculative-decode step shape
        [max_batch, spec_k+1]: greedy argmax at EVERY query position
        (the per-draft verdicts) instead of just the last."""
        fn = self._step_fns.get((B, T, sample, verify))
        if fn is None:
            fn = self._build_step(B, T, sample, verify)
            self._step_fns[(B, T, sample, verify)] = fn
        return fn

    def _build_step(self, B, T, sample, verify=False):
        jax, jnp = self._jax, self._jnp
        import contextlib
        model = self.model
        from ..core.tensor import Tensor
        from ..core.autograd import no_grad
        from ..jit import bind_arrays
        max_pos = model.config.max_seq_len - 1

        qdtypes = dict(self._qparam_dtypes)
        mp = self._mp

        def _spmd():
            # mp_layers key their collectives off the spmd region —
            # without it a >1-degree model would silently run the
            # degenerate single-rank math on sharded weights
            if mp > 1:
                from ..distributed import collective as C
                return C.spmd_region(('mp',))
            return contextlib.nullcontext()

        def _full_logits(lg):
            """Vocab-parallel logits -> full vocab: the tied LM head is
            the VocabParallelEmbedding weight, so under mp each shard
            computes [., V/mp] logits for its vocab rows; argmax /
            sampling need the whole vocab, so gather over 'mp' (shard
            i's rows are vocab block i — concat order is the identity)."""
            if mp <= 1:
                return lg
            g = jax.lax.all_gather(lg, 'mp')        # [mp, ..., V/mp]
            g = jnp.moveaxis(g, 0, -2)              # [..., mp, V/mp]
            return g.reshape(lg.shape[:-1] + (lg.shape[-1] * mp,))

        def step(params, kv, tokens, page_tables, seq_lens, q_lens, key,
                 ords, temps, top_ks):
            # int8 pools carry (k, v, k_scales, v_scales) per layer;
            # dense pools (k, v) — forward_paged keys off the arity
            cts = [tuple(Tensor(a) for a in c) for c in kv]
            # fused dequant of weight-only-quantized params:
            # q * (scale / 127) per out-channel, cast to storage dtype
            arrs = {}
            for n, v in params.items():
                if isinstance(v, dict):
                    s = v['s'] * (1.0 / 127.0)
                    shape = [1] * (v['q'].ndim - 1) + [-1]
                    arrs[n] = (v['q'].astype(jnp.float32)
                               * s.reshape(shape)).astype(qdtypes[n])
                else:
                    arrs[n] = v
            with bind_arrays(model, arrs), _spmd():
                pos = (seq_lens[:, None] - q_lens[:, None]
                       + jnp.arange(T, dtype=jnp.int32)[None, :])
                pos = jnp.clip(pos, 0, max_pos)
                h, new_kv = model.gpt.forward_paged(
                    Tensor(tokens), Tensor(pos), cts, page_tables,
                    seq_lens, q_lens)
                w = model.gpt.embeddings.word_embeddings.weight
                if verify:
                    # multi-query verify: greedy next-token at every
                    # draft position in one dispatch; padding positions
                    # (t >= q_len) produce garbage the host ignores.
                    # Rows that sample ride along via an extra column
                    # so the step still costs ONE host fetch.
                    logits_all = _full_logits(jnp.einsum(
                        'bth,vh->btv', h.data, w.data,
                        preferred_element_type=jnp.float32))
                    nxt = jnp.argmax(logits_all, axis=-1) \
                        .astype(jnp.int32)                  # [B, T]
                    if sample:
                        idx = jnp.clip(q_lens - 1, 0,
                                       T - 1).astype(jnp.int32)
                        last = jnp.take_along_axis(
                            logits_all, idx[:, None, None],
                            axis=1)[:, 0, :]
                        samp = _device_sample(
                            last.astype(jnp.float32), key, ords,
                            seq_lens, temps, top_ks)
                        nxt = jnp.concatenate([nxt, samp[:, None]], 1)
                    return nxt, [tuple(t.data for t in c)
                                 for c in new_kv]
                idx = jnp.clip(q_lens - 1, 0, T - 1).astype(jnp.int32)
                h_last = jnp.take_along_axis(
                    h.data, idx[:, None, None], axis=1)[:, 0, :]
                logits = _full_logits(jnp.einsum(
                    'bh,vh->bv', h_last, w.data,
                    preferred_element_type=jnp.float32))
                if sample:
                    nxt = _device_sample(logits.astype(jnp.float32),
                                         key, ords, seq_lens, temps,
                                         top_ks)
                else:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return nxt, [tuple(t.data for t in c) for c in new_kv]

        # donation updates the pool pages in place; CPU jax has no
        # donation support and would warn every call
        donate = (1,) if jax.default_backend() != 'cpu' else ()
        if mp > 1:
            # one jit(shard_map(step)) over the replica-local mesh —
            # the hybrid train step's layout applied to serving: params
            # at their split axes, KV pages on the heads axis, all the
            # tiny host-built operands (tokens/tables/lens/key)
            # replicated; the sampled tokens come back replicated
            # (every shard gathers the full vocab)
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P
            kv_specs = [tuple(P(None, None, 'mp') for _ in layer)
                        for layer in self.pool.kv]
            in_specs = (dict(self._param_specs), kv_specs,
                        P(), P(), P(), P(), P(), P(), P(), P())
            out_specs = (P(), kv_specs)
            step = shard_map(step, mesh=self.mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=False)
        jitted = jax.jit(step, donate_argnums=donate)

        def run(*args):
            was = model.training
            model.eval()
            try:
                with no_grad():
                    return jitted(*args)
            finally:
                if was:
                    model.train()
        return run

    def _fused_fn(self, B, K, sample):
        key = ('fused', B, K, sample)
        fn = self._step_fns.get(key)
        if fn is None:
            fn = self._build_fused_step(B, K, sample)
            self._step_fns[key] = fn
        return fn

    def _build_fused_step(self, B, K, sample):
        """Fourth compiled shape (ISSUE 19): K decode iterations under
        ONE jit via lax.scan. The carry is (kv pool, last token,
        seq_len, done-mask, emitted count) per row; each scan body is
        exactly the [B, 1] decode step — same forward_paged, same
        positions, same on-device sampling with the key folded per
        (ordinal, absolute position) — so the K stacked outputs are
        token-identical to K serial dispatches. Rows that hit eos or
        their budget mid-window flip `done` and ride the remaining
        iterations with q_len=0 (the idle-slot mechanism: KV writes
        dropped by the scatter, outputs ignored by the host)."""
        jax, jnp = self._jax, self._jnp
        import contextlib
        model = self.model
        from ..core.tensor import Tensor
        from ..core.autograd import no_grad
        from ..jit import bind_arrays
        max_pos = model.config.max_seq_len - 1
        qdtypes = dict(self._qparam_dtypes)
        mp = self._mp

        def _spmd():
            if mp > 1:
                from ..distributed import collective as C
                return C.spmd_region(('mp',))
            return contextlib.nullcontext()

        def _full_logits(lg):
            if mp <= 1:
                return lg
            g = jax.lax.all_gather(lg, 'mp')
            g = jnp.moveaxis(g, 0, -2)
            return g.reshape(lg.shape[:-1] + (lg.shape[-1] * mp,))

        def step(params, kv, tokens, page_tables, seq_lens, ords,
                 rems, eos_ids, live, key, temps, top_ks):
            arrs = {}
            for n, v in params.items():
                if isinstance(v, dict):
                    s = v['s'] * (1.0 / 127.0)
                    shape = [1] * (v['q'].ndim - 1) + [-1]
                    arrs[n] = (v['q'].astype(jnp.float32)
                               * s.reshape(shape)).astype(qdtypes[n])
                else:
                    arrs[n] = v
            with bind_arrays(model, arrs), _spmd():
                w = model.gpt.embeddings.word_embeddings.weight

                def body(carry, _):
                    kv_c, tok, seq, done, emitted = carry
                    alive = ~done
                    q = jnp.where(alive, 1, 0).astype(jnp.int32)
                    cts = [tuple(Tensor(a) for a in c) for c in kv_c]
                    pos = jnp.clip(seq - q, 0, max_pos)[:, None]
                    h, new_kv = model.gpt.forward_paged(
                        Tensor(tok[:, None]), Tensor(pos), cts,
                        page_tables, seq, q)
                    h_last = h.data[:, 0, :]
                    logits = _full_logits(jnp.einsum(
                        'bh,vh->bv', h_last, w.data,
                        preferred_element_type=jnp.float32))
                    if sample:
                        nxt = _device_sample(
                            logits.astype(jnp.float32), key, ords,
                            seq, temps, top_ks)
                    else:
                        nxt = jnp.argmax(logits, axis=-1) \
                            .astype(jnp.int32)
                    # serial-order accounting: the emitted token counts
                    # BEFORE the eos/budget check (append-then-check),
                    # so eos-in-window truncates precisely where the
                    # one-token path stops
                    emitted2 = emitted + q
                    hit_eos = (eos_ids >= 0) & (nxt == eos_ids)
                    done2 = done | hit_eos | (emitted2 >= rems)
                    tok2 = jnp.where(alive, nxt, tok)
                    seq2 = seq + q
                    new_kv = [tuple(t.data for t in c) for c in new_kv]
                    return (new_kv, tok2, seq2, done2, emitted2), nxt

                carry0 = (kv, tokens, seq_lens, ~live,
                          jnp.zeros((B,), jnp.int32))
                (kv, _t, _s, _d, _e), ys = jax.lax.scan(
                    body, carry0, xs=None, length=K)
            return jnp.moveaxis(ys, 0, 1), kv           # [B, K]

        donate = (1,) if jax.default_backend() != 'cpu' else ()
        if mp > 1:
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P
            kv_specs = [tuple(P(None, None, 'mp') for _ in layer)
                        for layer in self.pool.kv]
            in_specs = (dict(self._param_specs), kv_specs,
                        P(), P(), P(), P(), P(), P(), P(), P(), P(),
                        P())
            out_specs = (P(), kv_specs)
            step = shard_map(step, mesh=self.mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=False)
        jitted = jax.jit(step, donate_argnums=donate)

        def run(*args):
            was = model.training
            model.eval()
            try:
                with no_grad():
                    return jitted(*args)
            finally:
                if was:
                    model.train()
        return run

    def _fused_decode_window(self, K):
        """Up to K decode iterations in ONE dispatch + ONE host fetch.
        The caller holds scheduler/ladder quiescence; this method owns
        the page budget: every row's full window is reserved up front
        (pool.try_reserve — all-or-nothing per row) and the unused
        tail handed back with the spec-style trim after the fetch.
        Returns (rows, tokens emitted), or None when a reservation
        fails and the caller should fall back to the [B, 1] step."""
        jnp = self._jnp
        sched = self.scheduler
        B = self.config.max_batch_size
        rows = []
        for i, req in enumerate(sched.slots):
            if req is None or req.state != RequestState.RUNNING:
                continue
            w = min(K, req.max_new_tokens - len(req.generated))
            if not self.pool.try_reserve(req.id, req.context_len + w):
                # roll the earlier rows' fresh reservations back so the
                # serial fallback sees the pool it would have seen
                for _i, r, _w in rows:
                    self.pool.trim(r.id, r.context_len)
                return None
            rows.append((i, req, w))
        if not rows:
            return 0, 0
        with RecordEvent('serve::prepare', event_type='serve'):
            tokens = np.zeros((B,), np.int32)
            page_tables = np.zeros((B, self.max_pages_per_seq), np.int32)
            seq_lens = np.ones((B,), np.int32)
            ords = np.zeros((B,), np.int32)
            rems = np.zeros((B,), np.int32)
            eos_ids = np.full((B,), -1, np.int32)
            live = np.zeros((B,), bool)
            temps = np.zeros((B,), np.float32)
            top_ks = np.zeros((B,), np.int32)
            for i, req, w in rows:
                tokens[i] = (req.generated[-1] if req.generated
                             else req.prompt[-1])
                page_tables[i, :] = self._page_row(req)
                seq_lens[i] = req.context_len
                ords[i] = _ord_of(req)
                rems[i] = w
                if req.eos_token_id is not None:
                    eos_ids[i] = req.eos_token_id
                live[i] = True
                temps[i] = req.temperature
                top_ks[i] = req.top_k
                # decode roofline: iteration j of this row reads
                # context_len + j KV tokens
                self._it_kv_read_tokens += \
                    w * req.context_len + w * (w - 1) // 2
        sample = any(r.top_k > 0 for _, r, _ in rows)
        fn = self._fused_fn(B, K, sample)
        t0 = time.perf_counter()
        with RecordEvent('serve::compiled_step', event_type='serve',
                         shape='fused', batch=len(rows), k=K):
            nxt, new_kv = fn(
                self._params, self.pool.kv,
                jnp.asarray(tokens), jnp.asarray(page_tables),
                jnp.asarray(seq_lens), jnp.asarray(ords),
                jnp.asarray(rems), jnp.asarray(eos_ids),
                jnp.asarray(live), self._key,
                jnp.asarray(temps), jnp.asarray(top_ks))
        self.pool.kv = new_kv
        t1 = time.perf_counter()
        with RecordEvent('serve::sample_fetch', event_type='serve'):
            nxt = _host_fetch(nxt)      # ONE fetch for the whole window
        t2 = time.perf_counter()
        self._it_compute += t1 - t0
        self._it_decode_s += t1 - t0
        self._it_fetch += t2 - t1
        self._decode_time += t2 - t0
        # host accept replays the serial append-then-check loop per
        # row, so eos / max_new cuts truncate exactly where K serial
        # iterations would have stopped (the device done-mask already
        # idled the row past that point)
        emitted_total = 0
        per_iter_rows = [0] * K
        accepted = {}
        for i, req, w in rows:
            a = 0
            for j in range(K):
                if req.done:
                    break
                req.generated.append(int(nxt[i, j]))
                emitted_total += 1
                per_iter_rows[j] += 1
                a += 1
            accepted[i] = a
        iters_run = max(accepted.values())
        util = self.pool.utilization()
        for j in range(iters_run):
            self._occupancy_sum += per_iter_rows[j] / B
            self._util_sum += util
        self._decode_steps += iters_run
        self._decode_tokens += emitted_total
        self._fused_windows += 1
        self._fused_iterations += iters_run
        self._fused_tokens += emitted_total
        self.ledger.account_fused_window(K, iters_run, emitted_total)
        for i, req, w in rows:
            a = accepted[i]
            # every emitted token reached its request: delivered work,
            # nothing rejected (no draft columns in a fused window) —
            # the ledger's delivered+wasted == emitted identity holds
            # exactly as K serial account_decode(1, 0) calls would
            self.ledger.account_decode(a, 0, tenant_id=req.tenant_id)
            prev_high = getattr(req, '_computed_high', 0)
            req._computed_high = max(prev_high, req.context_len - 1)
            # hand back the reserved-but-unused window tail (early eos
            # or budget cut) — the speculative-decode trim discipline
            self.pool.trim(req.id, req.context_len)
            self.pool.register_prefix(req.id, req.tokens,
                                      req.context_len - 1,
                                      owner=req.tenant_id)
            self._trace(req, 'fused_decode', k=K, accepted=a,
                        tokens_generated=len(req.generated),
                        seq_len=req.context_len,
                        pages=len(self.pool.page_table(req.id)))
            if req.done:
                self._retire(req)
        self._fused_last = {'k': K, 'iters': iters_run,
                            'rows': per_iter_rows[:iters_run]}
        return len(rows), emitted_total

    def _page_row(self, req):
        row = self.pool.page_table(req.id)
        return row + [0] * (self.max_pages_per_seq - len(row))

    def _prefill_chunk_step(self, req):
        jnp = self._jnp
        C = self._effective_prefill_chunk()
        if req.state != RequestState.PREFILL:
            return 0        # preempted by an earlier request in this
                            # same step() sweep: it re-queued slotless,
                            # allocating pages to it now would bleed the
                            # pool (and preempt live work) for a request
                            # that isn't scheduled
        toks = req.tokens
        if req.prefilled == 0 and self.pool.prefix_cache:
            # first chunk of a fresh admit (or a resume): map the
            # longest indexed prefix — full pages only, capped one
            # short of the context so the step still computes the
            # logits the first sampled token needs
            cached = self.pool.match_and_map(req.id, toks,
                                             limit=len(toks) - 1)
            if cached:
                req.prefilled = cached
                self._trace(req, 'prefix_hit', cached_tokens=cached,
                            pages=len(self.pool.page_table(req.id)))
                # host-tier resurrection rode the hit (ISSUE 20): the
                # pages came back by prefetch, not re-prefill — the
                # trace event is what reconstruct() prices as
                # resurrected (transfer-cost) tokens
                rz = (self.pool.pop_resurrect_stats()
                      if self._host_tier is not None else None)
                if rz:
                    self._trace(req, 'resurrect', pages=rz['pages'],
                                tokens=rz['tokens'])
        start = req.prefilled
        n = min(C, len(toks) - start)
        if not self._ensure_or_preempt(req, start + n):
            return 0        # yielded to higher-priority pool pressure:
                            # re-queued, resumes when pressure clears
        chunk = toks[start:start + n] + [0] * (C - n)
        fn = self._step_fn(1, C, req.top_k > 0)
        tc0 = time.perf_counter()
        with RecordEvent('serve::compiled_step', event_type='serve',
                         shape='prefill'):
            nxt, new_kv = fn(
                self._params, self.pool.kv,
                jnp.asarray([chunk], jnp.int32),
                jnp.asarray([self._page_row(req)], jnp.int32),
                jnp.asarray([start + n], jnp.int32),
                jnp.asarray([n], jnp.int32),
                self._key,
                jnp.asarray([_ord_of(req)], jnp.int32),
                jnp.asarray([req.temperature], jnp.float32),
                jnp.asarray([req.top_k], jnp.int32))
        tc1 = time.perf_counter()
        self._it_compute += tc1 - tc0
        self._it_prefill_s += tc1 - tc0
        self._it_prefill_tokens += n
        self._it_prefill_ctx += n * (start + n)
        self.pool.kv = new_kv
        req.prefilled = start + n
        self._prefill_tokens += n
        self._prefill_chunks += 1
        # goodput: positions below the request's computed high-water
        # mark were forward-passed before (then destroyed by a
        # preemption release) — this chunk re-derives them, priced as
        # preempt_recompute waste. Prefix-cache resurrection advanced
        # `start` past the cached span, so resurrected pages never
        # bill. First-time positions are delivered prompt work.
        prev_high = getattr(req, '_computed_high', 0)
        recompute = max(0, min(prev_high, start + n) - start)
        req._computed_high = max(prev_high, start + n)
        self.ledger.account_prefill(n - recompute, recompute,
                                    tenant_id=req.tenant_id)
        # every prefilled token's K/V is resident: index the newly
        # completed full pages so siblings (and our own resume) share
        self.pool.register_prefix(req.id, toks, req.prefilled,
                                  owner=req.tenant_id)
        extra = {'recompute_tokens': recompute} if recompute else {}
        if req.prefilled == len(toks) and req.max_new_tokens > 0:
            # this chunk completes (re-)prefill and samples a token off
            # its final column below — marked so reconstruct() can tell
            # prefill-sampled tokens (initial AND every resume) from
            # decode-step tokens when pricing delivered work (v4)
            extra['sampled'] = 1
        self._trace(req, 'prefill_chunk', tokens=n, prefilled=start + n,
                    pages=len(self.pool.page_table(req.id)), **extra)
        if req.prefilled == len(toks):
            if req.max_new_tokens <= 0:
                self._retire(req)   # prefill-only request (scoring):
                return n            # the budget says emit nothing
            tf0 = time.perf_counter()
            with RecordEvent('serve::sample_fetch', event_type='serve'):
                tok = int(_host_fetch(nxt)[0])  # the sampled-token fetch
            self._it_fetch += time.perf_counter() - tf0
            req.generated.append(tok)
            if req.first_token_time is None:
                req.first_token_time = self._clock()
                ttft = req.first_token_time - req.submit_time
                self._ttfts_s.append(ttft)
                self._new_ttfts_s.append(ttft)
                self._trace(req, 'first_token',
                            t=req.first_token_time, tokens_generated=1,
                            pages=len(self.pool.page_table(req.id)))
            if req.done:
                self._retire(req)
            else:
                req.state = RequestState.RUNNING
        return n

    def _decode_step(self):
        """One batched decode dispatch. With spec_k=0 every running
        request advances exactly one token ([B, 1] step). With spec_k
        > 0, greedy requests whose history yields an n-gram proposal
        carry up to k draft tokens into the [B, spec_k+1] verify step:
        every draft position's greedy argmax comes back in the one
        fetch, the longest agreeing draft prefix is accepted plus the
        bonus token, and pages grown for rejected drafts are handed
        back (their slots are overwritten in place by later writes —
        the ragged kernel's seq_len mask never exposes a stale slot
        before the step that rewrites it). Returns (rows, tokens
        emitted)."""
        jnp = self._jnp
        sched = self.scheduler
        K = self._effective_spec_k()
        if self.config.spec_k > 0 and K == 0:
            # degrade stage >= 1 shed the configured draft capacity this
            # step: price the foregone draft columns (min(spec_k,
            # remaining budget) per greedy running row) as shed
            # capacity — never computed, so outside the emitted-token
            # identity
            for req in sched.slots:
                if req is None or req.state != RequestState.RUNNING \
                        or req.top_k > 0:
                    continue
                budget = req.max_new_tokens - len(req.generated) - 1
                if budget > 0:
                    self.ledger.account_spec_shed(
                        min(self.config.spec_k, budget),
                        tenant_id=req.tenant_id)
        proposals = {}
        if K > 0:
            for req in sched.slots:
                if req is None or req.state != RequestState.RUNNING \
                        or req.top_k > 0:
                    continue        # spec verify is greedy-only
                budget = req.max_new_tokens - len(req.generated) - 1
                drafts = _ngram_propose(req.tokens,
                                        self.config.spec_ngram,
                                        min(K, budget))
                if drafts:
                    proposals[req.id] = drafts
        # fused window (ISSUE 19): when no verify columns ride this
        # dispatch (spec takes precedence — its drafts already amortize
        # the host fetch) and the scheduler is quiescent for a full
        # window, scan k decode iterations on device and fetch once.
        # A failed page reservation falls through to the serial step
        # below rather than preempting — the window is an optimization,
        # never a capacity decision.
        FK = self._effective_fused_k()
        if FK > 1 and not proposals and self._fused_ok(FK):
            res = self._fused_decode_window(FK)
            if res is not None:
                return res
        # capacity first (may preempt, or yield the request itself);
        # then snapshot the running set — a yielded request left its
        # slot, so the batch build below skips it naturally
        for req in list(sched.slots):
            if req is not None and req.state == RequestState.RUNNING:
                if not self._ensure_or_preempt(
                        req, req.context_len
                        + len(proposals.get(req.id, ()))):
                    proposals.pop(req.id, None)
        B = self.config.max_batch_size
        verify = any(
            req is not None and req.state == RequestState.RUNNING
            and req.id in proposals for req in sched.slots)
        T = K + 1 if verify else 1
        with RecordEvent('serve::prepare', event_type='serve'):
            tokens = np.zeros((B, T), np.int32)
            page_tables = np.zeros((B, self.max_pages_per_seq), np.int32)
            seq_lens = np.ones((B,), np.int32)
            q_lens = np.zeros((B,), np.int32)
            ords = np.zeros((B,), np.int32)
            temps = np.zeros((B,), np.float32)
            top_ks = np.zeros((B,), np.int32)
            active = []
            for i, req in enumerate(sched.slots):
                if req is None or req.state != RequestState.RUNNING:
                    continue
                drafts = proposals.get(req.id, ()) if verify else ()
                active.append((i, req, list(drafts)))
                # decode roofline: KV tokens this row's attention reads
                self._it_kv_read_tokens += req.context_len + len(drafts)
                tokens[i, 0] = (req.generated[-1] if req.generated
                                else req.prompt[-1])
                if drafts:
                    tokens[i, 1:1 + len(drafts)] = drafts
                row = self._page_row(req)
                page_tables[i, :] = row
                seq_lens[i] = req.context_len + len(drafts)
                q_lens[i] = 1 + len(drafts)
                ords[i] = _ord_of(req)
                temps[i] = req.temperature
                top_ks[i] = req.top_k
        if not active:
            return 0, 0
        sample = any(r.top_k > 0 for _, r, _ in active)
        fn = self._step_fn(B, T, sample, verify=verify)
        t0 = time.perf_counter()
        with RecordEvent('serve::compiled_step', event_type='serve',
                         shape='verify' if verify else 'decode',
                         batch=len(active)):
            nxt, new_kv = fn(
                self._params, self.pool.kv,
                jnp.asarray(tokens), jnp.asarray(page_tables),
                jnp.asarray(seq_lens), jnp.asarray(q_lens), self._key,
                jnp.asarray(ords),
                jnp.asarray(temps), jnp.asarray(top_ks))
        self.pool.kv = new_kv
        t1 = time.perf_counter()
        with RecordEvent('serve::sample_fetch', event_type='serve'):
            nxt = _host_fetch(nxt)              # the sampled-token fetch
        t2 = time.perf_counter()
        dt = t2 - t0
        self._it_compute += t1 - t0
        self._it_decode_s += t1 - t0
        self._it_fetch += t2 - t1
        self._decode_time += dt
        self._decode_steps += 1
        self._occupancy_sum += len(active) / B
        self._util_sum += self.pool.utilization()
        emitted_total = 0
        for i, req, drafts in active:
            spec_m = None
            if verify:
                if req.top_k > 0:
                    appended = [int(nxt[i, T])]     # sampled column
                else:
                    g = nxt[i]
                    m = 0
                    while m < len(drafts) and int(g[m]) == drafts[m]:
                        m += 1
                    appended = drafts[:m] + [int(g[m])]
                    if drafts:
                        self._spec_proposed += len(drafts)
                        self._spec_accepted += m
                        self._spec_steps += 1
                        spec_m = m
            else:
                appended = [int(nxt[i])]
            # emit in order, honoring eos mid-burst exactly like the
            # one-token path would have (nothing after eos escapes)
            delivered_row = 0
            for tok in appended:
                req.generated.append(tok)
                emitted_total += 1
                delivered_row += 1
                if req.done:
                    break
            # goodput: this row computed 1 + len(drafts) query columns;
            # columns that never reached the request (rejected drafts,
            # post-eos overdraft) are spec_rejected waste
            self.ledger.account_decode(
                delivered_row, 1 + len(drafts) - delivered_row,
                tenant_id=req.tenant_id)
            if spec_m is not None:
                # emitted after the append sweep so `discarded` prices
                # the accepted-but-dropped tail (eos / budget cut the
                # burst short) — trace v4 waste matches the ledger's
                # spec_rejected charge per request exactly
                self._trace(req, 'spec_verify', proposed=len(drafts),
                            accepted=spec_m,
                            discarded=len(appended) - delivered_row)
            prev_high = getattr(req, '_computed_high', 0)
            req._computed_high = max(prev_high, req.context_len - 1)
            if drafts:
                # speculative rollback: hand back pages grown for
                # rejected drafts beyond the accepted context
                self.pool.trim(req.id, req.context_len)
            # K/V is resident for everything but the newest token
            self.pool.register_prefix(req.id, req.tokens,
                                      req.context_len - 1,
                                      owner=req.tenant_id)
            self._trace(req, 'decode',
                        tokens_generated=len(req.generated),
                        seq_len=req.context_len,
                        pages=len(self.pool.page_table(req.id)))
            if req.done:
                self._retire(req)
        self._decode_tokens += emitted_total
        return len(active), emitted_total

    def _retire(self, req):
        self.pool.release(req.id)
        self.scheduler.retire(req)
        self._completed += 1
        if req.tenant_id is not None:
            self._tstat(req.tenant_id)['completed'] += 1
        self._observe_slo(req)
        self._trace(req, 'retire', t=req.finish_time,
                    tokens_generated=len(req.generated),
                    preemptions=req.preemptions)

    def abort(self, req, reason='aborted'):
        """Drop a request wherever it sits: pages released, slot/queue
        entry cleared, journal closed with an `abort` event. The
        watchdog's deadline_action='abort' path and operator kill.
        No-op (returns False) on an already-retired/aborted request —
        double accounting would poison the SLO histograms."""
        if not self.scheduler.abort(req):
            return False
        self.pool.release(req.id)
        self._aborted += 1
        if req.tenant_id is not None:
            self._tstat(req.tenant_id)['aborted'] += 1
        self._observe_slo(req)
        self._trace(req, 'abort', t=req.finish_time, reason=reason,
                    tokens_generated=len(req.generated),
                    preemptions=req.preemptions)
        return True

    def _observe_slo(self, req):
        """Queue the per-request SLO samples (queue-wait, TPOT, e2e,
        preemption count) for the next histogram publish — host floats
        the scheduler already stamped, no device work. Requests with a
        tenant also queue tenant-labeled queue-wait/e2e samples, and a
        finish past the request's own deadline records a deadline_miss
        (counter + trace event) — the admission estimate was wrong or
        pressure grew after admit; either way the SLO view must say
        so."""
        slo = self._new_slo
        qw = e2e = None
        if req.submit_time is not None and req.admit_time is not None:
            qw = req.admit_time - req.submit_time
            slo['queue_wait_s'].append(qw)
        if (req.first_token_time is not None
                and req.finish_time is not None
                and len(req.generated) > 1):
            slo['tpot_s'].append(
                (req.finish_time - req.first_token_time)
                / (len(req.generated) - 1))
        if req.submit_time is not None and req.finish_time is not None:
            e2e = req.finish_time - req.submit_time
            slo['e2e_s'].append(e2e)
        slo['preemptions'].append(req.preemptions)
        if req.tenant_id is not None:
            ts = self._new_tenant_slo.setdefault(
                req.tenant_id, {'queue_wait_s': [], 'e2e_s': []})
            if qw is not None:
                ts['queue_wait_s'].append(qw)
            if e2e is not None:
                ts['e2e_s'].append(e2e)
        if (req.deadline_s is not None and e2e is not None
                and e2e > req.deadline_s):
            self._deadline_misses += 1
            if req.tenant_id is not None:
                self._tstat(req.tenant_id)['deadline_misses'] += 1
            self._trace(req, 'deadline_miss', t=req.finish_time,
                        e2e_s=e2e, deadline_s=req.deadline_s)

    # -- stalled-request watchdog --------------------------------------------
    def _check_stalled(self):
        """Requests older than config.request_deadline_s produce a
        structured serve_report artifact (trace + timeline tail + pool
        census) once, instead of silently sitting in the queue."""
        deadline = self.config.request_deadline_s
        if not deadline:
            return
        now = self._clock()
        stalled = [r for r in (list(self.scheduler.waiting)
                               + [s for s in self.scheduler.slots
                                  if s is not None])
                   if r.submit_time is not None
                   and now - r.submit_time > deadline
                   and r.id not in self._stall_reported]
        for req in stalled:
            self._stall_reported.add(req.id)
            self.last_serve_report = self._build_report(
                req, age_s=now - req.submit_time)
            self.last_serve_report['path'] = write_serve_report(
                self.last_serve_report, self.config.report_dir)
            if self.config.deadline_action == 'abort':
                self.abort(req, reason='deadline_exceeded')

    def _build_report(self, req, age_s):
        events = (self.tracer.events(req.id)
                  if self.tracer is not None else [])
        return build_serve_report(
            reason=f'request exceeded deadline '
                   f'({self.config.request_deadline_s}s)',
            request_summary={
                'req': req.id, 'state': req.state, 'age_s': age_s,
                'deadline_s': self.config.request_deadline_s,
                'prompt_tokens': len(req.prompt),
                'tokens_generated': len(req.generated),
                'preemptions': req.preemptions,
            },
            trace_events=events,
            timeline_tail=self.timeline.tail(32),
            pool_stats=self.pool.stats(),
            pool_census=self.pool.census(),
            engine_stats={
                'in_flight': len(self.scheduler.running()),
                'waiting': len(self.scheduler.waiting),
                'submitted': self._submitted,
                'completed': self._completed,
                'aborted': self._aborted,
            })

    # -- stats / metrics -----------------------------------------------------
    def stats(self):
        steps = max(self._decode_steps, 1)
        s = {
            'decode_tokens_per_sec':
                (self._decode_tokens / self._decode_time
                 if self._decode_time else 0.0),
            'ttft_ms_mean':
                (1000.0 * sum(self._ttfts_s) / len(self._ttfts_s)
                 if self._ttfts_s else None),
            'batch_occupancy': self._occupancy_sum / steps,
            'kv_page_utilization': self._util_sum / steps,
            'slots': self.config.max_batch_size,
            'in_flight': len(self.scheduler.running()),
            'waiting': len(self.scheduler.waiting),
            'pool': self.pool.stats(),
            'requests_submitted_total': self._submitted,
            'requests_completed_total': self._completed,
            'requests_aborted_total': self._aborted,
            'preemptions_total': self.scheduler.preemptions,
            'decode_steps_total': self._decode_steps,
            'decode_tokens_total': self._decode_tokens,
            'prefill_tokens_total': self._prefill_tokens,
            'prefill_chunks_total': self._prefill_chunks,
            'weight_dtype': (str(self.config.weight_dtype)
                             if self.config.weight_dtype else None),
            'quantized_params': len(self._qparam_dtypes),
            # prefix cache (pool-owned counters) + speculative decode
            'prefix_cache': self.pool.prefix_cache,
            'prefix_hits_total': self.pool.prefix_hits,
            'prefix_misses_total': self.pool.prefix_misses,
            'prefix_hit_tokens_total': self.pool.prefix_hit_tokens,
            'prefix_shared_pages': self.pool.shared_pages,
            'prefix_cached_pages': self.pool.cached_pages,
            'prefix_evictions_total': self.pool.prefix_evictions,
            'spec_k': self.config.spec_k,
            'spec_proposed_tokens_total': self._spec_proposed,
            'spec_accepted_tokens_total': self._spec_accepted,
            'spec_steps_total': self._spec_steps,
            'spec_acceptance_rate':
                (self._spec_accepted / self._spec_proposed
                 if self._spec_proposed else None),
            # fused multi-token decode (ISSUE 19)
            'fused_k': self.config.fused_k,
            'fused_windows_total': self._fused_windows,
            'fused_iterations_total': self._fused_iterations,
            'fused_tokens_total': self._fused_tokens,
            # multi-tenant SLO layer (ISSUE 15): always present so the
            # snapshot shape is stable — zeros/empty when untenanted
            'quota_deferrals_total': self._quota_deferrals,
            'preemptions_charged_total': self._preemptions_charged,
            'deadline_rejects_total': self._deadline_rejects,
            'deadline_misses_total': self._deadline_misses,
            'degrade_stage': self.degrade_stage(),
            'tenancy': self._tenancy_stats(),
        }
        return s

    def _tenancy_stats(self):
        """Per-tenant lifetime view for stats()/serve_snapshot() and
        health_dump tenants: policy (priority/quota/weight), live
        bucket level, and the accounting rows."""
        out = {
            'enabled': self._tenants is not None,
            'degrade_enabled': self._ladder is not None,
            'degrade_stage': self.degrade_stage(),
            'pressure': (round(self._ladder.pressure(), 4)
                         if self._ladder is not None else 0.0),
            'stage_transitions': (self._ladder.transitions
                                  if self._ladder is not None else 0),
            'tenants': {},
        }
        tids = set(self._tenant_stats)
        if self._tenants is not None:
            tids.update(self._tenants.tenants())
        for tid in sorted(tids):
            row = dict(self._tenant_stats.get(tid)
                       or self._blank_tstat())
            if self._tenants is not None:
                pol = self._tenants.policy(tid)
                if pol is not None:
                    row['priority'] = pol['priority']
                    row['quota_tokens_per_s'] = pol['quota_tokens_per_s']
                    row['weight'] = pol['weight']
                bucket = self._tenants.bucket(tid)
                if bucket is not None:
                    row['bucket_level'] = round(bucket.level, 3)
            out['tenants'][tid] = row
        return out

    def reset_stats(self):
        """Zero the rate/occupancy accounting AND the trace/timeline
        observatory (NOT the pool or queue) — bench legs call this
        after compile warmup so steady-state numbers aren't polluted by
        the first-dispatch compiles."""
        self._decode_time = 0.0
        self._decode_tokens = 0
        self._decode_steps = 0
        self._occupancy_sum = 0.0
        self._util_sum = 0.0
        self._prefill_tokens = 0
        self._prefill_chunks = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_steps = 0
        self._fused_windows = 0
        self._fused_iterations = 0
        self._fused_tokens = 0
        self._ttfts_s = []
        self._new_ttfts_s = []
        for v in self._new_slo.values():
            v.clear()
        for d in self._new_tenant_slo.values():
            for v in d.values():
                v.clear()
        if self.tracer is not None:
            self.tracer.reset()
        self.timeline.reset()
        self.ledger.reset()
        self._gap.reset()

    def publish_metrics(self):
        s = self.stats()
        s['_new_ttfts_s'] = list(self._new_ttfts_s)
        self._new_ttfts_s.clear()
        s['_new_slo'] = {k: list(v) for k, v in self._new_slo.items()}
        for v in self._new_slo.values():
            v.clear()
        s['_new_tenant_slo'] = {t: {k: list(v) for k, v in d.items()}
                                for t, d in self._new_tenant_slo.items()}
        for d in self._new_tenant_slo.values():
            for v in d.values():
                v.clear()
        s['timeline'] = self.timeline.summary()
        self._last_publish = self._clock()
        self._last_publish_wall = _monitor._time_fn()
        _metrics.publish(s)
        self.ledger.publish()
        self._gap.publish()

    def request_table(self):
        """Per-request SLO reconstruction from the lifecycle journals
        (request_trace.reconstruct) — empty when tracing is off."""
        return self.tracer.request_table() if self.tracer else {}

    def export_trace(self, jsonl_path=None, chrome_path=None):
        """Export the request journals: JSON-lines (schema header +
        one event per line) and/or chrome-trace. The chrome export
        folds in any serve::* engine-phase spans sitting in the
        profiler's span buffer, so requests render as tracks next to
        the engine steps that served them (Perfetto-loadable)."""
        if self.tracer is None:
            raise RuntimeError("tracing is off — build the engine with "
                               "ServingConfig(trace=True)")
        out = {}
        if jsonl_path:
            out['jsonl'] = self.tracer.export_jsonl(jsonl_path)
        if chrome_path:
            from .. import profiler as _prof
            spans = [s for s in _prof._buffer.snapshot()
                     if s.get('cat') == 'serve']
            out['chrome'] = self.tracer.export_chrome_tracing(
                chrome_path, extra_spans=spans)
        return out

    def shutdown(self):
        """Drop the pool's device pages and the compiled steps, and
        unregister the gap monitor + serve ledger so a dead engine
        stops reporting (the PR-13 training-engine discipline —
        serve_ledger_snapshot() and the host-gap registry read live
        objects, not stale gauges)."""
        if self._host_tier is not None:
            self._host_tier.shutdown()
        self.pool.drop_arrays()
        self._step_fns.clear()
        self._params = {}
        unregister_monitor(self._gap)
        self.ledger.unregister()
        return {'released': True}


def _ngram_propose(tokens, ngram, k):
    """Prompt-lookup draft proposer (the model-free speculator): find
    the most recent earlier occurrence of the context's trailing
    n-gram and propose the up-to-k tokens that followed it. Backs off
    to shorter n-grams; returns [] when nothing matches — the request
    then just decodes one token this step. Pure host work on the token
    list the scheduler already holds."""
    L = len(tokens)
    if k <= 0 or L < 2:
        return []
    for n in range(min(int(ngram), L - 1), 0, -1):
        # rightmost candidate ends one short of the trailing gram, so
        # the continuation (which may overlap the suffix — that is how
        # repetition loops propose) is never empty. Compared in place:
        # this runs per greedy row per decode step, so no per-position
        # slice allocations on the miss path.
        first = tokens[L - n]
        for j in range(L - n - 1, -1, -1):
            if tokens[j] != first:
                continue
            if all(tokens[j + t] == tokens[L - n + t]
                   for t in range(1, n)):
                return [int(t) for t in tokens[j + n:j + n + k]]
    return []


def _ord_of(req):
    """The request's sampling ordinal for the per-position key fold.
    engine.submit assigns engine-local ordinals in submission order
    (and adopted requests carry their submitter's); requests injected
    past submit — scheduler-level tests driving engine internals —
    fall back to the global request id, still a stable per-request
    fold."""
    o = getattr(req, 'sample_ord', None)
    return int(o if o is not None else req.id)


def _device_sample(logits, key, ords, positions, temps, top_ks):
    """On-device next-token choice, [B, V] fp32 logits -> [B] int32.

    Matches GPTForCausalLM._sample_next semantics: top_k <= 0 means
    GREEDY argmax (temperature ignored); top_k > 0 samples from the
    temperature-scaled top-k renormalized distribution.

    The per-row key is fold_in(fold_in(key, ords[b]), positions[b]) —
    a pure function of (seed, request ordinal, absolute token
    position), never of dispatch count or batch composition. That
    invariance is what makes fused-k windows, serial decode, the spec
    verify column and preempt/resume re-prefill all sample IDENTICAL
    tokens (ISSUE 19); `positions` is the absolute index of the token
    being sampled (== seq_lens in every step shape)."""
    import jax
    import jax.numpy as jnp
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps[:, None], 1e-6)
    k = jnp.clip(top_ks, 1, V)
    srt = jnp.sort(scaled, axis=-1)             # ascending
    kth = jnp.take_along_axis(srt, (V - k)[:, None], axis=-1)
    masked = jnp.where(scaled < kth, -1e30, scaled)
    keys = jax.vmap(
        lambda o, p: jax.random.fold_in(jax.random.fold_in(key, o), p)
    )(ords, positions)
    sampled = jax.vmap(jax.random.categorical)(keys, masked).astype(
        jnp.int32)
    return jnp.where(top_ks > 0, sampled, greedy)
