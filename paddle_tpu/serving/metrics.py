"""ptpu_serve_* metrics — the serving engine's observability surface.

Published through core.monitor (same registry the training telemetry
uses), read back by `serve_snapshot()` for
`profiler.StepTelemetry.snapshot()['serve']`, bench records, and
`tools/health_dump.py serve`. Gauge table in docs/serving.md.

The SLO layer (ISSUE 6): per-request queue-wait / TTFT / TPOT / e2e /
preemption-count histograms with bucket-interpolated p50/p90/p99
(core.monitor.Histogram.percentiles) in the snapshot, plus the
scheduler-timeline summary — the occupancy-feedback signal the future
disaggregated router consumes.

The multi-tenant layer (ISSUE 15): tenant-labeled
ptpu_serve_tenant_{queue_wait,e2e}_seconds histograms (one series per
tenant), the quota/preemption/deadline counters-as-gauges, and the
degradation-ladder stage/pressure gauges — `serve_snapshot()['tenants']`
is the per-tenant SLO table `tools/health_dump.py tenants` renders.
"""
from ..core import monitor as _m

TTFT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                2.5, 5.0, 10.0, 30.0, float('inf'))
TPOT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, float('inf'))
E2E_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
               5.0, 10.0, 30.0, 60.0, 120.0, float('inf'))
PREEMPT_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, float('inf'))

# SLO histograms: monitor name -> (engine _new_slo key, buckets, help)
_SLO_HISTOGRAMS = {
    'ptpu_serve_queue_wait_seconds': (
        'queue_wait_s', TTFT_BUCKETS,
        'per-request submit -> first admit wait'),
    'ptpu_serve_tpot_seconds': (
        'tpot_s', TPOT_BUCKETS,
        'per-request mean inter-token latency (time per output token)'),
    'ptpu_serve_e2e_seconds': (
        'e2e_s', E2E_BUCKETS,
        'per-request submit -> retire latency'),
    'ptpu_serve_preemptions_per_request': (
        'preemptions', PREEMPT_BUCKETS,
        'preemptions suffered per retired request'),
}

_GAUGE_NAMES = (
    'ptpu_serve_decode_tokens_per_sec',
    'ptpu_serve_batch_occupancy',
    'ptpu_serve_kv_page_utilization',
    'ptpu_serve_kv_pages_total',
    'ptpu_serve_kv_pages_in_use',
    'ptpu_serve_kv_pages_high_water',
    'ptpu_serve_kv_pool_bytes',
    'ptpu_serve_kv_bytes_per_token',
    'ptpu_serve_batch_slots',
    'ptpu_serve_requests_in_flight',
    'ptpu_serve_requests_waiting',
    # prefix cache (ISSUE 9): lifetime hit/miss lookups, pages mapped
    # by >1 request right now, ref-0 pages parked for resurrection
    'ptpu_serve_prefix_hits',
    'ptpu_serve_prefix_misses',
    'ptpu_serve_prefix_shared_pages',
    'ptpu_serve_prefix_cached_pages',
    # multi-tenant SLO layer (ISSUE 15): lifetime quota deferral /
    # charged-preemption / deadline-reject counts (engine-owned
    # monotonic state mirrored as gauges, like the _total block) and
    # the degradation ladder's current stage + windowed pressure
    'ptpu_serve_quota_deferrals',
    'ptpu_serve_preemptions_charged',
    'ptpu_serve_deadline_rejects',
    'ptpu_serve_deadline_misses',
    'ptpu_serve_degrade_stage',
    'ptpu_serve_degrade_pressure',
    # fused multi-token decode (ISSUE 19): the configured window
    # length (1 = per-token decode)
    'ptpu_serve_fused_k',
    # host-RAM KV tier (ISSUE 20): occupancy gauges — published ONLY
    # when the engine has a host tier (pool stats carry tier_* keys),
    # so tierless configs keep exactly the PR-19 gauge set (asserted
    # in tests/test_serving_kvtier.py)
    'ptpu_serve_tier_host_pages',
    'ptpu_serve_tier_host_used_pages',
    'ptpu_serve_tier_resident_pages',
    'ptpu_serve_tier_spill_inflight_pages',
)

# host-RAM tier gauges: name -> (help, value(pool stats)). Conditional
# on the pool actually carrying tier stats — see _GAUGE_NAMES note.
_TIER_GAUGES = (
    ('ptpu_serve_tier_host_pages',
     'host-tier capacity in KV pages',
     lambda p: p.get('tier_host_pages', 0)),
    ('ptpu_serve_tier_host_used_pages',
     'host-tier slots holding spilled pages right now',
     lambda p: p.get('tier_host_used_pages', 0)),
    ('ptpu_serve_tier_resident_pages',
     'device-resident KV pages (mapped + parked) — the HBM side of '
     'the tier split',
     lambda p: (p.get('pages_in_use', 0) + p.get('cached_pages', 0))),
    ('ptpu_serve_tier_spill_inflight_pages',
     'device pages pinned by an in-flight spill (unavailable to '
     'allocation until the transfer lands)',
     lambda p: p.get('tier_spill_inflight_pages', 0)),
)

# host-RAM tier counters-as-gauges (engine-owned lifetime totals,
# mirrored like _COUNTER_NAMES; conditional like _TIER_GAUGES)
_TIER_COUNTERS = (
    ('ptpu_serve_tier_resurrected_pages_total',
     'host-resident pages resurrected by prefetch instead of '
     're-prefill (lifetime)', 'tier_resurrected_pages_total'),
    ('ptpu_serve_tier_resurrected_tokens_total',
     'prompt tokens whose KV came back from the host tier instead of '
     'recompute (lifetime)', 'tier_resurrected_tokens_total'),
)

# transfer totals: REAL monitor counters incremented by host_tier.py
# at transfer time (never re-published as gauges — the registry would
# conflict); scalar_series mirrors them from pool stats so per-replica
# cluster snapshots carry them without touching the shared registry
_TIER_TRANSFER_COUNTERS = (
    ('ptpu_serve_tier_spilled_pages_total',
     'KV pages spilled device->host tier (lifetime)',
     'tier_spilled_pages_total'),
    ('ptpu_serve_tier_spilled_bytes_total',
     'bytes spilled device->host tier (lifetime)',
     'tier_spilled_bytes_total'),
    ('ptpu_serve_tier_fetched_pages_total',
     'KV pages fetched host->device (lifetime)',
     'tier_fetched_pages_total'),
    ('ptpu_serve_tier_fetched_bytes_total',
     'bytes fetched host->device (lifetime)',
     'tier_fetched_bytes_total'),
)

# tenant-labeled SLO histograms: name -> (engine tenant-slo key,
# buckets, help). One labeled series per tenant in the one registry
# metric — serve_snapshot()['tenants'] renders per-tenant percentiles.
_TENANT_HISTOGRAMS = {
    'ptpu_serve_tenant_queue_wait_seconds': (
        'queue_wait_s', TTFT_BUCKETS,
        'per-request submit -> first admit wait, by tenant'),
    'ptpu_serve_tenant_e2e_seconds': (
        'e2e_s', E2E_BUCKETS,
        'per-request submit -> retire latency, by tenant'),
}
_COUNTER_NAMES = (
    'ptpu_serve_requests_submitted_total',
    'ptpu_serve_requests_completed_total',
    'ptpu_serve_requests_aborted_total',
    'ptpu_serve_preemptions_total',
    'ptpu_serve_decode_steps_total',
    'ptpu_serve_decode_tokens_total',
    'ptpu_serve_prefill_tokens_total',
    'ptpu_serve_prefill_chunks_total',
    'ptpu_serve_prefix_hit_tokens_total',
    'ptpu_serve_spec_proposed_tokens_total',
    'ptpu_serve_spec_accepted_tokens_total',
    # fused multi-token decode (ISSUE 19): windows dispatched (one
    # host fetch each), device iterations inside them, tokens they
    # delivered — decode_steps_total keeps counting ITERATIONS, so
    # per-token dashboards stay comparable across fused/serial
    'ptpu_serve_fused_windows_total',
    'ptpu_serve_fused_iterations_total',
    'ptpu_serve_fused_tokens_total',
)

# scalar gauges: name -> (help, value(stats, pool)). One declarative
# table so publish() (global registry) and scalar_series() (per-replica
# compact snapshots for the cluster `metrics` op) can never drift.
_SCALAR_GAUGES = (
    ('ptpu_serve_decode_tokens_per_sec',
     'batched decode throughput (generated tokens/sec)',
     lambda s, p: s.get('decode_tokens_per_sec', 0.0)),
    ('ptpu_serve_batch_occupancy',
     'mean running slots / decode slots over decode steps',
     lambda s, p: s.get('batch_occupancy', 0.0)),
    ('ptpu_serve_kv_page_utilization',
     'KV pool pages in use / total',
     lambda s, p: s.get('kv_page_utilization', 0.0)),
    ('ptpu_serve_kv_pages_total', 'KV pool size in pages',
     lambda s, p: p.get('num_pages', 0)),
    ('ptpu_serve_kv_pages_in_use', 'KV pages mapped right now',
     lambda s, p: p.get('pages_in_use', 0)),
    ('ptpu_serve_kv_pages_high_water',
     'max KV pages simultaneously mapped',
     lambda s, p: p.get('high_water', 0)),
    ('ptpu_serve_kv_pool_bytes',
     'device bytes of the paged KV pool (scale buffers '
     'included for int8 pools)',
     lambda s, p: p.get('pool_bytes', 0)),
    ('ptpu_serve_kv_bytes_per_token',
     'K+V device bytes per cached token across layers '
     '(docs/serving.md#quantized-kv capacity math)',
     lambda s, p: p.get('bytes_per_token', 0)),
    ('ptpu_serve_batch_slots', 'decode batch slots',
     lambda s, p: s.get('slots', 0)),
    ('ptpu_serve_requests_in_flight', 'requests holding a decode slot',
     lambda s, p: s.get('in_flight', 0)),
    ('ptpu_serve_requests_waiting', 'queued requests',
     lambda s, p: s.get('waiting', 0)),
    ('ptpu_serve_prefix_hits',
     'prefix-cache lookups that mapped shared pages (lifetime)',
     lambda s, p: s.get('prefix_hits_total', 0)),
    ('ptpu_serve_prefix_misses',
     'prefix-cache lookups that found nothing (lifetime)',
     lambda s, p: s.get('prefix_misses_total', 0)),
    ('ptpu_serve_prefix_shared_pages',
     'physical KV pages currently mapped by >1 request',
     lambda s, p: s.get('prefix_shared_pages', 0)),
    ('ptpu_serve_prefix_cached_pages',
     'ref-0 pages retained by the prefix index '
     '(evictable, resurrectable)',
     lambda s, p: s.get('prefix_cached_pages', 0)),
    ('ptpu_serve_quota_deferrals',
     'requests deferred by a tenant token-rate quota '
     '(defer episodes, lifetime)',
     lambda s, p: s.get('quota_deferrals_total', 0)),
    ('ptpu_serve_preemptions_charged',
     'preemptions debited against the preempting tenant\'s '
     'quota (lifetime)',
     lambda s, p: s.get('preemptions_charged_total', 0)),
    ('ptpu_serve_deadline_rejects',
     'requests rejected at submit because their deadline was '
     'already unmeetable (lifetime)',
     lambda s, p: s.get('deadline_rejects_total', 0)),
    ('ptpu_serve_deadline_misses',
     'requests finished past their own deadline (lifetime)',
     lambda s, p: s.get('deadline_misses_total', 0)),
    ('ptpu_serve_fused_k',
     'configured fused decode window length (decode iterations per '
     'dispatch; 1 = per-token decode)',
     lambda s, p: s.get('fused_k', 1)),
)


def scalar_series(stats):
    """Pure view: engine stats dict -> {gauge name: scalar value} for
    every scalar ptpu_serve_* series publish() would set. Reads the
    same keys, pops nothing — the replica `metrics` control-channel op
    uses this to build compact per-replica snapshots without touching
    the (process-global, shared between in-process replicas) registry."""
    pool = stats.get('pool') or {}
    out = {name: fn(stats, pool) for name, _h, fn in _SCALAR_GAUGES}
    for name in _COUNTER_NAMES:
        key = name[len('ptpu_serve_'):-len('_total')]
        out[name] = stats.get(key + '_total', 0)
    if 'tier_host_pages' in pool:       # host tier attached (ISSUE 20)
        for name, _h, fn in _TIER_GAUGES:
            out[name] = fn(pool)
        for name, _h, key in _TIER_COUNTERS:
            out[name] = pool.get(key, 0)
        for name, _h, key in _TIER_TRANSFER_COUNTERS:
            out[name] = pool.get(key, 0)
    out['ptpu_serve_degrade_stage'] = stats.get('degrade_stage', 0)
    tenancy = stats.get('tenancy')
    out['ptpu_serve_degrade_pressure'] = \
        (tenancy or {}).get('pressure', 0.0)
    return out


# scheduler-timeline summary from the engine's last publish — a dict,
# not registry gauges: it is a windowed aggregate that the snapshot
# passes through whole (the router-feedback signal)
_last_timeline = None
# per-tenant accounting table from the engine's last publish
# (engine._tenancy_stats()) — passed through whole like the timeline
_last_tenancy = None


def publish_degrade_stage(stage, pressure):
    """Gauge a degradation-ladder transition the moment it happens —
    every stage change must be visible even between periodic publishes
    (the 'explicit, gauged, traced event' bar of ISSUE 15)."""
    _m.gauge('ptpu_serve_degrade_stage',
             help='graceful-degradation ladder stage (0 = normal, '
                  '1 = spec shed, 2 = prefill shrink, 3 = weighted '
                  'prefix eviction)').set(int(stage))
    _m.gauge('ptpu_serve_degrade_pressure',
             help='windowed scheduler pressure signal (pool occupancy '
                  '+ waiting depth) driving the ladder').set(
        float(pressure))


def publish(stats):
    """Publish an engine stats dict (ServingEngine.stats()) as
    ptpu_serve_* gauges. Counters are published as gauges set to the
    engine's lifetime totals — the engine owns the monotonic state, the
    registry just mirrors it (monitor counters can't be set)."""
    global _last_timeline, _last_tenancy
    g = _m.gauge
    # ptpu_serve_ttft_ms (deprecated mean gauge) was REMOVED in ISSUE 7
    # after its one-release grace: use the ptpu_serve_ttft_seconds
    # histogram percentiles
    pool = stats.get('pool') or {}
    for name, help_, fn in _SCALAR_GAUGES:
        g(name, help=help_).set(fn(stats, pool))
    for name in _COUNTER_NAMES:
        key = name[len('ptpu_serve_'):-len('_total')]
        g(name, help=f'serving {key.replace("_", " ")} (lifetime)').set(
            stats.get(key + '_total', 0))
    # host-RAM tier (ISSUE 20): published only when the pool carries
    # tier stats, so tierless engines keep exactly the PR-19 gauge
    # set. Transfer totals are real counters host_tier.py owns — not
    # re-published here.
    if 'tier_host_pages' in pool:
        for name, help_, fn in _TIER_GAUGES:
            g(name, help=help_).set(fn(pool))
        for name, help_, key in _TIER_COUNTERS:
            g(name, help=help_).set(pool.get(key, 0))
    h = _m.histogram('ptpu_serve_ttft_seconds',
                     help='per-request time to first token',
                     buckets=TTFT_BUCKETS)
    for t in stats.pop('_new_ttfts_s', ()):
        h.observe(t)
    slo = stats.pop('_new_slo', None) or {}
    for name, (key, buckets, help_) in _SLO_HISTOGRAMS.items():
        vals = slo.get(key)
        if not vals:
            continue
        hh = _m.histogram(name, help=help_, buckets=buckets)
        for v in vals:
            hh.observe(v)
    # multi-tenant layer (ISSUE 15): the quota/deadline
    # counters-as-gauges rode the table above; the ladder
    # stage/pressure + one labeled series per tenant in the
    # queue-wait/e2e histograms land here
    tenancy = stats.pop('tenancy', None)
    publish_degrade_stage(
        stats.get('degrade_stage', 0),
        (tenancy or {}).get('pressure', 0.0))
    tslo = stats.pop('_new_tenant_slo', None) or {}
    for tid, samples in tslo.items():
        for name, (key, buckets, help_) in _TENANT_HISTOGRAMS.items():
            vals = samples.get(key)
            if not vals:
                continue
            hh = _m.histogram(name, help=help_, buckets=buckets,
                              labelnames=('tenant',))
            for v in vals:
                hh.observe(v, tenant=str(tid))
    if tenancy is not None:
        _last_tenancy = tenancy
    tl = stats.pop('timeline', None)
    if tl is not None:
        _last_timeline = tl
    # telemetry time axis (ISSUE 18): history sampling piggybacks on
    # the publish cadence — metadata-only, no device work, no-op
    # unless MetricsRegistry.enable_history() opted in
    _m.metrics().history_tick()


def _histogram_view(h, scale_ms=True):
    """JSON-ready histogram summary: count/sum/mean + interpolated
    p50/p90/p99 (seconds scaled to ms when scale_ms)."""
    v = h.value()
    pct = h.percentiles((50, 90, 99))
    k = 1000.0 if scale_ms else 1.0
    unit = '_ms' if scale_ms else ''
    out = {'count': v['count'], 'sum': v['sum'],
           f'mean{unit}': (v['sum'] / v['count'] * k) if v['count']
           else None}
    for name, val in pct.items():
        out[f'{name}{unit}'] = val * k if val is not None else None
    return out


def serve_snapshot():
    """JSON-ready view of every ptpu_serve_* metric (None-able: {} when
    the engine never published — StepTelemetry drops it to None).
    Histograms carry bucket-interpolated p50/p90/p99; `timeline` is the
    scheduler-timeline summary from the engine's last publish."""
    reg = _m.metrics()
    out = {}
    for name in (_GAUGE_NAMES + _COUNTER_NAMES
                 + tuple(n for n, _h, _k in _TIER_COUNTERS)
                 + tuple(n for n, _h, _k in _TIER_TRANSFER_COUNTERS)):
        m = reg.get(name)
        if m is None:
            continue
        out[name] = m.value()
    h = reg.get('ptpu_serve_ttft_seconds')
    if h is not None:
        out['ptpu_serve_ttft_seconds'] = _histogram_view(h)
    for name, (key, _b, _h) in _SLO_HISTOGRAMS.items():
        m = reg.get(name)
        if m is not None:
            out[name] = _histogram_view(
                m, scale_ms=(key != 'preemptions'))
    # derived rates (ISSUE 9): prefix hit-rate over lookups, spec
    # acceptance over proposed drafts — None until there is traffic
    if 'ptpu_serve_prefix_hits' in out:
        hits = out['ptpu_serve_prefix_hits']
        total = hits + out.get('ptpu_serve_prefix_misses', 0)
        out['prefix_hit_rate'] = hits / total if total else None
    if 'ptpu_serve_spec_proposed_tokens_total' in out:
        prop = out['ptpu_serve_spec_proposed_tokens_total']
        out['spec_acceptance_rate'] = (
            out.get('ptpu_serve_spec_accepted_tokens_total', 0) / prop
            if prop else None)
    # per-tenant view (ISSUE 15): the engine's accounting table from
    # the last publish merged with per-tenant histogram percentiles —
    # what health_dump tenants renders
    if out:
        tenants = {}
        if _last_tenancy is not None:
            out['tenancy'] = {k: v for k, v in _last_tenancy.items()
                              if k != 'tenants'}
            tenants = {tid: dict(row) for tid, row in
                       (_last_tenancy.get('tenants') or {}).items()}
        for name, (key, _b, _h) in _TENANT_HISTOGRAMS.items():
            m = reg.get(name)
            if m is None:
                continue
            label = key[:-2]            # queue_wait_s -> queue_wait
            for lkey, child in m._series().items():
                tenants.setdefault(lkey[0], {})[label] = \
                    _histogram_view(child)
        if tenants:
            out['tenants'] = tenants
    if out and _last_timeline is not None:
        out['timeline'] = dict(_last_timeline)
    # serving ledger / goodput / roofline (ISSUE 17): read the LIVE
    # ledger registry — not the gauges — so engines that unregistered
    # at shutdown stop reporting here; per-tenant goodput folds into
    # the tenants rows beside the SLO percentiles
    led = None
    try:
        from . import ledger as _serve_ledger
        led = _serve_ledger.serve_ledger_snapshot()
    except Exception:
        pass
    if led is not None:
        if led.get('ledger'):
            out['ledger'] = led['ledger']
        good = led.get('goodput')
        if good and good.get('emitted_tokens'):
            out['goodput'] = {k: v for k, v in good.items()
                              if k != 'per_tenant'}
            for tid, row in (good.get('per_tenant') or {}).items():
                dst = out.setdefault('tenants', {}).setdefault(tid, {})
                dst['delivered_tokens'] = row['delivered_tokens']
                dst['wasted_tokens'] = row['wasted_tokens']
        if led.get('roofline'):
            out['roofline'] = led['roofline']
    return out
