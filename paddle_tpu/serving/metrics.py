"""ptpu_serve_* metrics — the serving engine's observability surface.

Published through core.monitor (same registry the training telemetry
uses), read back by `serve_snapshot()` for
`profiler.StepTelemetry.snapshot()['serve']`, bench records, and
`tools/health_dump.py serve`. Gauge table in docs/serving.md.
"""
from ..core import monitor as _m

TTFT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                2.5, 5.0, 10.0, 30.0, float('inf'))

_GAUGE_NAMES = (
    'ptpu_serve_decode_tokens_per_sec',
    'ptpu_serve_ttft_ms',
    'ptpu_serve_batch_occupancy',
    'ptpu_serve_kv_page_utilization',
    'ptpu_serve_kv_pages_total',
    'ptpu_serve_kv_pages_in_use',
    'ptpu_serve_kv_pages_high_water',
    'ptpu_serve_batch_slots',
    'ptpu_serve_requests_in_flight',
    'ptpu_serve_requests_waiting',
)
_COUNTER_NAMES = (
    'ptpu_serve_requests_submitted_total',
    'ptpu_serve_requests_completed_total',
    'ptpu_serve_preemptions_total',
    'ptpu_serve_decode_steps_total',
    'ptpu_serve_decode_tokens_total',
    'ptpu_serve_prefill_tokens_total',
    'ptpu_serve_prefill_chunks_total',
)


def publish(stats):
    """Publish an engine stats dict (ServingEngine.stats()) as
    ptpu_serve_* gauges. Counters are published as gauges set to the
    engine's lifetime totals — the engine owns the monotonic state, the
    registry just mirrors it (monitor counters can't be set)."""
    g = _m.gauge
    g('ptpu_serve_decode_tokens_per_sec',
      help='batched decode throughput (generated tokens/sec)').set(
          stats.get('decode_tokens_per_sec', 0.0))
    g('ptpu_serve_ttft_ms',
      help='mean time-to-first-token over completed requests').set(
          stats.get('ttft_ms_mean') or 0.0)
    g('ptpu_serve_batch_occupancy',
      help='mean running slots / decode slots over decode steps').set(
          stats.get('batch_occupancy', 0.0))
    g('ptpu_serve_kv_page_utilization',
      help='KV pool pages in use / total').set(
          stats.get('kv_page_utilization', 0.0))
    pool = stats.get('pool') or {}
    g('ptpu_serve_kv_pages_total', help='KV pool size in pages').set(
        pool.get('num_pages', 0))
    g('ptpu_serve_kv_pages_in_use', help='KV pages mapped right now').set(
        pool.get('pages_in_use', 0))
    g('ptpu_serve_kv_pages_high_water',
      help='max KV pages simultaneously mapped').set(
          pool.get('high_water', 0))
    g('ptpu_serve_batch_slots', help='decode batch slots').set(
        stats.get('slots', 0))
    g('ptpu_serve_requests_in_flight',
      help='requests holding a decode slot').set(
          stats.get('in_flight', 0))
    g('ptpu_serve_requests_waiting', help='queued requests').set(
        stats.get('waiting', 0))
    for name in _COUNTER_NAMES:
        key = name[len('ptpu_serve_'):-len('_total')]
        g(name, help=f'serving {key.replace("_", " ")} (lifetime)').set(
            stats.get(key + '_total', 0))
    h = _m.histogram('ptpu_serve_ttft_seconds',
                     help='per-request time to first token',
                     buckets=TTFT_BUCKETS)
    for t in stats.pop('_new_ttfts_s', ()):
        h.observe(t)


def serve_snapshot():
    """JSON-ready view of every ptpu_serve_* metric (None-able: {} when
    the engine never published — StepTelemetry drops it to None)."""
    reg = _m.metrics()
    out = {}
    for name in _GAUGE_NAMES + _COUNTER_NAMES:
        m = reg.get(name)
        if m is None:
            continue
        out[name] = m.value()
    h = reg.get('ptpu_serve_ttft_seconds')
    if h is not None:
        v = h.value()
        out['ptpu_serve_ttft_seconds'] = {
            'count': v['count'],
            'sum': v['sum'],
            'mean_ms': (v['sum'] / v['count'] * 1000.0) if v['count']
            else None,
        }
    return out
