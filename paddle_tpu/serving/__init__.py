"""paddle_tpu.serving — TPU-native LLM serving engine.

The inference counterpart of the fleet training engines: a block-paged
KV-cache pool shared by every in-flight request (`kv_pool.py`), a
continuous-batching scheduler that admits / chunk-prefills / batch-
decodes / preempts requests across fixed-shape jitted steps
(`scheduler.py` + `engine.py`), copy-on-write prefix caching over
refcounted pages (requests sharing a system prompt map the same
physical pages and skip its prefill) plus n-gram speculative decoding
(a `[max_batch, spec_k+1]` verify step advances greedy requests
several tokens per dispatch, token-identically), the multi-tenant
SLO layer (priority classes, token-bucket quotas, deadline-aware
admission, charged preemption, and the graceful-degradation ladder —
`ServingConfig(tenants=...)`, docs/serving.md#multi-tenant), and the
ragged paged-attention Pallas kernel
(`ops/pallas/paged_attention.py`) those steps call. Metrics
publish as `ptpu_serve_*` gauges + SLO percentile histograms through
core.monitor (`metrics.py`), surfaced in
`profiler.StepTelemetry.snapshot()['serve']` and rendered by
`tools/health_dump.py serve`; per-request lifecycle journals, the
scheduler timeline, and the stalled-request watchdog live in
`request_trace.py` + `scheduler.SchedulerTimeline`. See
docs/serving.md.
"""
from .kv_pool import KVPagePool, PoolExhausted
from .scheduler import (AdmissionRejected, DegradeLadder, Request,
                        RequestState, Scheduler, SchedulerTimeline,
                        TenantTable, TokenBucket)
from .engine import ServingConfig, ServingEngine
from .request_trace import (RequestTracer, load_trace, reconstruct,
                            render_serve_report)
from . import metrics

__all__ = [
    'KVPagePool', 'PoolExhausted', 'Request', 'RequestState',
    'Scheduler', 'SchedulerTimeline', 'ServingConfig', 'ServingEngine',
    'AdmissionRejected', 'DegradeLadder', 'TenantTable', 'TokenBucket',
    'RequestTracer', 'load_trace', 'reconstruct',
    'render_serve_report', 'metrics',
]
