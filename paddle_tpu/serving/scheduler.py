"""Continuous-batching scheduler (host side).

Orca-style iteration-level scheduling: requests join a FCFS queue,
claim a decode slot when one frees up, chunk-prefill their prompt, then
ride the batched decode step (one token per iteration, or up to
spec_k+1 with speculative decoding) until EOS / length, at which
point the slot is immediately re-filled — no waiting for the rest of
the batch. When the KV pool runs dry the YOUNGEST running request is
preempted: its page mappings are dropped — pages a prefix-sharing
sibling still references survive untouched (kv_pool.py refcounts) —
and it re-queues at the front with its generated tokens kept, so
resume is a re-prefill of prompt+generated that itself prefix-hits
any of its pages still cached (recompute of the rest beats reserving
swap space at these sizes).

All of this is pure host bookkeeping between fixed-shape jitted steps
(engine.py) — the device never sees a dynamic shape.

`SchedulerTimeline` is the iteration-level flight record: a ring
buffer of each engine sweep's batch composition (slots occupied,
prefill vs decode tokens, pool occupancy, admissions/preemptions) —
the per-replica occupancy-feedback signal the future disaggregated
router consumes (ROADMAP serve_scale), and the context a request
trace is read against ("request 7 stalled because iterations 40-60
ran the pool at 100%").
"""
import collections
import itertools
import time


class RequestState:
    WAITING = 'waiting'
    PREFILL = 'prefill'
    RUNNING = 'running'
    FINISHED = 'finished'
    ABORTED = 'aborted'


_ids = itertools.count()


class Request:
    """One generation request. `tokens` is the full device-visible
    context (prompt + generated so far); `prefilled` counts how many of
    them already sit in KV pages."""

    def __init__(self, prompt_ids, max_new_tokens=32, eos_token_id=None,
                 temperature=0.0, top_k=0):
        self.id = next(_ids)
        self.prompt = [int(t) for t in prompt_ids]
        if not self.prompt:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.generated = []
        self.prefilled = 0
        self.state = RequestState.WAITING
        self.submit_time = None
        self.admit_time = None           # first admit (queue-wait end)
        self.admit_bypasses = 0          # followers admitted past this
                                         # request while it sat at the
                                         # queue head over-budget
                                         # (engine._admit starvation
                                         # bound)
        self.first_token_time = None
        self.finish_time = None
        self.preemptions = 0

    @property
    def tokens(self):
        return self.prompt + self.generated

    @property
    def context_len(self):
        return len(self.prompt) + len(self.generated)

    @property
    def done(self):
        if len(self.generated) >= self.max_new_tokens:
            return True
        return (self.eos_token_id is not None and self.generated
                and self.generated[-1] == self.eos_token_id)

    def ttft_ms(self):
        if self.submit_time is None or self.first_token_time is None:
            return None
        return (self.first_token_time - self.submit_time) * 1000.0

    def output_ids(self):
        return list(self.tokens)


class Scheduler:
    """Slot table + FCFS queue. The engine drives it: `admit()` between
    steps, `preempt_victim()` when the pool is dry, `retire()` on
    completion."""

    def __init__(self, num_slots, clock=None):
        self.num_slots = int(num_slots)
        self.slots = [None] * self.num_slots
        self.waiting = []
        self.finished = []
        self.preemptions = 0
        self.clock = clock or time.perf_counter

    def submit(self, request):
        request.submit_time = self.clock()
        request.state = RequestState.WAITING
        self.waiting.append(request)
        return request.id

    def running(self):
        return [r for r in self.slots if r is not None]

    def occupancy(self):
        return len(self.running()) / self.num_slots

    @property
    def has_work(self):
        return bool(self.waiting or self.running())

    def admit(self, limit=None):
        """Fill free slots from the queue (FCFS), at most `limit` of
        them (None = all). One body with `admit_request` below — this
        is the unconditional head-first loop; the engine's budgeted
        sweep picks specific requests via admit_request directly."""
        admitted = []
        while self.waiting and (limit is None
                                or len(admitted) < limit):
            req = self.admit_request(self.waiting[0])
            if req is None:
                break
            admitted.append(req)
        return admitted

    def admit_request(self, request):
        """Admit one SPECIFIC waiting request into a free slot — the
        engine's head-of-line fairness path (ISSUE 11 satellite): when
        the queue head's first chunk exceeds the page budget this
        sweep, admissible followers behind it are admitted in FCFS
        order instead of starving behind the blocked head (which keeps
        its queue position and first claim on next sweep's budget).
        Returns the request, or None if it isn't waiting / no slot."""
        if request not in self.waiting:
            return None
        for i in range(self.num_slots):
            if self.slots[i] is None:
                self.waiting.remove(request)
                request.state = RequestState.PREFILL
                request.prefilled = 0
                if request.admit_time is None:
                    request.admit_time = self.clock()
                self.slots[i] = request
                return request
        return None

    def adopt(self, request):
        """Place an externally-prefilled request straight into a free
        slot in RUNNING state — the prefill→decode disaggregation
        handoff (serving/cluster/disagg.py): its KV pages were
        streamed into this engine's pool, so there is nothing to
        prefill. Returns the slot index, or None when no slot is
        free (the caller keeps it pending and retries)."""
        for i in range(self.num_slots):
            if self.slots[i] is None:
                request.state = RequestState.RUNNING
                if request.admit_time is None:
                    request.admit_time = self.clock()
                self.slots[i] = request
                return i
        return None

    def slot_of(self, request):
        return self.slots.index(request)

    def preempt_victim(self, exclude=None):
        """Youngest running/prefilling request (highest id ≈ last
        admitted), excluding `exclude`. None if there is nobody to
        preempt."""
        candidates = [r for r in self.slots
                      if r is not None and r is not exclude]
        if not candidates:
            return None
        return max(candidates, key=lambda r: r.id)

    def preempt(self, request):
        """Release the slot and push the request to the FRONT of the
        queue (it keeps FCFS priority over never-started work)."""
        i = self.slot_of(request)
        self.slots[i] = None
        request.state = RequestState.WAITING
        request.preemptions += 1
        self.preemptions += 1
        self.waiting.insert(0, request)

    def retire(self, request):
        i = self.slot_of(request)
        self.slots[i] = None
        request.state = RequestState.FINISHED
        request.finish_time = self.clock()
        self.finished.append(request)

    def abort(self, request):
        """Drop a request wherever it sits (queue or slot) — the
        watchdog's deadline_action='abort' path and operator kill.
        No-op on a request that already reached a terminal state (a
        double abort must not re-append to `finished` or restamp
        finish_time). Returns True if the request was aborted here."""
        if request.state in (RequestState.FINISHED,
                             RequestState.ABORTED):
            return False
        if request in self.waiting:
            self.waiting.remove(request)
        elif request in self.slots:
            self.slots[self.slots.index(request)] = None
        request.state = RequestState.ABORTED
        request.finish_time = self.clock()
        self.finished.append(request)
        return True


class SchedulerTimeline:
    """Ring buffer of per-iteration batch-composition records — what
    the engine actually ran each sweep. One dict per engine.step():

      iter, t, decode_slots_occupied, decode_slots, prefill_tokens,
      decode_tokens, admissions, preemptions, waiting,
      pool_pages_in_use, pool_pages_total

    `summary()` aggregates it into the occupancy-feedback numbers the
    bench leg and serve_snapshot() surface."""

    def __init__(self, capacity=2048):
        self._ring = collections.deque(maxlen=int(capacity))
        self.iterations = 0         # lifetime count (ring may be full)

    def record(self, **entry):
        entry['iter'] = self.iterations
        self.iterations += 1
        self._ring.append(entry)

    def tail(self, n=32):
        n = int(n)
        return list(self._ring)[-n:] if n else []

    def snapshot(self):
        return list(self._ring)

    def reset(self):
        self._ring.clear()
        self.iterations = 0

    def summary(self):
        rows = list(self._ring)
        if not rows:
            return {'iterations': 0}
        n = len(rows)
        slots = max(rows[-1].get('decode_slots', 1), 1)
        pool = max(rows[-1].get('pool_pages_total', 1), 1)
        decode_rows = [r for r in rows if r.get('decode_tokens')]
        return {
            'iterations': self.iterations,
            'window': n,
            'mean_decode_slots_occupied':
                sum(r.get('decode_slots_occupied', 0)
                    for r in rows) / n,
            'mean_occupancy':
                sum(r.get('decode_slots_occupied', 0)
                    for r in decode_rows) / (len(decode_rows) * slots)
                if decode_rows else 0.0,
            'mean_pool_utilization':
                sum(r.get('pool_pages_in_use', 0) for r in rows)
                / (n * pool),
            'prefill_tokens': sum(r.get('prefill_tokens', 0)
                                  for r in rows),
            'decode_tokens': sum(r.get('decode_tokens', 0)
                                 for r in rows),
            'admissions': sum(r.get('admissions', 0) for r in rows),
            'preemptions': sum(r.get('preemptions', 0) for r in rows),
            'max_waiting': max(r.get('waiting', 0) for r in rows),
        }
