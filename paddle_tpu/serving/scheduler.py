"""Continuous-batching scheduler (host side).

Orca-style iteration-level scheduling: requests join a FCFS queue,
claim a decode slot when one frees up, chunk-prefill their prompt, then
ride the batched one-token decode step until EOS / length, at which
point the slot is immediately re-filled — no waiting for the rest of
the batch. When the KV pool runs dry the YOUNGEST running request is
preempted: its pages are released and it re-queues at the front with
its generated tokens kept, so resume is a re-prefill of
prompt+generated (recompute beats reserving swap space at these sizes).

All of this is pure host bookkeeping between fixed-shape jitted steps
(engine.py) — the device never sees a dynamic shape.
"""
import itertools
import time


class RequestState:
    WAITING = 'waiting'
    PREFILL = 'prefill'
    RUNNING = 'running'
    FINISHED = 'finished'


_ids = itertools.count()


class Request:
    """One generation request. `tokens` is the full device-visible
    context (prompt + generated so far); `prefilled` counts how many of
    them already sit in KV pages."""

    def __init__(self, prompt_ids, max_new_tokens=32, eos_token_id=None,
                 temperature=0.0, top_k=0):
        self.id = next(_ids)
        self.prompt = [int(t) for t in prompt_ids]
        if not self.prompt:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.generated = []
        self.prefilled = 0
        self.state = RequestState.WAITING
        self.submit_time = None
        self.first_token_time = None
        self.finish_time = None
        self.preemptions = 0

    @property
    def tokens(self):
        return self.prompt + self.generated

    @property
    def context_len(self):
        return len(self.prompt) + len(self.generated)

    @property
    def done(self):
        if len(self.generated) >= self.max_new_tokens:
            return True
        return (self.eos_token_id is not None and self.generated
                and self.generated[-1] == self.eos_token_id)

    def ttft_ms(self):
        if self.submit_time is None or self.first_token_time is None:
            return None
        return (self.first_token_time - self.submit_time) * 1000.0

    def output_ids(self):
        return list(self.tokens)


class Scheduler:
    """Slot table + FCFS queue. The engine drives it: `admit()` between
    steps, `preempt_victim()` when the pool is dry, `retire()` on
    completion."""

    def __init__(self, num_slots):
        self.num_slots = int(num_slots)
        self.slots = [None] * self.num_slots
        self.waiting = []
        self.finished = []
        self.preemptions = 0

    def submit(self, request):
        request.submit_time = time.perf_counter()
        request.state = RequestState.WAITING
        self.waiting.append(request)
        return request.id

    def running(self):
        return [r for r in self.slots if r is not None]

    def occupancy(self):
        return len(self.running()) / self.num_slots

    @property
    def has_work(self):
        return bool(self.waiting or self.running())

    def admit(self, limit=None):
        """Fill free slots from the queue (FCFS), at most `limit` of
        them (None = all). Returns the admitted requests; the engine
        admits one at a time against its page budget and allocates
        first pages at the prefill step (bouncing a request back via
        `preempt()` if even that fails)."""
        admitted = []
        for i in range(self.num_slots):
            if limit is not None and len(admitted) >= limit:
                break
            if self.slots[i] is None and self.waiting:
                req = self.waiting.pop(0)
                req.state = RequestState.PREFILL
                # resume after preemption re-prefills prompt+generated
                req.prefilled = 0
                self.slots[i] = req
                admitted.append(req)
        return admitted

    def slot_of(self, request):
        return self.slots.index(request)

    def preempt_victim(self, exclude=None):
        """Youngest running/prefilling request (highest id ≈ last
        admitted), excluding `exclude`. None if there is nobody to
        preempt."""
        candidates = [r for r in self.slots
                      if r is not None and r is not exclude]
        if not candidates:
            return None
        return max(candidates, key=lambda r: r.id)

    def preempt(self, request):
        """Release the slot and push the request to the FRONT of the
        queue (it keeps FCFS priority over never-started work)."""
        i = self.slot_of(request)
        self.slots[i] = None
        request.state = RequestState.WAITING
        request.preemptions += 1
        self.preemptions += 1
        self.waiting.insert(0, request)

    def retire(self, request):
        i = self.slot_of(request)
        self.slots[i] = None
        request.state = RequestState.FINISHED
        request.finish_time = time.perf_counter()
        self.finished.append(request)
