"""Continuous-batching scheduler (host side).

Orca-style iteration-level scheduling: requests join an admission
queue, claim a decode slot when one frees up, chunk-prefill their
prompt, then ride the batched decode step (one token per iteration, or
up to spec_k+1 with speculative decoding) until EOS / length, at which
point the slot is immediately re-filled — no waiting for the rest of
the batch. When the KV pool runs dry a victim is preempted: its page
mappings are dropped — pages a prefix-sharing sibling still references
survive untouched (kv_pool.py refcounts) — and it re-queues at the
front with its generated tokens kept, so resume is a re-prefill of
prompt+generated that itself prefix-hits any of its pages still cached
(recompute of the rest beats reserving swap space at these sizes).

Multi-tenant SLO layer (ISSUE 15): a `Request` carries `tenant_id`,
`priority` (small int class, larger = more important) and an optional
`deadline_s`; `TenantTable` maps tenants to (priority, token-rate
quota via a refillable `TokenBucket`, prefix-cache weight). Admission
order is priority-then-FCFS-within-class (`admission_order()`), and
the preemption victim under pool pressure is the youngest request of
the LOWEST priority class strictly below the admitting request
(`preempt_victim(below_priority=)`). With no tenants configured every
request sits in the default class 0 and both rules degrade EXACTLY to
the original FCFS / preempt-youngest behavior (token-identity asserted
in tests/test_serving_tenants.py).

`DegradeLadder` is the graceful-overload controller: a windowed
pressure signal (pool occupancy + waiting depth) walks the engine up
three degradation stages — shed speculative decoding, shrink prefill
chunks, evict prefix-cache subtrees by tenant weight — and back down
hysteretically (lower down-thresholds + a dwell count) when pressure
clears, so a noisy signal never oscillates the ladder.

All of this is pure host bookkeeping between fixed-shape jitted steps
(engine.py) — the device never sees a dynamic shape.

`SchedulerTimeline` is the iteration-level flight record: a ring
buffer of each engine sweep's batch composition (slots occupied,
prefill vs decode tokens, pool occupancy, admissions/preemptions) —
the per-replica occupancy-feedback signal the future disaggregated
router consumes (ROADMAP serve_scale), and the context a request
trace is read against ("request 7 stalled because iterations 40-60
ran the pool at 100%").
"""
import collections
import itertools
import time


class AdmissionRejected(RuntimeError):
    """Deadline-aware admission turned a request away AT SUBMIT: its
    estimated completion (pending tokens / observed decode rate — the
    PR-11 router `deadline_bound_s` math moved down into the engine)
    already exceeds its `deadline_s`, so queueing it would only burn
    pool pages on certain failure. Structured so callers can back off
    by the hint instead of a fixed sleep (the cluster router re-raises
    it as a structured RouterRejected)."""

    def __init__(self, reason, retry_after_s=None, estimated_s=None,
                 deadline_s=None, message=None):
        super().__init__(
            message or f"admission rejected ({reason}): estimated "
                       f"completion {_fmt_s(estimated_s)} exceeds "
                       f"deadline {_fmt_s(deadline_s)} — retry in "
                       f"~{_fmt_s(retry_after_s)}")
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.estimated_s = estimated_s
        self.deadline_s = deadline_s


def _fmt_s(v):
    return f'{v:.3f}s' if isinstance(v, (int, float)) else '?'


class TokenBucket:
    """Refillable token-rate quota. `rate` tokens/s stream in up to a
    `burst` cap; admission debits a request's whole token bill at
    once. The level may go NEGATIVE (debt) in two cases: a request
    bigger than the burst admits when the bucket is full (over-quota
    tenants are deferrable, never unservable), and charged preemptions
    (`charge()`) debit unconditionally — the tenant then waits out its
    debt before the next admit. Refill is lazy (computed from the
    injected clock at read time), so deterministic-clock tests step it
    exactly."""

    def __init__(self, rate, burst=None, clock=None):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None
                           else max(self.rate, 1.0))
        self.clock = clock or time.perf_counter
        self._level = self.burst
        self._t = self.clock()

    def _refill(self):
        now = self.clock()
        dt = max(now - self._t, 0.0)
        self._t = now
        self._level = min(self._level + dt * self.rate, self.burst)

    @property
    def level(self):
        self._refill()
        return self._level

    def try_debit(self, cost):
        """Debit `cost` tokens if the tenant has quota NOW: the bucket
        must hold min(cost, burst) — a bill larger than the burst cap
        admits from a full bucket and leaves debt. Returns True when
        debited (admit), False when the caller should defer."""
        self._refill()
        if self._level < min(float(cost), self.burst):
            return False
        self._level -= float(cost)
        return True

    def charge(self, cost):
        """Unconditional debit (may go negative) — the charged-
        preemption path: churning the pool spends the preemptor's own
        quota."""
        self._refill()
        self._level -= float(cost)

    def seconds_until(self, cost):
        """Time until try_debit(cost) would succeed (0.0 when it would
        succeed now) — the quota-defer retry hint."""
        self._refill()
        need = min(float(cost), self.burst) - self._level
        if need <= 0.0:
            return 0.0
        return need / self.rate if self.rate > 0 else float('inf')


class TenantTable:
    """The `ServingConfig.tenants` policy map resolved into runtime
    state: per-tenant priority class, optional `TokenBucket` quota and
    prefix-cache eviction weight. Unknown tenants (and tenant_id=None)
    fall into the default class: priority 0, no quota, weight 1.0 —
    declaring tenants must never break anonymous traffic."""

    def __init__(self, tenants, clock=None):
        self.clock = clock or time.perf_counter
        self._policies = {}
        self._buckets = {}
        for tid, pol in (tenants or {}).items():
            pol = dict(pol or {})
            unknown = set(pol) - {'priority', 'quota_tokens_per_s',
                                  'burst_tokens', 'weight'}
            if unknown:
                raise ValueError(
                    f"tenant {tid!r}: unknown policy keys "
                    f"{sorted(unknown)} (allowed: priority, "
                    f"quota_tokens_per_s, burst_tokens, weight)")
            self._policies[str(tid)] = {
                'priority': int(pol.get('priority', 0)),
                'quota_tokens_per_s': pol.get('quota_tokens_per_s'),
                'burst_tokens': pol.get('burst_tokens'),
                'weight': float(pol.get('weight', 1.0)),
            }
            rate = pol.get('quota_tokens_per_s')
            if rate is not None:
                self._buckets[str(tid)] = TokenBucket(
                    rate, pol.get('burst_tokens'), clock=self.clock)

    def __contains__(self, tenant_id):
        return str(tenant_id) in self._policies

    def tenants(self):
        return list(self._policies)

    def policy(self, tenant_id):
        return self._policies.get(str(tenant_id))

    def priority_of(self, tenant_id):
        pol = self._policies.get(str(tenant_id))
        return pol['priority'] if pol else 0

    def bucket(self, tenant_id):
        return self._buckets.get(str(tenant_id))

    def weight_of(self, tenant_id):
        pol = self._policies.get(str(tenant_id))
        return pol['weight'] if pol else 1.0

    def eviction_weights(self):
        """{tenant_id: weight} for kv_pool.set_eviction_weights —
        lower weight evicts first at degradation stage 3."""
        return {tid: pol['weight']
                for tid, pol in self._policies.items()}


class DegradeLadder:
    """Graceful-degradation controller (ISSUE 15): a windowed pressure
    signal walks an integer stage 0..3 up eagerly and down
    hysteretically.

    Pressure per iteration = max(pool utilization, waiting/(2*slots))
    clamped to [0, 1] — either a full pool or a deep queue is
    overload — averaged over the last `window` observations. The stage
    steps UP (one stage per observation) when the mean crosses
    `up[stage]`, and steps DOWN only after the mean has sat below
    `down[stage-1]` for `hold` consecutive observations — the up/down
    threshold gap plus the dwell count is the hysteresis that keeps a
    noisy signal from oscillating the ladder (asserted in
    tests/test_serving_tenants.py).

    Stage semantics live in the engine (0 = normal, 1 = shed
    speculative decoding, 2 = shrink prefill chunks, 3 = weighted
    prefix-cache eviction); the ladder only decides WHEN. Every
    transition lands in `history` — the engine turns each into a gauge
    update + trace event."""

    STAGE_NAMES = ('normal', 'shed_spec', 'shrink_prefill',
                   'weighted_evict')

    def __init__(self, window=8, up=(0.85, 0.92, 0.97),
                 down=(0.60, 0.70, 0.80), hold=4, clock=None):
        if len(up) != 3 or len(down) != 3:
            raise ValueError("up/down need one threshold per stage "
                             "transition (3 each)")
        if any(d >= u for u, d in zip(up, down)):
            raise ValueError(
                f"each down-threshold must sit below its up-threshold "
                f"for hysteresis (up={up}, down={down})")
        self.window = int(window)
        self.up = tuple(float(u) for u in up)
        self.down = tuple(float(d) for d in down)
        self.hold = int(hold)
        self.clock = clock or time.perf_counter
        self.stage = 0
        self._ring = collections.deque(maxlen=self.window)
        self._calm = 0                  # consecutive below-threshold
        self.history = []               # [{t, from, to, pressure}]
        self.transitions = 0

    @staticmethod
    def pressure_of(pool_utilization, waiting, slots, spill=0.0):
        """`spill` (ISSUE 20) is the host-tier occupancy fraction:
        while the tier absorbs pool pressure by spilling, the pool-
        utilization signal alone under-reports how close the system is
        to REAL capacity — a saturating second tier must push the
        ladder toward stage-3 weighted eviction before allocation
        starts dropping prefixes outright. 0.0 (tierless) reproduces
        the PR-15 signal exactly."""
        q = min(float(waiting) / max(2.0 * slots, 1.0), 1.0)
        return min(max(float(pool_utilization), q, float(spill)), 1.0)

    def pressure(self):
        """Windowed mean of the observed pressure (0.0 when empty)."""
        return (sum(self._ring) / len(self._ring)
                if self._ring else 0.0)

    def would_transition(self, pressure_signal, steps=1):
        """Would holding `pressure_signal` for the next `steps`
        observations move the stage? Pure simulation on COPIES of the
        ring/calm/stage state — the engine's fused-window quiescence
        guard (ISSUE 19): a k-iteration fused dispatch commits the
        engine to k observations it cannot react to mid-window, so it
        only engages when no stage transition is due within the
        window."""
        ring = collections.deque(self._ring, maxlen=self.window)
        calm = self._calm
        stage = self.stage
        sig = min(max(float(pressure_signal), 0.0), 1.0)
        for _ in range(int(steps)):
            ring.append(sig)
            p = sum(ring) / len(ring)
            if stage < 3 and p >= self.up[stage]:
                return True
            elif stage > 0 and p < self.down[stage - 1]:
                calm += 1
                if calm >= self.hold:
                    return True
            else:
                calm = 0
        return False

    def observe(self, pool_utilization, waiting, slots, spill=0.0):
        """Feed one iteration's raw signals; returns the transition
        dict when the stage changed this observation, else None."""
        self._ring.append(self.pressure_of(pool_utilization, waiting,
                                           slots, spill))
        p = self.pressure()
        prev = self.stage
        if self.stage < 3 and p >= self.up[self.stage]:
            self.stage += 1
            self._calm = 0
        elif self.stage > 0 and p < self.down[self.stage - 1]:
            self._calm += 1
            if self._calm >= self.hold:
                self.stage -= 1
                self._calm = 0
        else:
            self._calm = 0
        if self.stage == prev:
            return None
        ev = {'t': self.clock(), 'from': prev, 'to': self.stage,
              'pressure': round(p, 4)}
        self.history.append(ev)
        self.transitions += 1
        return ev


class RequestState:
    WAITING = 'waiting'
    PREFILL = 'prefill'
    RUNNING = 'running'
    FINISHED = 'finished'
    ABORTED = 'aborted'


_ids = itertools.count()


class Request:
    """One generation request. `tokens` is the full device-visible
    context (prompt + generated so far); `prefilled` counts how many of
    them already sit in KV pages."""

    def __init__(self, prompt_ids, max_new_tokens=32, eos_token_id=None,
                 temperature=0.0, top_k=0, tenant_id=None, priority=0,
                 deadline_s=None):
        self.id = next(_ids)
        self.prompt = [int(t) for t in prompt_ids]
        if not self.prompt:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        # tenancy (ISSUE 15): tenant_id groups quota/SLO accounting,
        # priority orders admission and bounds preemption, deadline_s
        # (relative to submit) drives deadline-aware admission
        self.tenant_id = (str(tenant_id) if tenant_id is not None
                          else None)
        self.priority = int(priority)
        self.deadline_s = (float(deadline_s) if deadline_s is not None
                           else None)
        self.quota_charged = False       # token bill debited at first
                                         # admit only (resume is free —
                                         # the preemptor paid)
        self.quota_defers = 0
        self.quota_deferred = False      # edge-detect for the
                                         # quota_defer trace event
        self.generated = []
        self.prefilled = 0
        self.state = RequestState.WAITING
        self.submit_time = None
        self.admit_time = None           # first admit (queue-wait end)
        self.admit_bypasses = 0          # followers admitted past this
                                         # request while it sat at the
                                         # queue head over-budget
                                         # (engine._admit starvation
                                         # bound)
        self.first_token_time = None
        self.finish_time = None
        self.preemptions = 0
        # engine-local sampling ordinal (ISSUE 19): assigned once at
        # engine.submit and folded with the absolute token position
        # into the device sampling key, so a request's sampled tokens
        # are a pure function of (seed, ordinal, position) — invariant
        # across fused/serial decode, spec verify, and preempt/resume
        self.sample_ord = None

    @property
    def tokens(self):
        return self.prompt + self.generated

    @property
    def context_len(self):
        return len(self.prompt) + len(self.generated)

    @property
    def done(self):
        if len(self.generated) >= self.max_new_tokens:
            return True
        return (self.eos_token_id is not None and self.generated
                and self.generated[-1] == self.eos_token_id)

    def ttft_ms(self):
        if self.submit_time is None or self.first_token_time is None:
            return None
        return (self.first_token_time - self.submit_time) * 1000.0

    def output_ids(self):
        return list(self.tokens)


class Scheduler:
    """Slot table + admission queue. The engine drives it: `admit()`
    between steps, `preempt_victim()` when the pool is dry, `retire()`
    on completion. `self.waiting` stays in arrival order (preempts
    re-insert at the front); `admission_order()` is the priority view
    the engine sweeps — identical to arrival order when every request
    sits in the default class."""

    def __init__(self, num_slots, clock=None):
        self.num_slots = int(num_slots)
        self.slots = [None] * self.num_slots
        self.waiting = []
        self.finished = []
        self.preemptions = 0
        self.clock = clock or time.perf_counter

    def submit(self, request):
        request.submit_time = self.clock()
        request.state = RequestState.WAITING
        self.waiting.append(request)
        return request.id

    def running(self):
        return [r for r in self.slots if r is not None]

    def occupancy(self):
        return len(self.running()) / self.num_slots

    @property
    def has_work(self):
        return bool(self.waiting or self.running())

    def quiescent(self):
        """True when a multi-iteration decode window can run with no
        scheduling decision falling due mid-window (the fused-decode
        eligibility gate, ISSUE 19): nothing waiting to admit, at
        least one occupied slot, and every occupied slot a RUNNING
        decoder. Retires inside the window need no host decision —
        the fused done-mask idles finished rows on device and the
        engine retires them at window end; with an empty queue the
        held slot admits nobody late. Page growth (the only
        preemption trigger) is pre-reserved per window by the engine,
        and degrade-transition headroom is checked against the ladder
        separately."""
        if self.waiting:
            return False
        occupied = [r for r in self.slots if r is not None]
        if not occupied:
            return False
        return all(r.state == RequestState.RUNNING for r in occupied)

    def admission_order(self):
        """The queue in admission order: priority classes high to low,
        FCFS (arrival order, preempts first) within a class. A stable
        sort on -priority over the arrival-ordered list — with no
        tenants configured every priority is 0 and this IS the arrival
        order, so default scheduling is unchanged."""
        return sorted(self.waiting, key=lambda r: -r.priority)

    def admit(self, limit=None):
        """Fill free slots from the queue (priority-then-FCFS), at
        most `limit` of them (None = all). One body with
        `admit_request` below — this is the unconditional head-first
        loop; the engine's budgeted sweep picks specific requests via
        admit_request directly. The order is sorted ONCE per call:
        admitting never reorders the remaining queue (stable key), so
        re-sorting per admission would be pure waste on the host hot
        path."""
        admitted = []
        for req in self.admission_order():
            if limit is not None and len(admitted) >= limit:
                break
            if self.admit_request(req) is None:
                break
            admitted.append(req)
        return admitted

    def admit_request(self, request):
        """Admit one SPECIFIC waiting request into a free slot — the
        engine's head-of-line fairness path (ISSUE 11 satellite): when
        the queue head's first chunk exceeds the page budget this
        sweep, admissible followers behind it are admitted in FCFS
        order instead of starving behind the blocked head (which keeps
        its queue position and first claim on next sweep's budget).
        Returns the request, or None if it isn't waiting / no slot."""
        if request not in self.waiting:
            return None
        for i in range(self.num_slots):
            if self.slots[i] is None:
                self.waiting.remove(request)
                request.state = RequestState.PREFILL
                request.prefilled = 0
                if request.admit_time is None:
                    request.admit_time = self.clock()
                self.slots[i] = request
                return request
        return None

    def adopt(self, request):
        """Place an externally-prefilled request straight into a free
        slot in RUNNING state — the prefill→decode disaggregation
        handoff (serving/cluster/disagg.py): its KV pages were
        streamed into this engine's pool, so there is nothing to
        prefill. Returns the slot index, or None when no slot is
        free (the caller keeps it pending and retries)."""
        for i in range(self.num_slots):
            if self.slots[i] is None:
                request.state = RequestState.RUNNING
                if request.admit_time is None:
                    request.admit_time = self.clock()
                self.slots[i] = request
                return i
        return None

    def slot_of(self, request):
        return self.slots.index(request)

    def preempt_victim(self, exclude=None, below_priority=None):
        """Preemption victim among running/prefilling requests,
        excluding `exclude`. With `below_priority` set (tenancy
        active), the victim is the YOUNGEST request (highest id ≈ last
        admitted) of the LOWEST priority class strictly below it — a
        high-priority admit displaces the least important, most
        recently started work first, and never a peer or better. With
        `below_priority` None (no tenants), the victim is the youngest
        overall — the original behavior, bit-for-bit. None if nobody
        qualifies."""
        candidates = [r for r in self.slots
                      if r is not None and r is not exclude]
        if not candidates:
            return None
        if below_priority is None:
            return max(candidates, key=lambda r: r.id)
        candidates = [r for r in candidates
                      if r.priority < below_priority]
        if not candidates:
            return None
        lowest = min(r.priority for r in candidates)
        return max((r for r in candidates if r.priority == lowest),
                   key=lambda r: r.id)

    def preempt(self, request):
        """Release the slot and push the request to the FRONT of the
        queue (it keeps FCFS priority over never-started work)."""
        i = self.slot_of(request)
        self.slots[i] = None
        request.state = RequestState.WAITING
        request.preemptions += 1
        self.preemptions += 1
        self.waiting.insert(0, request)

    def retire(self, request):
        i = self.slot_of(request)
        self.slots[i] = None
        request.state = RequestState.FINISHED
        request.finish_time = self.clock()
        self.finished.append(request)

    def abort(self, request):
        """Drop a request wherever it sits (queue or slot) — the
        watchdog's deadline_action='abort' path and operator kill.
        No-op on a request that already reached a terminal state (a
        double abort must not re-append to `finished` or restamp
        finish_time). Returns True if the request was aborted here."""
        if request.state in (RequestState.FINISHED,
                             RequestState.ABORTED):
            return False
        if request in self.waiting:
            self.waiting.remove(request)
        elif request in self.slots:
            self.slots[self.slots.index(request)] = None
        request.state = RequestState.ABORTED
        request.finish_time = self.clock()
        self.finished.append(request)
        return True


class SchedulerTimeline:
    """Ring buffer of per-iteration batch-composition records — what
    the engine actually ran each sweep. One dict per engine.step():

      iter, t, decode_slots_occupied, decode_slots, prefill_tokens,
      decode_tokens, admissions, preemptions, waiting,
      pool_pages_in_use, pool_pages_total

    `summary()` aggregates it into the occupancy-feedback numbers the
    bench leg and serve_snapshot() surface."""

    def __init__(self, capacity=2048):
        self._ring = collections.deque(maxlen=int(capacity))
        self.iterations = 0         # lifetime count (ring may be full)

    def record(self, **entry):
        entry['iter'] = self.iterations
        self.iterations += 1
        self._ring.append(entry)

    def tail(self, n=32):
        n = int(n)
        return list(self._ring)[-n:] if n else []

    def snapshot(self):
        return list(self._ring)

    def reset(self):
        self._ring.clear()
        self.iterations = 0

    def summary(self):
        rows = list(self._ring)
        if not rows:
            return {'iterations': 0}
        n = len(rows)
        slots = max(rows[-1].get('decode_slots', 1), 1)
        pool = max(rows[-1].get('pool_pages_total', 1), 1)
        decode_rows = [r for r in rows if r.get('decode_tokens')]
        return {
            'iterations': self.iterations,
            'window': n,
            'mean_decode_slots_occupied':
                sum(r.get('decode_slots_occupied', 0)
                    for r in rows) / n,
            'mean_occupancy':
                sum(r.get('decode_slots_occupied', 0)
                    for r in decode_rows) / (len(decode_rows) * slots)
                if decode_rows else 0.0,
            'mean_pool_utilization':
                sum(r.get('pool_pages_in_use', 0) for r in rows)
                / (n * pool),
            'prefill_tokens': sum(r.get('prefill_tokens', 0)
                                  for r in rows),
            'decode_tokens': sum(r.get('decode_tokens', 0)
                                 for r in rows),
            'admissions': sum(r.get('admissions', 0) for r in rows),
            'preemptions': sum(r.get('preemptions', 0) for r in rows),
            'max_waiting': max(r.get('waiting', 0) for r in rows),
            'degrade_stage': rows[-1].get('degrade_stage', 0),
            'max_degrade_stage': max(r.get('degrade_stage', 0)
                                     for r in rows),
            # fused decode (ISSUE 19): entries recorded for iterations
            # that ran INSIDE a fused window — the engine records one
            # entry per iteration, never per dispatch, so occupancy
            # and token sums stay comparable across fused/serial
            'fused_iterations': sum(1 for r in rows if r.get('fused')),
        }
