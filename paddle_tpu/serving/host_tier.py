"""Host-RAM tier under the paged KV pool (ISSUE 20).

A pinned host buffer pool that holds KV pages spilled out of the
device pool: one preallocated numpy buffer per layer buffer, shaped
like the device pool's but with `host_pages` rows, so a spilled page
lands in the host row its slot id names and a fetch scatters it back
into whichever device page the pool hands out. Int8 pools need no
special casing — each layer's buffer TUPLE is mirrored element-wise,
so the fp32 scale siblings travel with their int8 pages bit-identically
(the `page_stream` contract: rows move as stored, nothing re-quantizes).

Transfer discipline is PR-13's background ring, adapted to spills:

  * device→host SPILL stages a gather (`kv[l][b][pages]` — a fresh
    device array, so live device pages are never aliased) on the
    caller's thread, then hands the staged arrays to one background
    transfer thread that blocks on `device_get` and copies rows into
    the host buffers;
  * the in-flight window is bounded (`window` jobs): a producer that
    outruns the drain blocks on the semaphore instead of queueing
    unbounded staging footprint;
  * the spilled DEVICE pages stay pinned (outside the pool's free and
    cached sets — `try_reserve` and `_take_page` cannot see them)
    until the job lands and its completion callback returns them;
  * host→device FETCH (resurrect/warm) runs synchronously on the
    caller's thread — callers mutate `pool.kv`, which only the engine
    thread (or a replica host holding the engine lock) may do — and
    waits out any still-in-flight spill of the requested slots first.

Transfers are chunked through `core.bucketing._chunk_spans` exactly
like `cluster/page_stream.py`, which also makes the mp-sharded case
fall out: gather/scatter on the page axis of a `P(None, None, 'mp')`
sharded pool moves each rank's local-heads shard, so per-rank shards
spill and fetch through the same chunked path.
"""
import queue
import threading
import time

import numpy as np

from ..core import monitor as _m
from ..core.bucketing import _chunk_spans


def _count_transfer(kind, pages, nbytes):
    if kind == 'spilled':
        _m.counter('ptpu_serve_tier_spilled_pages_total',
                   help='KV pages spilled device->host tier '
                        '(lifetime)').inc(pages)
        _m.counter('ptpu_serve_tier_spilled_bytes_total',
                   help='bytes spilled device->host tier, scale '
                        'buffers included (lifetime)').inc(nbytes)
    else:
        _m.counter('ptpu_serve_tier_fetched_pages_total',
                   help='KV pages fetched host->device tier '
                        '(lifetime)').inc(pages)
        _m.counter('ptpu_serve_tier_fetched_bytes_total',
                   help='bytes fetched host->device tier, scale '
                        'buffers included (lifetime)').inc(nbytes)


class HostTier:
    """Slot allocator + pinned host buffers + the transfer thread.

    `host_pages` is the tier's capacity in pages; buffers allocate
    lazily on first spill (mirroring the pool's materialized layer
    shapes), so a tier-enabled engine that never spills costs no host
    RAM and dispatches nothing — the no-spill path stays inert."""

    def __init__(self, host_pages, chunk_pages=0, window=2):
        if host_pages <= 0:
            raise ValueError("host_pages must be positive")
        self.host_pages = int(host_pages)
        self.chunk_pages = int(chunk_pages)
        self.window = max(int(window), 1)
        self._free = list(range(self.host_pages - 1, -1, -1))
        self._buffers = None            # [layer][buf] np arrays
        self._landed = {}               # slot -> Event (in-flight spill)
        self._jobs = queue.Queue()
        self._slots_sem = threading.Semaphore(self.window)
        self._thread = None
        self._lock = threading.Lock()
        self.spilled_pages = 0
        self.spilled_bytes = 0
        self.fetched_pages = 0
        self.fetched_bytes = 0
        self.spill_jobs = 0
        self._wall_s = 0.0              # un-drained transfer wall —
                                        # the engine folds it into the
                                        # ledger's page_stream component

    # -- slots ---------------------------------------------------------------
    @property
    def used_slots(self):
        return self.host_pages - len(self._free)

    @property
    def free_slots(self):
        return len(self._free)

    def alloc_slots(self, n):
        """Take n host slots, or None when the tier lacks room (the
        pool then evicts its LRU host subtree or falls back to plain
        device-side eviction)."""
        with self._lock:
            if len(self._free) < n:
                return None
            return [self._free.pop() for _ in range(n)]

    def free_slot(self, slot):
        with self._lock:
            self._landed.pop(slot, None)
            self._free.append(slot)

    # -- buffers -------------------------------------------------------------
    def _ensure_buffers(self, kv):
        if self._buffers is not None:
            return
        bufs = []
        for layer in kv:
            bufs.append([np.zeros((self.host_pages,) + tuple(b.shape[1:]),
                                  dtype=np.dtype(b.dtype))
                         for b in layer])
        self._buffers = bufs

    @staticmethod
    def _page_bytes(buf):
        return int(buf.nbytes) // buf.shape[0]

    # -- spill (device -> host, background) ----------------------------------
    def _stage(self, kv, device_pages):
        """Gather the page rows into fresh device arrays (one per
        layer buffer, chunk boundaries preserved) — the never-alias
        staging copy. Dispatch is async; the transfer thread's
        device_get is what blocks on it."""
        import jax.numpy as jnp
        n = len(device_pages)
        spans = _chunk_spans(n, 1, self.chunk_pages) or [(0, n)]
        idx = jnp.asarray(list(device_pages), jnp.int32)
        staged = []
        for layer in kv:
            staged.append([[b[idx[st:st + w]] for (st, w) in spans]
                           for b in layer])
        return staged, spans

    def _land(self, staged, spans, slots):
        import jax
        t0 = time.perf_counter()
        nbytes = 0
        for li, layer in enumerate(staged):
            for bi, chunks in enumerate(layer):
                host = self._buffers[li][bi]
                for (st, w), chunk in zip(spans, chunks):
                    rows = jax.device_get(chunk)
                    for j in range(w):
                        host[slots[st + j]] = rows[j]
                nbytes += len(slots) * self._page_bytes(host)
        dt = time.perf_counter() - t0
        with self._lock:
            self.spilled_pages += len(slots)
            self.spilled_bytes += nbytes
            self.spill_jobs += 1
            self._wall_s += dt
            for s in slots:
                ev = self._landed.get(s)
                if ev is not None:
                    ev.set()
        _count_transfer('spilled', len(slots), nbytes)

    def _worker(self):
        while True:
            job = self._jobs.get()
            if job is None:
                return
            staged, spans, slots, on_landed = job
            try:
                self._land(staged, spans, slots)
            finally:
                # release the window slot BEFORE the callback: the
                # producer may be blocked on the semaphore while
                # holding the pool lock (submit_spill runs under it),
                # and on_landed needs that same lock — callback-first
                # would deadlock the pair
                self._slots_sem.release()
                if on_landed is not None:
                    on_landed()

    def submit_spill(self, kv, device_pages, slots, on_landed=None):
        """Queue an async spill of `device_pages` into host `slots`.
        Blocks only when `window` jobs are already in flight (the
        bounded ring). `on_landed` runs on the transfer thread after
        the rows are host-resident — the pool uses it to unpin the
        device pages."""
        self._ensure_buffers(kv)
        with self._lock:
            for s in slots:
                self._landed[s] = threading.Event()
        self._slots_sem.acquire()
        staged, spans = self._stage(kv, device_pages)
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._worker, name='kvtier-spill', daemon=True)
            self._thread.start()
        self._jobs.put((staged, spans, slots, on_landed))

    def spill_sync(self, kv, device_pages, slots):
        """Inline spill — the exhaustion fallback when `_take_page`
        needs a free page NOW and the proactive spiller hasn't kept
        up. Same staging + landing path, no thread hop."""
        self._ensure_buffers(kv)
        with self._lock:
            for s in slots:
                self._landed[s] = threading.Event()
        staged, spans = self._stage(kv, device_pages)
        self._land(staged, spans, slots)

    def wait_landed(self, slots):
        """Block until every slot's in-flight spill (if any) has
        landed — fetch correctness when a resurrect races a spill."""
        for s in list(slots):
            with self._lock:
                ev = self._landed.get(s)
            if ev is not None:
                ev.wait()

    # -- fetch (host -> device, synchronous) ---------------------------------
    def fetch(self, kv, slots, device_pages):
        """Scatter host rows `slots[i]` into device pages
        `device_pages[i]` of every layer buffer; returns the NEW kv
        list (functional, like page_stream). Waits out in-flight
        spills of the requested slots first."""
        import jax.numpy as jnp
        self.wait_landed(slots)
        n = len(slots)
        spans = _chunk_spans(n, 1, self.chunk_pages) or [(0, n)]
        dst_idx = jnp.asarray(list(device_pages), jnp.int32)
        t0 = time.perf_counter()
        out = []
        nbytes = 0
        for li, layer in enumerate(kv):
            bufs = []
            for bi, d in enumerate(layer):
                host = self._buffers[li][bi]
                for (st, w) in spans:
                    rows = np.stack([host[slots[st + j]]
                                     for j in range(w)])
                    d = d.at[dst_idx[st:st + w]].set(
                        jnp.asarray(rows))
                nbytes += n * self._page_bytes(host)
                bufs.append(d)
            out.append(tuple(bufs))
        dt = time.perf_counter() - t0
        with self._lock:
            self.fetched_pages += n
            self.fetched_bytes += nbytes
            self._wall_s += dt
        _count_transfer('fetched', n, nbytes)
        return out

    # -- accounting ----------------------------------------------------------
    def take_wall(self):
        """Pop the accumulated transfer wall (spill + fetch seconds)
        — the engine attributes it to the serve ledger's page_stream
        component once per step."""
        with self._lock:
            w, self._wall_s = self._wall_s, 0.0
        return w

    def drain(self):
        """Block until every queued spill job has landed (tests,
        shutdown). The per-slot landed events already give completion,
        so drain just waits out the pending ones."""
        with self._lock:
            pending = [ev for ev in self._landed.values()
                       if not ev.is_set()]
        for ev in pending:
            ev.wait()

    def stats(self):
        with self._lock:
            return {
                'tier_host_pages': self.host_pages,
                'tier_host_used_pages': self.used_slots,
                'tier_spilled_pages_total': self.spilled_pages,
                'tier_spilled_bytes_total': self.spilled_bytes,
                'tier_fetched_pages_total': self.fetched_pages,
                'tier_fetched_bytes_total': self.fetched_bytes,
                'tier_spill_jobs_total': self.spill_jobs,
            }

    def shutdown(self):
        self.drain()
        if self._thread is not None:
            self._jobs.put(None)
            self._thread.join(timeout=5)
            self._thread = None
        self._buffers = None
