"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's API.

Top-level namespace parity: python/paddle/__init__.py of the reference
(sandyhouse/Paddle ~v2.1). Eager tensors + autograd tape over jax.vjp; jitted
functional train steps for performance; XLA collectives for distribution.
"""
__version__ = '0.1.0'

# persistent XLA compilation cache (docs/performance.md): no-op unless
# PTPU_COMPILE_CACHE_DIR is set; must run before the first jit compile
from .core import compile_cache as _compile_cache
_compile_cache.enable_from_env()

from .core import dtypes as _dtypes_mod
from .core.dtypes import (bool_ as bool, uint8, int8, int16, int32, int64,  # noqa
                          float16, bfloat16, float32, float64, complex64,
                          complex128)
from .core.tensor import Tensor, to_tensor, _install_operators
from .core import autograd as _autograd
from .core.autograd import no_grad, enable_grad
from .core.lazy import lazy_guard
from .core.rng import seed, get_rng_state, set_rng_state

from . import ops
_install_operators()

# ---- re-export op surface at paddle.* level --------------------------------
from .ops.math import (  # noqa
    add, subtract, multiply, divide, floor_divide, remainder, mod, pow,
    maximum, minimum, fmax, fmin, exp, expm1, log, log2, log10, log1p, sqrt,
    rsqrt, square, abs, sign, floor, ceil, round, trunc, reciprocal, neg, sin,
    cos, tan, asin, acos, atan, sinh, cosh, tanh, asinh, acosh, atanh, atan2,
    erf, lgamma, digamma, scale, clip, increment, stanh, matmul, bmm, mm, dot,
    inner, outer, kron, cross, mv, addmm, sum, mean, max, min, prod, amax,
    amin, nansum, nanmean, logsumexp, all, any, std, var, median, mode,
    quantile, cumsum, cumprod, argmax, argmin, argsort, sort, topk, nonzero,
    equal, not_equal, less_than, less_equal, greater_than, greater_equal,
    equal_all, allclose, isclose, logical_and, logical_or, logical_xor,
    logical_not, bitwise_and, bitwise_or, bitwise_xor, bitwise_not, isnan,
    isinf, isfinite, nan_to_num, norm, dist, where, multiplex, trace, diag,
    diag_embed, lerp, frac, rad2deg, deg2rad, gcd, lcm, count_nonzero,
    heaviside, histogram, broadcast_shape, clip_by_norm, sigmoid,
)
from .ops.manip import (  # noqa
    cast, reshape, transpose, moveaxis, swapaxes, squeeze, unsqueeze, flatten,
    concat, stack, split, chunk, unstack, unbind, tile, expand, expand_as,
    broadcast_to, broadcast_tensors, flip, roll, rot90, gather, gather_nd,
    take_along_axis, put_along_axis, scatter, scatter_nd, scatter_nd_add,
    index_select, index_sample, masked_select, slice, strided_slice, tril,
    triu, diagonal, unique, unique_consecutive, one_hot, shard_index,
    meshgrid, repeat_interleave, as_complex, as_real, real, imag, numel,
    shape, masked_fill,
)
from .ops.creation import (  # noqa
    zeros, ones, full, empty, zeros_like, ones_like, full_like, empty_like,
    arange, linspace, logspace, eye, assign, clone, diagflat, complex,
    uniform, rand, randn, normal, standard_normal, randint, randint_like,
    randperm, bernoulli, poisson, multinomial, gaussian,
)
from .ops import linalg  # noqa
from .ops.linalg import einsum  # noqa

from . import nn
from . import optimizer
from . import amp
from . import io
from . import metric
from . import vision
from . import autograd
from . import jit
from . import static
from . import distributed
from .distributed import DataParallel   # parity: paddle.DataParallel
from . import device
from . import framework
from . import utils
from . import incubate
from . import hapi
from .hapi import Model
from .framework import (save, load, get_default_dtype, set_default_dtype,
                        set_grad_enabled, is_grad_enabled, grad, in_dynamic_mode,
                        CPUPlace, CUDAPlace, TPUPlace, set_device, get_device)
from .nn.layer.common import ParamAttr
from .jit import to_static

# paddle.disable_static / enable_static no-ops (dygraph is the default mode)
from .static import enable_static, disable_static, in_static_mode  # noqa

flops = lambda *a, **k: 0


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_tpu():
    return True


def summary(net, input_size=None, dtypes=None):
    from .hapi.summary import summary as _summary
    return _summary(net, input_size, dtypes)
from . import text  # noqa: E402
from . import profiler  # noqa: E402
from . import models  # noqa: E402
from .ops import fft  # noqa: E402
from .ops.math import (  # noqa: E402
    bincount, bucketize, searchsorted, take, tensordot, logcumsumexp,
    renorm, diff, trapezoid, vander, angle, conj, polar, crop)
from .core.flags import set_flags, get_flags  # noqa: E402
from . import distribution  # noqa: E402
from . import regularizer  # noqa: E402
from . import version  # noqa: E402


def get_cudnn_version():
    return None

from .api_tail import (add_n, floor_mod, inverse, t, is_tensor,  # noqa
                       is_empty, rank, reverse, scatter_,
                       set_printoptions, batch, get_cuda_rng_state,
                       set_cuda_rng_state, CUDAPinnedPlace, NPUPlace,
                       cholesky, create_parameter, check_shape,
                       tanh_, reshape_, squeeze_, unsqueeze_)
from .core import dtypes as dtype  # noqa — paddle.dtype namespace
from . import inference  # noqa
from . import sysconfig  # noqa
from . import onnx  # noqa
