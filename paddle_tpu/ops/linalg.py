"""Linear-algebra ops.

Reference parity: operators/ cholesky, inverse, matmul family, bilinear ops
(SURVEY.md Appendix B) + python/paddle/tensor/linalg.py surface.
"""
import jax
import jax.numpy as jnp

from .common import as_tensor
from ..core.autograd import run_op
from ..core.tensor import Tensor


def cholesky(x, upper=False, name=None):
    x = as_tensor(x)
    def fn(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L
    return run_op('cholesky', fn, [x])


def inverse(x, name=None):
    x = as_tensor(x)
    return run_op('inverse', jnp.linalg.inv, [x])


def matrix_power(x, n, name=None):
    x = as_tensor(x)
    return run_op('matrix_power', lambda a: jnp.linalg.matrix_power(a, n), [x])


def matrix_rank(x, tol=None, hermitian=False):
    x = as_tensor(x)
    return Tensor(jnp.linalg.matrix_rank(x.data, tol=tol))


def det(x):
    x = as_tensor(x)
    return run_op('determinant', jnp.linalg.det, [x])


def slogdet(x):
    x = as_tensor(x)
    sign, logdet = jnp.linalg.slogdet(x.data)
    return Tensor(jnp.stack([sign, logdet]))


def svd(x, full_matrices=False):
    x = as_tensor(x)
    u, s, vh = jnp.linalg.svd(x.data, full_matrices=full_matrices)
    return Tensor(u), Tensor(s), Tensor(jnp.swapaxes(vh, -1, -2))


def qr(x, mode='reduced'):
    x = as_tensor(x)
    q, r = jnp.linalg.qr(x.data, mode=mode)
    return Tensor(q), Tensor(r)


def eig(x):
    x = as_tensor(x)
    w, v = jnp.linalg.eig(jax.device_get(x.data))
    return Tensor(w), Tensor(v)


def eigh(x, UPLO='L'):
    x = as_tensor(x)
    w, v = jnp.linalg.eigh(x.data, symmetrize_input=True)
    return Tensor(w), Tensor(v)


def eigvals(x):
    x = as_tensor(x)
    return Tensor(jnp.linalg.eigvals(jax.device_get(x.data)))


def eigvalsh(x, UPLO='L'):
    x = as_tensor(x)
    return Tensor(jnp.linalg.eigvalsh(x.data))


def pinv(x, rcond=1e-15, hermitian=False):
    x = as_tensor(x)
    return run_op('pinv', lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), [x])


def solve(x, y):
    x, y = as_tensor(x), as_tensor(y)
    return run_op('solve', jnp.linalg.solve, [x, y])


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    x, y = as_tensor(x), as_tensor(y)
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return run_op('triangular_solve', fn, [x, y])


def lstsq(x, y, rcond=None):
    x, y = as_tensor(x), as_tensor(y)
    sol, res, rank, sv = jnp.linalg.lstsq(x.data, y.data, rcond=rcond)
    return Tensor(sol), Tensor(res), Tensor(rank), Tensor(sv)


def lu(x, pivot=True):
    x = as_tensor(x)
    lu_, piv = jax.scipy.linalg.lu_factor(x.data)
    return Tensor(lu_), Tensor(piv.astype(jnp.int32) + 1)


def cholesky_solve(x, y, upper=False):
    x, y = as_tensor(x), as_tensor(y)
    def fn(b, chol):
        return jax.scipy.linalg.cho_solve((chol, not upper), b)
    return run_op('cholesky_solve', fn, [x, y])


def cond(x, p=None):
    x = as_tensor(x)
    return Tensor(jnp.linalg.cond(x.data, p=p))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    x = as_tensor(x)
    return Tensor(jnp.cov(x.data, rowvar=rowvar, ddof=1 if ddof else 0))


def corrcoef(x, rowvar=True):
    x = as_tensor(x)
    return Tensor(jnp.corrcoef(x.data, rowvar=rowvar))


def bilinear_tensor_product(x, y, weight, bias=None):
    """Parity: operators/bilinear_tensor_product_op."""
    x, y, weight = as_tensor(x), as_tensor(y), as_tensor(weight)
    tensors = [x, y, weight]
    if bias is not None:
        tensors.append(as_tensor(bias))
    def fn(a, b, w, *rest):
        out = jnp.einsum('bi,oij,bj->bo', a, w, b)
        if rest:
            out = out + rest[0]
        return out
    return run_op('bilinear_tensor_product', fn, tensors)


def einsum(equation, *operands):
    tensors = [as_tensor(o) for o in operands]
    return run_op('einsum', lambda *arrs: jnp.einsum(equation, *arrs), tensors)


def histogramdd(*a, **k):
    raise NotImplementedError


def inv(x, name=None):
    """paddle.linalg.inv (operators/inverse_op.cc)."""
    from ..core.autograd import run_op
    import jax.numpy as jnp
    from .common import as_tensor
    return run_op('inverse', jnp.linalg.inv, [as_tensor(x)])
