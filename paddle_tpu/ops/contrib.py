"""Remaining tier-2/3 op families (fluid.layers surface).

Reference parity: operators/ nce_op.cc, hierarchical_sigmoid_op.cc,
unpool_op.cc, im2sequence_op.cc, spp_op.cc, row_conv_op.cc,
spectral_norm_op.cc (VERDICT r2 missing #1 / Appendix B remainder).

TPU-native: each op is one fixed-shape jnp program — candidate sampling
uses the functional RNG stream; hsigmoid walks the complete binary tree
with a static-length (ceil(log2 C)) vectorized path instead of the
reference's per-sample host loops; im2sequence rides
conv_general_dilated_patches (the MXU-friendly patch extractor).
"""
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .common import as_tensor
from ..core import rng
from ..core.autograd import run_op
from ..core.tensor import Tensor


def nce(input, label, num_total_classes, weight, bias=None,
        num_neg_samples=5, sampler='uniform', name=None):
    """Parity: operators/nce_op.cc — noise-contrastive estimation loss.
    input [N, D], label [N] or [N, 1] int, weight [C, D], bias [C] →
    cost [N, 1]. Negatives drawn per batch from the uniform sampler (the
    reference's default); loss = -log σ(s_pos) − Σ_neg log σ(−s_neg)."""
    if sampler != 'uniform':
        raise NotImplementedError(f"nce sampler {sampler!r} (uniform only)")
    input, label, weight = (as_tensor(input), as_tensor(label),
                            as_tensor(weight))
    tensors = [input, weight]
    has_bias = bias is not None
    if has_bias:
        tensors.append(as_tensor(bias))
    tensors.append(label)
    key = rng.next_key()
    k_neg = int(num_neg_samples)

    def fn(*args):
        x, w = args[0], args[1]
        b = args[2] if has_bias else None
        lb = args[-1].reshape(-1).astype(jnp.int32)
        N = x.shape[0]
        neg = jax.random.randint(key, (N, k_neg), 0, num_total_classes)
        pos_w = w[lb]                                   # [N, D]
        s_pos = jnp.sum(x * pos_w, -1)                  # [N]
        neg_w = w[neg]                                  # [N, k, D]
        s_neg = jnp.einsum('nd,nkd->nk', x, neg_w)
        if b is not None:
            s_pos = s_pos + b[lb]
            s_neg = s_neg + b[neg]
        # sample-prob correction (uniform q = k/C, nce_op.cc):
        logq = jnp.log(jnp.asarray(k_neg / num_total_classes,
                                   jnp.float32))
        pos = jax.nn.log_sigmoid(s_pos - logq)
        negl = jax.nn.log_sigmoid(-(s_neg - logq)).sum(-1)
        return (-(pos + negl))[:, None]
    return run_op('nce', fn, tensors, n_nondiff=1)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Parity: operators/hierarchical_sigmoid_op.cc — complete-binary-tree
    hierarchical softmax. input [N, D], label [N], weight [C-1, D],
    bias [C-1] → loss [N, 1]. Custom trees via path_table/path_code
    [N, L] (MatchTableByPath role)."""
    input, label, weight = (as_tensor(input), as_tensor(label),
                            as_tensor(weight))
    tensors = [input, weight]
    has_bias = bias is not None
    if has_bias:
        tensors.append(as_tensor(bias))
    tensors.append(label)
    custom = path_table is not None
    if custom:
        tensors.append(as_tensor(path_table))
        tensors.append(as_tensor(path_code))
    L = int(math.ceil(math.log2(max(num_classes, 2))))

    def fn(*args):
        x, w = args[0], args[1]
        b = args[2] if has_bias else None
        if custom:
            lb = args[-3].reshape(-1).astype(jnp.int32)
            table = args[-2].astype(jnp.int32)          # [N, L]
            code = args[-1].astype(jnp.float32)         # [N, L]
            valid = (table >= 0).astype(jnp.float32)
            nodes = jnp.maximum(table, 0)
        else:
            lb = args[-1].reshape(-1).astype(jnp.int32)
            # complete binary tree: leaf id = label + C; walk to the root
            # (node 1); internal node n stores row n-1
            node = lb + num_classes
            nodes_l, codes_l = [], []
            for _ in range(L):
                parent = node // 2
                codes_l.append((node % 2).astype(jnp.float32))
                nodes_l.append(parent - 1)
                node = parent
            nodes = jnp.stack(nodes_l, 1)               # [N, L]
            code = jnp.stack(codes_l, 1)
            valid = (nodes + 1 >= 1).astype(jnp.float32) \
                * (nodes + 1 <= num_classes - 1).astype(jnp.float32)
            nodes = jnp.clip(nodes, 0, max(num_classes - 2, 0))
        wr = w[nodes]                                   # [N, L, D]
        logits = jnp.einsum('nd,nld->nl', x, wr)
        if b is not None:
            logits = logits + b[nodes]
        # BCE against the path code: -[c·log σ(z) + (1−c)·log σ(−z)]
        loss = -(code * jax.nn.log_sigmoid(logits)
                 + (1 - code) * jax.nn.log_sigmoid(-logits))
        return jnp.sum(loss * valid, -1, keepdims=True)
    return run_op('hierarchical_sigmoid', fn, tensors,
                  n_nondiff=(3 if custom else 1))


def unpool(x, indices, kernel_size, stride=None, padding=0,
           output_size=None, data_format='NCHW', name=None):
    """Parity: operators/unpool_op.cc — max-unpool2d: scatter each pooled
    value back to the argmax position recorded by max_pool2d
    (return_mask=True). indices are flat per-channel-map positions."""
    x, indices = as_tensor(x), as_tensor(indices)
    ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
        else (kernel_size, kernel_size)
    st = stride if stride is not None else ks
    st = st if isinstance(st, (list, tuple)) else (st, st)
    pd = padding if isinstance(padding, (list, tuple)) \
        else (padding, padding)

    def fn(a, idx):
        N, C, H, W = a.shape
        if output_size is not None:
            Ho, Wo = output_size[-2], output_size[-1]
        else:
            Ho = (H - 1) * st[0] - 2 * pd[0] + ks[0]
            Wo = (W - 1) * st[1] - 2 * pd[1] + ks[1]
        flat = jnp.zeros((N, C, Ho * Wo), a.dtype)
        ii = idx.reshape(N, C, H * W).astype(jnp.int32)
        out = flat.at[
            jnp.arange(N)[:, None, None],
            jnp.arange(C)[None, :, None], ii].set(
                a.reshape(N, C, H * W))
        return out.reshape(N, C, Ho, Wo)
    return run_op('unpool', fn, [x, indices], n_nondiff=1)


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    """Parity: operators/im2sequence_op.cc — sliding k×k patches become a
    sequence: [N, C, H, W] → [N * out_h * out_w, C * kh * kw] (row-major
    over output positions, the LoD the reference emits becomes the
    leading dim factorization)."""
    input = as_tensor(input)
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    st = stride if isinstance(stride, (list, tuple)) else (stride, stride)
    pd = padding if isinstance(padding, (list, tuple)) \
        else (padding, padding, padding, padding)
    if len(pd) == 2:
        pd = (pd[0], pd[0], pd[1], pd[1])

    def fn(a):
        N, C = a.shape[0], a.shape[1]
        patches = lax.conv_general_dilated_patches(
            a, ks, st, [(pd[0], pd[1]), (pd[2], pd[3])],
            dimension_numbers=('NCHW', 'OIHW', 'NCHW'),
            precision=lax.Precision.HIGHEST)   # exact on TPU (bf16 default
        #                                        would round the values)
        # [N, C*kh*kw, oh, ow] → [N*oh*ow, C*kh*kw]
        Np, CK, oh, ow = patches.shape
        return patches.transpose(0, 2, 3, 1).reshape(N * oh * ow, CK)
    return run_op('im2sequence', fn, [input])


def spp(input, pyramid_height=3, pool_type='max', name=None):
    """Parity: operators/spp_op.cc — spatial pyramid pooling: levels
    l=0..h-1 adaptively pool to 2^l x 2^l bins; concat flattened bins →
    [N, C * Σ 4^l]."""
    from . import nn_ops as F
    input = as_tensor(input)
    outs = []
    for l in range(pyramid_height):
        bins = 2 ** l
        if pool_type == 'max':
            p = F.adaptive_max_pool2d(input, bins)
        else:
            p = F.adaptive_avg_pool2d(input, bins)
        from . import manip
        outs.append(manip.reshape(p, [p.shape[0], -1]))
    from . import manip
    return manip.concat(outs, axis=1)


def row_conv(input, weight, name=None):
    """Parity: operators/row_conv_op.cc — lookahead (row) convolution for
    streaming models: out[:, t] = Σ_{i<k, t+i<T} x[:, t+i] * w[i].
    input [N, T, D], weight [k, D]."""
    input, weight = as_tensor(input), as_tensor(weight)

    def fn(a, w):
        k = w.shape[0]
        T = a.shape[1]
        out = jnp.zeros_like(a)
        for i in range(k):
            seg = a[:, i:, :] * w[i][None, None, :]
            out = out.at[:, :T - i, :].add(seg)
        return out
    return run_op('row_conv', fn, [input, weight])


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, u=None, v=None,
                  name=None):
    """Parity: operators/spectral_norm_op.cc — normalize the weight by its
    largest singular value via `power_iters` rounds of power iteration
    (fresh-start u when no state is passed, like the op's Input(U))."""
    weight = as_tensor(weight)
    tensors = [weight]
    if u is not None:
        tensors.append(as_tensor(u))
    key = rng.next_key()

    def fn(*args):
        w = args[0]
        mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        h, wdim = mat.shape
        uu = args[1].reshape(h) if len(args) > 1 else \
            jax.random.normal(key, (h,), jnp.float32)
        vv = None
        for _ in range(max(power_iters, 1)):
            vv = mat.T @ uu
            vv = vv / (jnp.linalg.norm(vv) + eps)
            uu = mat @ vv
            uu = uu / (jnp.linalg.norm(uu) + eps)
        sigma = uu @ mat @ vv
        return w / sigma
    return run_op('spectral_norm', fn, tensors)


# ---------------------------------------------------------------------------
# misc functional tail (VERDICT r3 missing #4 — remaining op families)
# ---------------------------------------------------------------------------

def center_loss(input, label, num_classes, alpha=0.5, centers=None,
                update_center=True):
    """center_loss_op.cc: loss_i = 0.5 * ||x_i - c_{y_i}||^2; centers
    move toward their class means by alpha * mean-residual. Returns
    (loss [N, 1], new_centers [C, D]); centers default to zeros
    [num_classes, D]."""
    if centers is None:
        d = as_tensor(input).data.shape[-1]
        centers = jnp.zeros((num_classes, d), jnp.float32)
    elif as_tensor(centers).data.shape[0] != num_classes:
        raise ValueError(
            f"centers has {as_tensor(centers).data.shape[0]} rows but "
            f"num_classes={num_classes}")

    def fn(x, c, y, _alpha=alpha, _upd=update_center):
        y = y.reshape(-1).astype(jnp.int32)
        cy = c[y]
        diff = x - cy
        loss = 0.5 * jnp.sum(diff * diff, axis=1, keepdims=True)
        if _upd:
            # residual-mean per class (reference divides by count + 1)
            cnt = jnp.zeros((c.shape[0],), jnp.float32).at[y].add(1.0)
            acc = jnp.zeros_like(c).at[y].add(diff)
            c = c + _alpha * acc / (cnt[:, None] + 1.0)
        return loss, c
    out = run_op('center_loss', fn,
                 [as_tensor(input), as_tensor(centers), as_tensor(label)],
                 n_nondiff=1)
    return out


def hash_op(x, num_hash=1, mod_by=1 << 20):
    """hash_op.cc: int ids → num_hash hashed buckets in [0, mod_by)
    (reference uses XXH64 per hash seed; here a Knuth-style mixing hash —
    same contract, traceable on device)."""
    def fn(ids, _n=num_hash, _m=mod_by):
        v = ids.astype(jnp.uint32).reshape(ids.shape + (1,))
        seeds = (jnp.arange(1, _n + 1, dtype=jnp.uint32)
                 * jnp.uint32(0x9E3779B1))
        h = v * seeds + jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 15)
        h = h * jnp.uint32(0x2545F491)
        h = h ^ (h >> 13)
        return (h % jnp.uint32(_m)).astype(jnp.int64)
    return run_op('hash', fn, [as_tensor(x)])


def ctc_align(input, blank=0, lengths=None, padding_value=0):
    """ctc_align_op: collapse repeats then drop blanks, left-packed and
    padded with padding_value (dense [B, L] form of the LoD op)."""
    x = as_tensor(input)

    def fn(ids, _b=blank, _p=padding_value):
        B, L = ids.shape
        prev = jnp.concatenate(
            [jnp.full((B, 1), -1, ids.dtype), ids[:, :-1]], axis=1)
        keep = (ids != prev) & (ids != _b)
        if lengths is not None:
            lens = as_tensor(lengths).data.reshape(-1, 1)
            keep = keep & (jnp.arange(L)[None, :] < lens)
        pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
        out = jnp.full((B, L), _p, ids.dtype)
        rows = jnp.broadcast_to(jnp.arange(B)[:, None], (B, L))
        tgt = jnp.where(keep, pos, L)          # dropped when not kept
        out = out.at[rows, tgt].set(ids, mode='drop')
        out_len = keep.sum(axis=1)
        return out, out_len
    return run_op('ctc_align', fn, [x])


def conv_shift(x, y):
    """conv_shift_op: circular correlation — out[b, i] =
    Σ_j x[b, (i + j - N//2) mod M] * y[b, j]."""
    def fn(xa, ya):
        B, M = xa.shape
        N = ya.shape[1]
        half = N // 2
        idx = (jnp.arange(M)[:, None] + jnp.arange(N)[None, :]
               - half) % M
        return jnp.einsum('bmn,bn->bm', xa[:, idx], ya)
    return run_op('conv_shift', fn, [as_tensor(x), as_tensor(y)])


def is_empty(x):
    """is_empty_op: numel == 0."""
    xa = as_tensor(x)
    return Tensor(jnp.asarray(int(np.prod(xa.data.shape)) == 0))


def assign_value(shape, dtype, values):
    """assign_value_op: constant tensor from attribute values."""
    return Tensor(jnp.asarray(np.array(values, dtype).reshape(shape)))


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=False,
                     out_val_if_empty=0):
    """filter_by_instag_op: keep rows whose tag set intersects
    filter_tag; dense form returns (rows left-packed + padded, index map,
    loss weight mask)."""
    x = _np_arr(ins)
    tags = _np_arr(ins_tag)
    want = set(int(t) for t in _np_arr(filter_tag).reshape(-1))
    keep = [i for i in range(x.shape[0])
            if set(int(t) for t in np.atleast_1d(tags[i])) & want]
    if keep:
        out = x[keep]
        idx = np.asarray(keep, np.int64)
        w = np.ones((len(keep), 1), np.float32)
    else:                       # reference emits one dummy row
        out = np.full((1,) + x.shape[1:], out_val_if_empty, x.dtype)
        idx = np.zeros((1,), np.int64)
        w = np.zeros((1, 1), np.float32)
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(idx)), \
        Tensor(jnp.asarray(w))


def chunk_eval(infer, label, chunk_scheme='IOB', num_chunk_types=1,
               lengths=None, excluded_chunk_types=()):
    """chunk_eval_op: chunk-level precision/recall/F1 for sequence
    labeling (host-side metric, numpy — like the reference CPU kernel).
    Tag layout per the reference: tag = chunk_type * tag_num + tag_pos
    with IOB: B=0, I=1."""
    inf = _np_arr(infer)
    lab = _np_arr(label)
    lens = _np_arr(lengths).reshape(-1) if lengths is not None \
        else np.full(inf.shape[0], inf.shape[1], np.int64)
    if chunk_scheme != 'IOB':
        raise NotImplementedError("chunk_eval: IOB scheme only")

    def chunks(seq):
        out = []
        start, ctype = None, None
        for i, t in enumerate(seq):
            t = int(t)
            ct, pos = divmod(t, 2)
            if ct >= num_chunk_types:           # O / out-of-chunk tag
                if start is not None:
                    out.append((start, i - 1, ctype))
                start, ctype = None, None
            elif pos == 0:                      # B — chunk starts
                if start is not None:
                    out.append((start, i - 1, ctype))
                start, ctype = i, ct
            elif pos == 1 and start is not None and ct == ctype:
                continue                        # I — extends
            else:                               # broken I
                if start is not None:
                    out.append((start, i - 1, ctype))
                start, ctype = None, None
        if start is not None:
            out.append((start, len(seq) - 1, ctype))
        return {c for c in out if c[2] not in excluded_chunk_types}

    n_inf = n_lab = n_correct = 0
    for b in range(inf.shape[0]):
        L = int(lens[b])
        ci = chunks(inf[b, :L])
        cl = chunks(lab[b, :L])
        n_inf += len(ci)
        n_lab += len(cl)
        n_correct += len(ci & cl)
    p = n_correct / n_inf if n_inf else 0.0
    r = n_correct / n_lab if n_lab else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    return (Tensor(jnp.asarray(p, jnp.float32)),
            Tensor(jnp.asarray(r, jnp.float32)),
            Tensor(jnp.asarray(f1, jnp.float32)),
            Tensor(jnp.asarray(n_inf)), Tensor(jnp.asarray(n_lab)),
            Tensor(jnp.asarray(n_correct)))


def _np_arr(x):
    import numpy as _np
    return _np.asarray(x.data if isinstance(x, Tensor) else x)


def sampled_softmax_with_cross_entropy(logits=None, label=None,
                                       num_samples=None, seed=0,
                                       remove_accidental_hits=True, *,
                                       input=None, weight=None,
                                       bias=None):
    """sampled_softmax_with_cross_entropy_op (reference signature:
    logits [N, C], label [N, 1], num_samples): softmax xent over the
    true class + num_samples UNIQUE uniformly sampled negatives instead
    of the full class set. The keyword form (input [N, D] features,
    weight [C, D], bias [C]) skips materializing full logits — the
    sampled-FC variant for large vocabularies.

    Negatives resample EVERY call from the functional RNG stream
    (paddle.seed-reproducible); pass seed!=0 to pin a fixed draw."""
    fc_mode = logits is None
    if fc_mode:
        x = as_tensor(input)
        w = as_tensor(weight)
        C = w.data.shape[0]
    else:
        x = as_tensor(logits)
        C = x.data.shape[1]
    lb = as_tensor(label)
    S = min(int(num_samples), C)
    key = jax.random.PRNGKey(seed) if seed else rng.next_key()
    has_b = bias is not None
    tensors = [x] + ([w] if fc_mode else []) \
        + ([as_tensor(bias)] if has_b else []) + [lb]

    def fn(xa, *rest):
        wa = rest[0] if fc_mode else None
        ba = rest[1 if fc_mode else 0] if has_b else None
        y = rest[-1].reshape(-1).astype(jnp.int32)
        neg = jax.random.permutation(key, C)[:S].astype(jnp.int32)
        cls = jnp.concatenate(
            [y[:, None],
             jnp.broadcast_to(neg, (y.shape[0], S))], axis=1)  # [N,1+S]
        if fc_mode:
            wsel = wa[cls]                               # [N, 1+S, D]
            logit = jnp.einsum('nd,nsd->ns', xa, wsel)
            if ba is not None:
                logit = logit + ba[cls]
        else:
            logit = jnp.take_along_axis(xa, cls, axis=1)
        if remove_accidental_hits:
            # a sampled negative equal to the true class would cancel
            # the target logit — mask it out (reference semantics)
            hit = cls[:, 1:] == y[:, None]
            logit = jnp.concatenate(
                [logit[:, :1],
                 jnp.where(hit, -1e30, logit[:, 1:])], axis=1)
        lse = jax.nn.logsumexp(logit, axis=1, keepdims=True)
        return lse - logit[:, :1]
    return run_op('sampled_softmax_with_cross_entropy', fn, tensors,
                  n_nondiff=1)


# ---------------------------------------------------------------------------
# beam-search backtrace / metric / misc tier (VERDICT r3 op remainder)
# ---------------------------------------------------------------------------

def gather_tree(ids, parents):
    """gather_tree_op.cc — backtrace beam-search selections into full
    sequences. ids/parents: [T, B, W] int; reference semantics
    (fluid/layers/nn.py:14984): start from the last step's beams and walk
    parents backwards, gathering ids along the surviving paths.

    TPU-native: one reversed `lax.scan` over time with a per-(batch,beam)
    gather — no host loop, compiles to a single fused backtrace."""
    ids = as_tensor(ids)
    parents = as_tensor(parents, ref=ids)

    def fn(idv, par):
        T, B, W = idv.shape
        beams0 = jnp.broadcast_to(jnp.arange(W), (B, W))

        def body(beams, xs):
            id_t, par_t = xs           # [B, W] each, time t
            out_t = jnp.take_along_axis(id_t, beams, axis=1)
            nxt = jnp.take_along_axis(par_t, beams, axis=1)
            return nxt, out_t

        # t = T-1 down to 0; at each step gather ids at the current beam
        # set, then hop to those beams' parents for the step below
        _, outs = lax.scan(body, beams0, (idv[::-1], par[::-1]))
        return outs[::-1]
    return run_op('gather_tree', fn, [ids, parents], n_nondiff=2)


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """edit_distance_op.cc (oracle: test_edit_distance_op.py Levenshtein)
    — batched Levenshtein distance over dense padded token rows +
    lengths (the LoD-free contract, SURVEY N11 disposition).

    TPU-native DP: the row recurrence D[i][j] = min(D[i-1][j]+1,
    D[i][j-1]+1, D[i-1][j-1]+cost) has a sequential j-dependency only
    through a min-plus prefix scan: with a_j = min(D[i-1][j]+1,
    D[i-1][j-1]+cost_ij), D[i][j] = j + cummin(a_k - k)_j — one
    `lax.associative_scan` per row, `lax.scan` over rows, `vmap` over the
    batch. Returns (distances [B,1] float32, seq_num int64)."""
    input = as_tensor(input)
    label = as_tensor(label, ref=input)
    B, T1 = input.data.shape[0], input.data.shape[1]
    T2 = label.data.shape[1]
    if input_length is None:
        in_len = jnp.full((B,), T1, jnp.int32)
    else:
        in_len = as_tensor(input_length).data.reshape(-1).astype(jnp.int32)
    if label_length is None:
        lb_len = jnp.full((B,), T2, jnp.int32)
    else:
        lb_len = as_tensor(label_length).data.reshape(-1).astype(jnp.int32)
    ign = tuple(int(t) for t in (ignored_tokens or ()))

    def compact(row, ln, toks):
        # drop ignored tokens, keep order (stable sort on is-ignored)
        keep = jnp.ones(row.shape, bool)
        for t in toks:
            keep &= row != t
        keep &= jnp.arange(row.shape[0]) < ln
        order = jnp.argsort(~keep, stable=True)
        return row[order], keep.sum().astype(jnp.int32)

    def fn(hyp, ref):
        h_len, r_len = in_len, lb_len
        if ign:
            hyp, h_len = jax.vmap(lambda r, l: compact(r, l, ign))(hyp,
                                                                   h_len)
            ref, r_len = jax.vmap(lambda r, l: compact(r, l, ign))(ref,
                                                                   r_len)

        def one(h, r, m, n):
            jj = jnp.arange(T2 + 1, dtype=jnp.float32)
            row0 = jj                               # D[0][j] = j

            def step(prev, xs):
                hi, i = xs                          # hyp token, row index
                cost = jnp.where(hi == r, 0.0, 1.0)  # [T2]
                a = jnp.concatenate(
                    [jnp.asarray([i], jnp.float32),  # D[i][0] = i
                     jnp.minimum(prev[1:] + 1.0, prev[:-1] + cost)])
                row = jj + lax.associative_scan(jnp.minimum, a - jj)
                return row, row

            _, rows = lax.scan(
                step, row0, (h, jnp.arange(1, T1 + 1, dtype=jnp.float32)))
            rows = jnp.concatenate([row0[None], rows])  # [T1+1, T2+1]
            d = rows[m, n]
            # empty-string edge cases match the oracle: D(0,n)=n, D(m,0)=m
            return d

        d = jax.vmap(one)(hyp, ref, h_len, r_len)
        if normalized:
            d = d / jnp.maximum(r_len.astype(jnp.float32), 1.0)
        return d.reshape(B, 1).astype(jnp.float32)

    out = run_op('edit_distance', fn, [input, label], n_nondiff=2)
    return out, Tensor(jnp.asarray(np.int64(B)))


def mean_iou(input, label, num_classes):
    """mean_iou_op.cc (oracle: test_mean_iou.py compute_mean_iou) —
    semantic-segmentation mean intersection-over-union. correct[c] counts
    pred==label hits; wrong[c] counts both sides of each miss; per-class
    IOU = correct / (correct + wrong) averaged over classes seen.
    Returns (mean_iou f32 scalar, out_wrong i32 [C], out_correct i32 [C]).
    """
    input = as_tensor(input)
    label = as_tensor(label, ref=input)
    C = int(num_classes)

    def fn(pred, lab):
        pred = pred.reshape(-1).astype(jnp.int32)
        lab = lab.reshape(-1).astype(jnp.int32)
        hit = pred == lab
        correct = jnp.zeros((C,), jnp.int32).at[pred].add(
            hit.astype(jnp.int32))
        wrong = jnp.zeros((C,), jnp.int32).at[pred].add(
            (~hit).astype(jnp.int32)).at[lab].add((~hit).astype(jnp.int32))
        denom = wrong + correct
        valid = (denom != 0).sum()
        iou = correct / jnp.maximum(denom, 1)
        miou = (iou.sum() / jnp.maximum(valid, 1)).astype(jnp.float32)
        return miou, wrong, correct

    return run_op('mean_iou', fn, [input, label], n_nondiff=2)


def precision_recall(max_probs, indices, labels, cls_num, weights=None,
                     states=None):
    """precision_recall_op.cc (oracle: test_precision_recall_op.py) —
    streaming multi-class precision/recall/F1. Returns (batch_metrics [6]
    = [macro-P, macro-R, macro-F1, micro-P, micro-R, micro-F1],
    accum_metrics [6], accum_states [C,4] TP/FP/TN/FN), accumulating into
    `states` when given."""
    C = int(cls_num)
    tens = [as_tensor(indices), as_tensor(labels)]
    has_w = weights is not None
    has_st = states is not None
    if has_w:
        tens.append(as_tensor(weights))
    if has_st:
        tens.append(as_tensor(states))

    def fn(idx, lab, *rest):
        idx = idx.reshape(-1).astype(jnp.int32)
        lab = lab.reshape(-1).astype(jnp.int32)
        N = idx.shape[0]
        w = (rest[0].reshape(-1).astype(jnp.float32) if has_w
             else jnp.ones((N,), jnp.float32))
        hit = idx == lab
        tp = jnp.zeros((C,), jnp.float32).at[idx].add(
            jnp.where(hit, w, 0.0))
        fp = jnp.zeros((C,), jnp.float32).at[idx].add(
            jnp.where(hit, 0.0, w))
        fn_ = jnp.zeros((C,), jnp.float32).at[lab].add(
            jnp.where(hit, 0.0, w))
        # TN: every instance credits every class, minus those involved
        tn = jnp.full((C,), w.sum(), jnp.float32)
        tn = tn.at[idx].add(-w)
        tn = tn.at[lab].add(jnp.where(hit, 0.0, -w))
        batch_states = jnp.stack([tp, fp, tn, fn_], axis=1)  # [C,4]

        def metrics(st):
            tp_, fp_, fn2 = st[:, 0], st[:, 1], st[:, 3]

            def prec(t, f):
                return jnp.where(t + f > 0,
                                 t / jnp.maximum(t + f, 1e-30), 1.0)

            def f1(p, r):
                return jnp.where(p + r > 0, 2 * p * r /
                                 jnp.maximum(p + r, 1e-30), 0.0)
            mp = prec(tp_, fp_).mean()
            mr = prec(tp_, fn2).mean()
            tpt, fpt, fnt = tp_.sum(), fp_.sum(), fn2.sum()
            up = prec(tpt, fpt)
            ur = prec(tpt, fnt)
            return jnp.stack([mp, mr, f1(mp, mr), up, ur,
                              f1(up, ur)]).astype(jnp.float32)

        accum = batch_states if not has_st else (
            batch_states + rest[-1].astype(jnp.float32))
        return metrics(batch_states), metrics(accum), accum

    # MaxProbs participates only in shape checks in the reference kernel;
    # the states math keys off indices/labels/weights
    return run_op('precision_recall', fn, tens, n_nondiff=len(tens))


def positive_negative_pair(score, label, query, column=-1, weight=None,
                           acc_pos=None, acc_neg=None, acc_neu=None):
    """positive_negative_pair_op.cc (oracle:
    test_positive_negative_pair_op.py py_pnpair_op) — ranking-order
    statistics grouped by query id. All same-query (i, j) pairs with
    differing labels score pos/neg/neutral by whether the score order
    matches the label order; pair weight = (w_i + w_j) / 2.

    TPU-native: the reference's per-query hash-map + combinations loop is
    one [N, N] masked pairwise block (upper triangle, query-equality
    mask) — MXU-trivial and batch-parallel."""
    tens = [as_tensor(score), as_tensor(label), as_tensor(query)]
    has_w = weight is not None
    has_acc = acc_pos is not None
    if has_w:
        tens.append(as_tensor(weight))
    if has_acc:
        tens += [as_tensor(acc_pos), as_tensor(acc_neg),
                 as_tensor(acc_neu)]

    def fn(sc, lb, q, *rest):
        sc = sc[:, int(column)] if sc.ndim > 1 else sc
        lb = lb.reshape(-1).astype(jnp.float32)
        q = q.reshape(-1)
        N = sc.shape[0]
        w = (rest[0].reshape(-1).astype(jnp.float32) if has_w
             else jnp.ones((N,), jnp.float32))
        pair_mask = (q[:, None] == q[None, :]) & \
            (jnp.arange(N)[:, None] < jnp.arange(N)[None, :]) & \
            (lb[:, None] != lb[None, :])
        pw = (w[:, None] + w[None, :]) * 0.5
        ds = sc[:, None] - sc[None, :]
        dl = lb[:, None] - lb[None, :]
        neu = jnp.where(pair_mask & (ds == 0), pw, 0.0).sum()
        pos = jnp.where(pair_mask & (ds * dl > 0), pw, 0.0).sum()
        neg = jnp.where(pair_mask & (ds != 0) & (ds * dl <= 0),
                        pw, 0.0).sum()
        if has_acc:
            pos = pos + rest[-3].reshape(())
            neg = neg + rest[-2].reshape(())
            neu = neu + rest[-1].reshape(())
        return (pos.astype(jnp.float32), neg.astype(jnp.float32),
                neu.astype(jnp.float32))

    return run_op('positive_negative_pair', fn, tens, n_nondiff=len(tens))


def affine_channel(x, scale=None, bias=None, data_layout='NCHW', act=None):
    """affine_channel_op.cc (fluid/layers/nn.py:12691) — per-channel
    x * scale + bias, differentiable through all three inputs."""
    x = as_tensor(x)
    scale = as_tensor(scale, ref=x)
    bias = as_tensor(bias, ref=x)
    nchw = data_layout in ('NCHW', 'AnyLayout')

    def fn(xa, sa, ba):
        shape = ([1, -1] + [1] * (xa.ndim - 2)) if nchw else \
            ([1] * (xa.ndim - 1) + [-1])
        out = xa * sa.reshape(shape) + ba.reshape(shape)
        if act == 'relu':
            out = jnp.maximum(out, 0)
        elif act is not None:
            raise ValueError(f"unsupported act {act!r}")
        return out
    return run_op('affine_channel', fn, [x, scale, bias])


def row_hash(input, hash_size, num_hash=1, name=None):
    """hash_op.cc:30-63 — the fluid `hash` layer contract: hash each
    LAST-DIM row (n-gram) as a unit into `num_hash` buckets in
    [0, hash_size) (reference: XXH64(row_bytes, seed=i) % hash_size).
    Here a seeded polynomial rolling hash over per-element mixes — same
    row-as-unit/seed/mod contract, deterministic and well-mixed, and
    fully traceable on device (works inside recorded static programs;
    the reference's element-wise cousin is `hash_op` above).
    Output: [N, num_hash, 1] int like the reference kernel."""
    x = as_tensor(input)

    def fn(ids, _n=int(num_hash), _m=int(hash_size)):
        v = ids.astype(jnp.uint32)
        if v.ndim == 1:
            v = v[:, None]
        v = v.reshape(v.shape[0], -1)                 # [N, D]
        seeds = (jnp.arange(1, _n + 1, dtype=jnp.uint32)
                 * jnp.uint32(0x9E3779B1))            # [H]
        h = v[:, None, :] * seeds[None, :, None] + jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 15)
        h = h * jnp.uint32(0x2545F491)
        h = h ^ (h >> 13)                             # [N, H, D] mixes
        D = h.shape[-1]
        powers = jnp.power(jnp.uint32(31), jnp.arange(
            D - 1, -1, -1, dtype=jnp.uint32))         # rolling combine
        rowh = (h * powers).sum(axis=-1, dtype=jnp.uint32)
        return (rowh % jnp.uint32(_m)).astype(jnp.int32)[..., None]
    return run_op('hash', fn, [x], n_nondiff=1)


def sample_logits(logits, labels, num_samples, uniq=True,
                  remove_accidental_hits=True, seed=None):
    """sample_logits_op.cc (oracle: test_sample_logits_op.py) — sampled-
    softmax front half: draw `num_samples` negatives from the log-uniform
    (Zipfian) class distribution, gather logits at [true, sampled]
    columns, and subtract log Q(class) so downstream softmax_xent yields
    the sampled-softmax estimator. Returns (samples [B, NT+S] int,
    probabilities [B, NT+S] f32, sampled_logits [B, NT+S],
    sampled_labels [B, NT] = positions of the true classes).

    `uniq=True` (reference LogUniformSampler unique=true resamples until
    S distinct classes): here draws stay fixed-shape for XLA — duplicate
    negative columns beyond the first occurrence are masked out of the
    softmax (-1e20, like accidental hits) and Probabilities report the
    unique-sampling inclusion mass 1-(1-q)^S instead of q.
    `remove_accidental_hits` masks negatives equal to ANY of the row's
    true labels."""
    logits = as_tensor(logits)
    labels = as_tensor(labels, ref=logits)
    S = int(num_samples)
    key = rng.next_key() if seed is None else jax.random.PRNGKey(int(seed))
    NT = int(np.prod(labels.shape)) // int(labels.shape[0])

    def fn(lg, lb):
        B, C = lg.shape
        lb2 = lb.reshape(B, NT).astype(jnp.int32)
        logC1 = jnp.log(jnp.asarray(C + 1.0))
        u = jax.random.uniform(key, (S,))
        neg = jnp.floor(jnp.exp(u * logC1)).astype(jnp.int32) - 1
        neg = jnp.clip(neg, 0, C - 1)                 # shared across rows

        def q(c):                                     # log-uniform mass
            c = c.astype(jnp.float32)
            return (jnp.log(c + 2.0) - jnp.log(c + 1.0)) / logC1

        samples = jnp.concatenate(
            [lb2, jnp.broadcast_to(neg, (B, S))], axis=1)
        probs = q(samples)
        if uniq:
            # inclusion probability of unique sampling (the expected-
            # count adjustment the reference/TF samplers report)
            probs = -jnp.expm1(S * jnp.log1p(-jnp.clip(probs, 0, 0.999)))
        slog = jnp.take_along_axis(lg, samples, axis=1) \
            - jnp.log(jnp.where(probs > 0, probs, 1.0))
        dead = jnp.zeros((B, S), bool)
        if remove_accidental_hits:
            dead |= (samples[:, NT:, None] == lb2[:, None, :]).any(-1)
        if uniq:
            dup = neg[:, None] == neg[None, :]        # [S, S]
            first = jnp.argmax(dup, axis=1)           # first occurrence
            dead |= (first != jnp.arange(S))[None, :]
        slog = jnp.concatenate(
            [slog[:, :NT],
             jnp.where(dead, slog[:, NT:] - 1e20, slog[:, NT:])], axis=1)
        onk = jnp.broadcast_to(jnp.arange(NT, dtype=jnp.int32), (B, NT))
        return samples, probs.astype(jnp.float32), slog, onk

    return run_op('sample_logits', fn, [logits, labels], n_nondiff=1)


def polygon_box_transform(input, name=None):
    """polygon_box_transform_op.cc (oracle:
    test_polygon_box_transform.py PolygonBoxRestore) — EAST-style
    geometry decode: channel pairs hold (w, h) offsets on a 4px grid;
    out = grid_index * 4 - input."""
    input = as_tensor(input)

    def fn(x):
        B, G, H, W = x.shape
        wi = jnp.broadcast_to(jnp.arange(W), (H, W))
        hi = jnp.broadcast_to(jnp.arange(H)[:, None], (H, W))
        pair = jnp.stack([wi, hi])                    # [2, H, W]
        idx = jnp.tile(pair, (G // 2, 1, 1)).astype(x.dtype)
        return idx[None] * 4 - x
    return run_op('polygon_box_transform', fn, [input])


def random_crop(x, shape, seed=None):
    """random_crop_op.cc (fluid/layers/nn.py:8643) — per-instance random
    crop of the trailing dims to `shape`; one offset draw per instance
    from the functional RNG stream."""
    x = as_tensor(x)
    shape = tuple(int(s) for s in shape)
    key = rng.next_key() if seed is None else jax.random.PRNGKey(int(seed))

    def fn(arr):
        lead = arr.shape[:arr.ndim - len(shape)]
        tail = arr.shape[arr.ndim - len(shape):]
        flat = arr.reshape((-1,) + tail)
        keys = jax.random.split(key, flat.shape[0])

        def one(a, k):
            offs = [jax.random.randint(jax.random.fold_in(k, d), (),
                                       0, t - s + 1)
                    for d, (t, s) in enumerate(zip(tail, shape))]
            return lax.dynamic_slice(a, offs, shape)
        out = jax.vmap(one)(flat, keys)
        return out.reshape(lead + shape)
    return run_op('random_crop', fn, [x])


def bilateral_slice(x, guide, grid, has_offset=False):
    """bilateral_slice_op.cc/.cu (fluid/contrib/layers/nn.py:1499) — HDRNet
    grid slicing: per-pixel trilinear lookup into a low-res bilateral grid
    at (x, y, guide[x, y]), the sampled coefficients applied as a per-pixel
    affine map of the input channels.

    x [N, Cin, H, W], guide [N, H, W] in [0, 1], grid [N, Cg, D, Hg, Wg]
    with Cg = Cout*(Cin+1) when has_offset else Cout*Cin. TPU-native: the
    eight trilinear corners become eight dense gathers + weighted sums
    (one fused XLA program), not a scalar loop. The z tap weight uses the
    reference's smoothed hat max(1 - sqrt(dz^2 + 1e-8), 0)."""
    x, guide, grid = as_tensor(x), as_tensor(guide), as_tensor(grid)
    has_offset = bool(has_offset)

    def fn(xa, ga, gr):
        N, Cin, H, W = xa.shape
        _, Cg, D, Hg, Wg = gr.shape
        stride = Cin + 1 if has_offset else Cin
        if Cg % stride:
            raise ValueError(
                f"grid channels {Cg} not divisible by Cin"
                f"{'+1' if has_offset else ''}={stride}")
        Cout = Cg // stride
        f32 = jnp.float32
        gx = (jnp.arange(W, dtype=f32) + 0.5) * (Wg / W)      # [W]
        gy = (jnp.arange(H, dtype=f32) + 0.5) * (Hg / H)      # [H]
        gz = ga.astype(f32) * D                               # [N, H, W]
        fx = jnp.floor(gx - 0.5)
        fy = jnp.floor(gy - 0.5)
        fz = jnp.floor(gz - 0.5)
        # grid in gather-friendly layout: [N, D, Hg, Wg, Cg]
        grt = jnp.transpose(gr, (0, 2, 3, 4, 1)).astype(f32)
        bb = jnp.arange(N)[:, None, None]
        acc = jnp.zeros((N, H, W, Cg), f32)
        for dz in range(2):
            zz = fz + dz
            wz = jnp.maximum(
                1.0 - jnp.sqrt((zz + 0.5 - gz) ** 2 + 1e-8), 0.0)
            zi = jnp.clip(zz, 0, D - 1).astype(jnp.int32)
            for dy in range(2):
                yy = fy + dy
                wy = jnp.maximum(1.0 - jnp.abs(yy + 0.5 - gy), 0.0)
                yi = jnp.clip(yy, 0, Hg - 1).astype(jnp.int32)
                for dx in range(2):
                    xx = fx + dx
                    wx = jnp.maximum(1.0 - jnp.abs(xx + 0.5 - gx), 0.0)
                    xi = jnp.clip(xx, 0, Wg - 1).astype(jnp.int32)
                    corner = grt[bb, zi,
                                 yi[None, :, None], xi[None, None, :]]
                    w = (wz * wy[None, :, None] * wx[None, None, :])
                    acc = acc + corner * w[..., None]
        # [N, H, W, Cout, stride]: affine coeffs per output channel
        co = acc.reshape(N, H, W, Cout, stride)
        xin = jnp.transpose(xa, (0, 2, 3, 1)).astype(f32)     # [N,H,W,Cin]
        val = jnp.einsum('nhwoc,nhwc->nhwo', co[..., :Cin], xin)
        if has_offset:
            val = val + co[..., Cin]
        return jnp.transpose(val, (0, 3, 1, 2)).astype(xa.dtype)
    return run_op('bilateral_slice', fn, [x, guide, grid])


def correlation(x, y, pad_size, kernel_size, max_displacement,
                stride1=1, stride2=1, corr_type_multiply=1):
    """correlation_op.cc/.cu (fluid/contrib/layers/nn.py:1562) — FlowNet
    cost volume: for every displacement (k, l) in the (2d+1)^2 window,
    the mean over a kernel_size^2 x C patch of x * shifted(y).

    Output [N, (2d+1)^2, H, W], channel index l+d + (2d+1)*(k+d). The
    displacement loop is a static Python unroll — (2d+1)^2 dense
    elementwise-mul + window-mean ops that XLA fuses; no gather/scatter.
    stride1/stride2 > 1 subsample query pixels/displacements on CUDA;
    this build keeps the dense stride-1 form and raises loudly otherwise.
    """
    if stride1 != 1 or stride2 != 1:
        raise NotImplementedError(
            "correlation: stride1/stride2 > 1 (sparse cost volume) is "
            "not implemented on the TPU build — compute the dense "
            "stride-1 volume and subsample the output, which XLA fuses "
            "to the same work")
    x, y = as_tensor(x), as_tensor(y)
    pad, K, d = int(pad_size), int(kernel_size), int(max_displacement)
    if K < 1 or K % 2 == 0:
        raise NotImplementedError(
            f"correlation: kernel_size={K} must be odd — the reference "
            "kernel taps a centered (2*((K-1)/2)+1)^2 patch "
            "(correlation_op InferShape uses kernel_radius=(K-1)/2)")
    if pad < 0:
        raise ValueError(f"correlation: pad_size={pad} must be >= 0")
    rad = (K - 1) // 2
    border = d + rad              # InferShape border_size
    D = 2 * d + 1

    def fn(xa, ya):
        N, C, H, W = xa.shape
        # reference InferShape: out = ceil((H + 2*pad - 2*border)/stride1)
        Ho, Wo = H + 2 * pad - 2 * border, W + 2 * pad - 2 * border
        if Ho < 1 or Wo < 1:
            raise ValueError(
                f"correlation: pad_size={pad} gives empty output "
                f"{Ho}x{Wo} (need H+2*pad_size > 2*(max_displacement"
                f"+(kernel_size-1)//2) = {2 * border})")
        f32 = jnp.float32
        cfg = [(0, 0), (0, 0), (pad, pad), (pad, pad)]
        x1 = jnp.pad(xa.astype(f32), cfg)
        y1 = jnp.pad(ya.astype(f32), cfg)
        # output pixel o centers at padded coord o + border; a patch tap
        # (ki, kj) sits at center + ki - rad, so the slice start is
        # border + ki - rad = d + ki (displacement k shifts y's by k)
        chans = []
        for k in range(-d, d + 1):
            for l in range(-d, d + 1):
                prod = jnp.zeros((N, Ho, Wo), f32)
                for ki in range(K):
                    for kj in range(K):
                        a = lax.dynamic_slice(
                            x1, (0, 0, d + ki, d + kj),
                            (N, C, Ho, Wo))
                        b = lax.dynamic_slice(
                            y1, (0, 0, d + k + ki, d + l + kj),
                            (N, C, Ho, Wo))
                        prod = prod + (a * b).sum(1)
                chans.append(prod / (K * K * C))
        out = jnp.stack(chans, 1)          # [(k,l) row-major] == l+d+D*(k+d)
        return out.astype(xa.dtype)
    return run_op('correlation', fn, [x, y])


def partial_concat(inputs, start_index=0, length=-1):
    """partial_concat_op.cc (fluid/contrib/layers/nn.py partial_concat) —
    concat the same column slice [start, start+length) of every 2-D
    input along axis 1."""
    ts = [as_tensor(t) for t in inputs]

    def fn(*arrs):
        outs = []
        for a in arrs:
            n = a.shape[1]
            s = start_index if start_index >= 0 else n + start_index
            e = n if length < 0 else s + length
            outs.append(a[:, s:e])
        return jnp.concatenate(outs, axis=1)
    return run_op('partial_concat', fn, ts)


def partial_sum(inputs, start_index=0, length=-1):
    """partial_sum_op.cc — elementwise sum of the same column slice of
    every 2-D input."""
    ts = [as_tensor(t) for t in inputs]

    def fn(*arrs):
        acc = None
        for a in arrs:
            n = a.shape[1]
            s = start_index if start_index >= 0 else n + start_index
            e = n if length < 0 else s + length
            sl = a[:, s:e]
            acc = sl if acc is None else acc + sl
        return acc
    return run_op('partial_sum', fn, ts)


def modified_huber_loss(input, label):
    """modified_huber_loss_op.cc — binary classification loss on margin
    z = 2*label-1 times prediction: (max(0, 1-yz))^2 for yz >= -1, else
    -4*yz."""
    input, label = as_tensor(input), as_tensor(label)

    def fn(x, y):
        yz = (2.0 * y.astype(x.dtype) - 1.0) * x
        sq = jnp.square(jnp.maximum(1.0 - yz, 0.0))
        return jnp.where(yz >= -1.0, sq, -4.0 * yz)
    return run_op('modified_huber_loss', fn, [input, label], n_nondiff=1)


def l1_norm(x):
    """l1_norm_op.cc — sum of absolute values (scalar)."""
    x = as_tensor(x)
    return run_op('l1_norm', lambda a: jnp.abs(a).sum(), [x])
