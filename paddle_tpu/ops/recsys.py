"""Recsys / PS operator tier (VERDICT r3 #6 — the config-5 ad/CTR family).

Reference parity (semantics, not implementation):
  tdm_child            /root/reference/paddle/fluid/operators/tdm_child_op.h:36
  tdm_sampler          .../tdm_sampler_op.h:39 (layer-wise NCE sampling)
  cvm                  .../cvm_op.h:26 (show/click prefix, custom grad)
  data_norm            .../data_norm_op.cc:287 (summary stats normalize)
  batch_fc             .../batch_fc_op.cu (per-slot batched GEMM + bias)
  rank_attention       .../rank_attention.cu.h:28 (rank-block expand + GEMM)
  shuffle_batch        .../shuffle_batch_op.cc:82
  match_matrix_tensor  .../match_matrix_tensor_op.cc:218 (X·W_t·Yᵀ)
  var_conv_2d          .../var_conv_2d_op.cc (variable-size conv)
  tree_conv            .../tree_conv_op.cc + math/tree2col.cc (TBCNN)
  pyramid_hash         .../pyramid_hash_op.cc:226 (hashed n-gram embedding)

TPU-native design: the FLOP-carrying parts are dense gathers/einsums that
land on the MXU (batch_fc, rank_attention, match_matrix, tree_conv's
patch = Eta @ features formulation); the data-dependent graph/sampling
prep (tdm_sampler's rejection sampling, tree2col's DFS, n-gram hashing)
runs host-side in numpy — exactly the split the reference uses (those
kernels are CPU-only there). LoD inputs are replaced by padded dense
batches + lengths, per the blueprint's LoD disposition.
"""
import hashlib

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.autograd import run_op
from .common import as_tensor


def _arr(x):
    return x.data if isinstance(x, Tensor) else jnp.asarray(x)


def _np(x):
    return np.asarray(x.data if isinstance(x, Tensor) else x)


def _host_only(name):
    """The data-dependent host-prep ops (graph DFS / rejection sampling /
    hashing) cannot be traced into the one-jit static replay; they belong
    in the input pipeline or a heter host segment."""
    from ..core.autograd import STATIC_RECORD_HOOK
    if STATIC_RECORD_HOOK is not None:
        raise NotImplementedError(
            f"{name} is a host-side data-prep op: call it eagerly (input "
            "pipeline / DataFeed) or under a device_guard('cpu') heter "
            "segment, not inside a recorded static program")


# ---------------------------------------------------------------------------
# TDM (tree-based deep match)
# ---------------------------------------------------------------------------

def _tdm_child_arrays(ids, info, child_nums=2):
    ids = ids.astype(jnp.int32)
    info = info.astype(jnp.int32)
    rows = info[ids]                                   # [..., length]
    has_child = (ids != 0) & (rows[..., 3] != 0)
    children = jnp.where(has_child[..., None],
                         rows[..., 3:3 + child_nums], 0)
    leaf = jnp.where(children > 0, info[children][..., 0] != 0, False)
    leaf = jnp.where(has_child[..., None], leaf, False)
    return children, leaf.astype(jnp.int32)


def tdm_child(x, tree_info, child_nums):
    """Children + leaf mask of each node id (tdm_child_op.h:36).

    tree_info rows: [item_id, layer_id, ancestor_id, child_0..child_n-1];
    node 0 or a zero child_0 means "no children". A child is a leaf when
    its item_id (col 0) is nonzero.
    """
    return run_op('tdm_child', _tdm_child_arrays,
                  [as_tensor(x), as_tensor(tree_info)],
                  {'child_nums': child_nums})


def tdm_sampler(x, travel, layer, neg_samples_num_list, layer_offset_lod,
                output_positive=True, seed=0):
    """Layer-wise NCE sampling along each item's tree path
    (tdm_sampler_op.h:39). Host-side (numpy) like the reference's
    CPU-only kernel: rejection sampling avoids the positive and
    duplicates; a zero travel entry is path padding → masked row.

    x: [N] item ids; travel: [num_items, layer_nums] path node ids;
    layer: flat per-layer node-id array with layer_offset_lod offsets.
    Returns (out, labels, mask), each [N, sum(neg+pos)] int32.
    """
    _host_only('tdm_sampler')
    ids = _np(x).reshape(-1)
    travel = _np(travel)
    layer_flat = _np(layer).reshape(-1)
    offs = list(layer_offset_lod)
    layer_nums = len(neg_samples_num_list)
    pos = 1 if output_positive else 0
    width = sum(n + pos for n in neg_samples_num_list)
    rng = np.random.RandomState(seed)

    out = np.zeros((len(ids), width), np.int32)
    lab = np.zeros((len(ids), width), np.int32)
    msk = np.ones((len(ids), width), np.int32)
    for i, item in enumerate(ids):
        col = 0
        path = travel[int(item)]
        for li in range(layer_nums):
            n_neg = neg_samples_num_list[li]
            nodes = layer_flat[offs[li]:offs[li + 1]]
            positive = int(path[li])
            if positive == 0:                      # path padding
                out[i, col:col + n_neg + pos] = 0
                lab[i, col:col + n_neg + pos] = 0
                msk[i, col:col + n_neg + pos] = 0
                col += n_neg + pos
                continue
            if pos:
                out[i, col] = positive
                lab[i, col] = 1
                col += 1
            avail = int((nodes != positive).sum())
            if n_neg > avail:
                raise ValueError(
                    f"tdm_sampler: layer {li} has only {avail} distinct "
                    f"non-positive nodes but neg_samples_num_list[{li}]="
                    f"{n_neg} (reference validates sample_num <= "
                    "node_nums - 1)")
            chosen = set()
            for _ in range(n_neg):
                while True:
                    j = rng.randint(0, len(nodes))
                    if nodes[j] != positive and j not in chosen:
                        chosen.add(j)
                        break
                out[i, col] = nodes[j]
                lab[i, col] = 0
                col += 1
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(lab)), \
        Tensor(jnp.asarray(msk))


# ---------------------------------------------------------------------------
# CTR feature ops
# ---------------------------------------------------------------------------

@jax.custom_vjp
def _cvm_use(x, cvm):
    y0 = jnp.log(x[:, :1] + 1)
    y1 = jnp.log(x[:, 1:2] + 1) - y0
    return jnp.concatenate([y0, y1, x[:, 2:]], axis=1)


def _cvm_use_fwd(x, cvm):
    return _cvm_use(x, cvm), (x.shape, cvm)


def _cvm_use_bwd(res, dy):
    # reference grad (cvm_op.h:42): the show/click columns take their
    # cotangent from the CVM input, the rest passes through
    shape, cvm = res
    dx = jnp.concatenate(
        [jnp.broadcast_to(cvm[:, :2], (shape[0], 2)), dy[:, 2:]], axis=1)
    return dx, jnp.zeros_like(cvm)


_cvm_use.defvjp(_cvm_use_fwd, _cvm_use_bwd)


@jax.custom_vjp
def _cvm_drop(x, cvm):
    return x[:, 2:]


def _cvm_drop_fwd(x, cvm):
    return _cvm_drop(x, cvm), (x.shape, cvm)


def _cvm_drop_bwd(res, dy):
    shape, cvm = res
    dx = jnp.concatenate(
        [jnp.broadcast_to(cvm[:, :2], (shape[0], 2)), dy], axis=1)
    return dx, jnp.zeros_like(cvm)


_cvm_drop.defvjp(_cvm_drop_fwd, _cvm_drop_bwd)


def continuous_value_model(input, cvm, use_cvm=True):
    """cvm op (cvm_op.h:26): the first two columns are show/click. With
    use_cvm they become log(show+1), log(click+1)-log(show+1) and the
    width is kept; without, they are dropped. Gradient parity: the two
    lead columns' dx comes from the CVM input."""
    fn = _cvm_use if use_cvm else _cvm_drop
    return run_op('cvm', fn, [as_tensor(input), as_tensor(cvm)])


def _data_norm_arrays(xa, bsize, bsum, bsq, epsilon=1e-4):
    bsize = bsize.astype(jnp.float32)
    means = bsum.astype(jnp.float32) / bsize
    scales = jnp.sqrt(bsize / bsq.astype(jnp.float32))
    return (xa - means) * scales, means, scales


def data_norm(x, batch_size, batch_sum, batch_square_sum, epsilon=1e-4):
    """data_norm_op.cc:287 — normalize by summary statistics:
    means = batch_sum / batch_size, scales = sqrt(batch_size /
    batch_square_sum); y = (x - means) * scales. Returns (y, means,
    scales)."""
    return run_op('data_norm', _data_norm_arrays,
                  [as_tensor(x), as_tensor(batch_size),
                   as_tensor(batch_sum), as_tensor(batch_square_sum)],
                  {'epsilon': epsilon})


def data_norm_update(x, batch_size, batch_sum, batch_square_sum,
                     summary_decay=0.9999999):
    """The summary-update half of data_norm: decay the running stats and
    add this batch's size/sum/square-sum (data_norm_op.cc grad kernel's
    stat accumulation)."""
    xa = _arr(x).astype(jnp.float32)
    n = xa.shape[0]
    new_size = _arr(batch_size) * summary_decay + n
    new_sum = _arr(batch_sum) * summary_decay + xa.sum(axis=0)
    new_sq = _arr(batch_square_sum) * summary_decay + (xa * xa).sum(axis=0)
    return Tensor(new_size), Tensor(new_sum), Tensor(new_sq)


def batch_fc(input, w, bias=None):
    """batch_fc_op: per-slot FC. input [S, N, D] · w [S, D, O] + b [S, O]
    → [S, N, O] — one batched MXU GEMM."""
    if bias is not None:
        return run_op(
            'batch_fc',
            lambda x, wa, b: jnp.einsum('snd,sdo->sno', x, wa)
            + b[:, None, :],
            [as_tensor(input), as_tensor(w), as_tensor(bias)])
    return run_op('batch_fc',
                  lambda x, wa: jnp.einsum('snd,sdo->sno', x, wa),
                  [as_tensor(input), as_tensor(w)])


def _rank_attention_arrays(x, param, ro, max_rank=3):
    ro = ro.astype(jnp.int32)
    n, d = x.shape
    p = param.shape[1]
    k = max_rank

    lower = ro[:, 0] - 1                              # [N]
    faster = ro[:, 1::2] - 1                          # [N, k]
    index = ro[:, 2::2]                               # [N, k]
    valid = (lower[:, None] >= 0) & (faster >= 0)     # [N, k]

    # input_help [N, k, D]: row X[index_k] per valid slot
    ih = jnp.where(valid[..., None],
                   x[jnp.clip(index, 0, n - 1)], 0.0)
    # param blocks [N, k, D, P]: block (lower*k + faster) of rank_param
    start = lower[:, None] * k + faster               # [N, k]
    start = jnp.clip(start, 0, k * k - 1)
    blocks = param.reshape(k * k, d, p)[start]        # [N, k, D, P]
    blocks = jnp.where(valid[..., None, None], blocks, 0.0)
    return jnp.einsum('nkd,nkdp->np', ih, blocks)


def rank_attention(input, rank_offset, rank_param, max_rank):
    """rank_attention_op (rank_attention.cu.h:28): each instance carries
    up to max_rank (faster-rank, peer-index) slots in rank_offset
    [N, 1+2k]; the input rows indexed by the slots form a [k*D] block
    row, the (lower_rank, faster_rank) blocks of rank_param
    [k*k*D, P] form a [k*D, P] block matrix, and out[i] = block_row @
    block_matrix. Invalid slots (rank <= 0) contribute zeros."""
    return run_op('rank_attention', _rank_attention_arrays,
                  [as_tensor(input), as_tensor(rank_param),
                   as_tensor(rank_offset)],
                  {'max_rank': max_rank}, n_nondiff=1)


def _shuffle_batch_arrays(xa, seed=0):
    lead = int(np.prod(xa.shape[:-1])) if xa.ndim > 1 else xa.shape[0]
    perm = jax.random.permutation(jax.random.PRNGKey(seed), lead)
    flat = xa.reshape(lead, -1) if xa.ndim > 1 else xa
    out = jnp.take(flat, perm, axis=0).reshape(xa.shape)
    return out, perm.astype(jnp.int32)


def shuffle_batch(x, seed=0):
    """shuffle_batch_op.cc:82 — shuffle rows (all dims but the last are
    flattened as the row axis). Returns (out, shuffle_idx); gradients
    unshuffle through the take."""
    return run_op('shuffle_batch', _shuffle_batch_arrays,
                  [as_tensor(x)], {'seed': int(seed)})


def _match_matrix_arrays(xa, ya, wa, *lens, has_x_len=False,
                         has_y_len=False):
    out = jnp.einsum('bxd,dte,bye->btxy', xa, wa, ya)
    li = 0
    if has_x_len:
        mx = jnp.arange(xa.shape[1])[None, :] < lens[li][:, None]
        out = out * mx[:, None, :, None]
        li += 1
    if has_y_len:
        my = jnp.arange(ya.shape[1])[None, :] < lens[li][:, None]
        out = out * my[:, None, None, :]
    return out


def match_matrix_tensor(x, y, w, x_len=None, y_len=None):
    """match_matrix_tensor_op.cc:218 — out[b,t] = X_b · W_t · Y_bᵀ.
    Dense form: x [B, Lx, D], y [B, Ly, D], w [D, T, D] → [B, T, Lx, Ly];
    positions past x_len/y_len are masked to 0 (the LoD replacement)."""
    args = [as_tensor(x), as_tensor(y), as_tensor(w)]
    n_lens = 0
    for l in (x_len, y_len):
        if l is not None:
            args.append(as_tensor(l))
            n_lens += 1
    return run_op('match_matrix_tensor', _match_matrix_arrays, args,
                  {'has_x_len': x_len is not None,
                   'has_y_len': y_len is not None}, n_nondiff=n_lens)


def _var_conv_2d_arrays(xa, wf, *lens, output_channel=1, input_channel=1,
                        filter_size=3, stride=1, masked=False):
    from jax import lax
    wa = wf.reshape(output_channel, input_channel,
                    filter_size, filter_size)

    def mask(t, rl, cl):
        m = ((jnp.arange(t.shape[2])[None, :, None] < rl[:, None, None]) &
             (jnp.arange(t.shape[3])[None, None, :] < cl[:, None, None]))
        return t * m[:, None, :, :].astype(t.dtype)

    if masked:
        rl, cl = lens
        xa = mask(xa, rl, cl)
    out = lax.conv_general_dilated(
        xa, wa, window_strides=(stride, stride), padding='SAME',
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
    if masked:
        out = mask(out, jnp.maximum((rl + stride - 1) // stride, 1),
                   jnp.maximum((cl + stride - 1) // stride, 1))
    return out


def var_conv_2d(x, w, input_channel, output_channel, filter_size, stride=1,
                row_lens=None, col_lens=None):
    """var_conv_2d_op — conv over per-sample-sized images. Dense form:
    x [B, C, H, W] padded; rows/cols past each sample's (row_lens[i],
    col_lens[i]) are zeroed before AND after the conv, so the valid
    region matches a per-sample conv on the true size."""
    args = [as_tensor(x), as_tensor(w)]
    masked = row_lens is not None
    if masked:
        args += [as_tensor(row_lens), as_tensor(col_lens)]
    return run_op('var_conv_2d', _var_conv_2d_arrays, args,
                  {'output_channel': output_channel,
                   'input_channel': input_channel,
                   'filter_size': filter_size, 'stride': stride,
                   'masked': masked}, n_nondiff=2 if masked else 0)


# ---------------------------------------------------------------------------
# tree_conv (TBCNN)
# ---------------------------------------------------------------------------

def _tree2col_eta(edges, num_nodes, max_depth):
    """Host-side tree2col (math/tree2col.cc:23): for every node u, DFS
    its patch to max_depth; each patch member v contributes with weights
    (eta_l, eta_r, eta_t). Returned as THREE dense [P, num_nodes]
    matrices so the patch becomes Eta_s @ features — a dense MXU matmul
    instead of the reference's scatter loop."""
    tr = [[] for _ in range(num_nodes + 1)]
    for u, v in edges:
        if u != 0 and v != 0:
            tr[int(u)].append(int(v))
        else:
            break

    etas = []          # per patch: list of (node, index, pclen, depth)
    for root in range(1, num_nodes + 1):
        stack = [(root, 1, 1, 0)]
        patch = [(root, 1, 1, 0)]
        visited = {root}
        while stack:
            node, idx, pclen, depth = stack[-1]
            end = True
            for i, v in enumerate(tr[node]):
                if v not in visited and depth + 1 < max_depth:
                    visited.add(v)
                    stack.append((v, i, len(tr[node]), depth + 1))
                    patch.append((v, i + 1, len(tr[node]), depth + 1))
                    end = False
            if end:
                stack.pop()
        etas.append(patch)

    P = len(etas)
    E = np.zeros((3, P, num_nodes), np.float32)     # l, r, t
    fd = float(max_depth)
    for pi, patch in enumerate(etas):
        for node, idx, pclen, depth in patch:
            eta_t = (fd - depth) / fd
            tmp = 0.5 if pclen == 1 else (idx - 1.0) / (pclen - 1.0)
            eta_l = (1.0 - eta_t) * tmp
            eta_r = (1.0 - eta_t) * (1.0 - tmp)
            E[0, pi, node - 1] += eta_l
            E[1, pi, node - 1] += eta_r
            E[2, pi, node - 1] += eta_t
    return E


def tree_conv(nodes_vector, edge_set, filter, max_depth=2):
    """tree_conv_op (TBCNN, arxiv 1409.5718): per sample, build the
    continuous-binary-tree patch matrices host-side, then
    out[p, o, m] = Σ_{f,s} (Eta_s @ X)[p, f] · filter[f, s, o, m].

    nodes_vector [B, N, F]; edge_set [B, E, 2] int (0,0-padded);
    filter [F, 3, O, M] → out [B, P, O, M] (P = N patches, zero rows for
    nodes past each sample's count)."""
    _host_only('tree_conv')
    xs = _arr(nodes_vector)
    w = _arr(filter)
    edges = _np(edge_set)
    B, N, F = xs.shape
    etas = []
    for b in range(B):
        nc = 0
        for u, v in edges[b]:
            if u != 0 and v != 0:
                nc += 1
            else:
                break
        num_nodes = nc + 1          # reference construct_tree: +1 always
        Eb = np.zeros((3, N, N), np.float32)
        E = _tree2col_eta(edges[b], num_nodes, max_depth)
        Eb[:, :E.shape[1], :E.shape[2]] = E
        etas.append(Eb)
    eta = jnp.asarray(np.stack(etas))                 # [B, 3, N, N]

    def fn(xs_, w_, eta_=eta):
        patch = jnp.einsum('bspn,bnf->bpfs', eta_, xs_)   # [B, P, F, 3]
        return jnp.einsum('bpfs,fsom->bpom', patch, w_)
    # differentiable tail through the tape (grads reach nodes_vector AND
    # the trainable filter); eta is host-built int prep, closed over
    return run_op('tree_conv', fn,
                  [as_tensor(nodes_vector), as_tensor(filter)])


# ---------------------------------------------------------------------------
# pyramid_hash
# ---------------------------------------------------------------------------

def _hash32(data, seed):
    h = hashlib.blake2s(data, digest_size=4,
                        salt=seed.to_bytes(8, 'little'))
    return int.from_bytes(h.digest(), 'little')


def pyramid_hash(x, w, num_emb, space_len, pyramid_layer=2, rand_len=16,
                 seq_lens=None, seed=0):
    """pyramid_hash_op.cc:226 — every n-gram (n = 2..pyramid_layer) of
    each sequence hashes to num_emb/rand_len slices of the hash-space
    weight table w [space_len + rand_len, 1]; a gram's embedding is the
    concatenation of those slices. Dense pooled form: x [B, L] int
    tokens (seq_lens masks padding) → [B, num_emb] sum over the
    sequence's grams (the reference emits per-gram LoD rows that
    downstream pools). Hash identity: blake2s stands in for XXH32 —
    same structure, different mix. Differentiable w.r.t. w (the gather
    runs in jax; hashing is host-side int prep)."""
    _host_only('pyramid_hash')
    ids = _np(x)
    B, L = ids.shape
    lens = _np(seq_lens).reshape(-1) if seq_lens is not None \
        else np.full(B, L, np.int64)
    n_slice = num_emb // rand_len
    max_grams = max(1, sum(max(0, L - n + 1)
                    for n in range(2, pyramid_layer + 1)))
    gather = np.zeros((B, max_grams, n_slice), np.int64)
    gmask = np.zeros((B, max_grams), np.float32)
    for b in range(B):
        g = 0
        for nlen in range(2, pyramid_layer + 1):
            for s in range(int(lens[b]) - nlen + 1):
                gram = np.ascontiguousarray(
                    ids[b, s:s + nlen].astype(np.int32)).tobytes()
                for j in range(n_slice):
                    gather[b, g, j] = _hash32(gram, seed + j) % space_len
                gmask[b, g] = 1.0
                g += 1
    idx = jnp.asarray(gather)[..., None] \
        + jnp.arange(rand_len)[None, None, None, :]
    gm = jnp.asarray(gmask)

    def fn(wa_, idx_=idx, gm_=gm):
        rows = jnp.take(wa_.reshape(-1)[:space_len + rand_len], idx_,
                        axis=0)                       # [B, G, S, rand]
        emb = rows.reshape(B, max_grams, num_emb)
        return (emb * gm_[..., None]).sum(axis=1)
    # differentiable tail through the tape — the trainable hash table
    # gets real gradients; hashing is host-side int prep
    return run_op('pyramid_hash', fn, [as_tensor(w)])


# ---------------------------------------------------------------------------
# filter_by_instag
# ---------------------------------------------------------------------------

def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0):
    """filter_by_instag_op.cc — keep only the instances whose tag set
    intersects `filter_tag` (ad-targeting row filter). Dense contract:
    ins [N, D]; ins_tag [N, T] padded with -1 (the LoD multi-tag rows);
    filter_tag [F].

    Host-side data-prep op (data-dependent output length). Returns
    (filtered rows [M, D] — or a single out_val_if_empty row when no
    instance matches, like the reference — loss_weight [M, 1],
    index map [M])."""
    import jax.numpy as jnp
    _host_only('filter_by_instag')
    x = _np(ins)
    tags = _np(ins_tag)
    if tags.ndim == 1:
        tags = tags[:, None]
    fset = set(int(t) for t in _np(filter_tag).reshape(-1))
    keep = [i for i in range(x.shape[0])
            if fset & set(int(t) for t in tags[i] if t >= 0)]
    if keep:
        rows = x[np.asarray(keep)]
        lw = np.ones((len(keep), 1), np.float32)
        idx = np.asarray(keep, np.int64)
    else:
        rows = np.full((1,) + x.shape[1:], out_val_if_empty, x.dtype)
        lw = np.zeros((1, 1), np.float32)
        idx = np.zeros((1,), np.int64)
    return (Tensor(jnp.asarray(rows)), Tensor(jnp.asarray(lw)),
            Tensor(jnp.asarray(idx)))
