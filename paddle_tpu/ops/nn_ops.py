"""Neural-network functional ops.

Reference parity: operators/ activation_op.cc (≈40 activations), softmax,
log_softmax, layer_norm, batch_norm, group/instance_norm, conv2d(+cudnn),
conv_transpose, pool2d, dropout, lookup_table_v2 (embedding),
softmax_with_cross_entropy, cross_entropy2, bce/nll/smooth_l1/kldiv losses,
interpolate_v2 (SURVEY.md Appendix B). Convs/matmuls map straight to the MXU via
lax.conv_general_dilated / jnp.matmul; elementwise ops fuse in XLA.
"""
import functools
import math as _pymath

import jax
import jax.numpy as jnp
import numpy as np

from .common import as_tensor, register, unary
from ..core import rng
from ..core.autograd import run_op, grad_enabled
from ..core.tensor import Tensor

# ---- activations -----------------------------------------------------------
relu = unary('relu', jax.nn.relu)
relu6 = unary('relu6', jax.nn.relu6)
elu_ = jax.nn.elu
silu = unary('silu', jax.nn.silu)
swish = unary('swish', jax.nn.silu)
softplus_ = jax.nn.softplus
softsign = unary('softsign', jax.nn.soft_sign)
hardsigmoid = unary('hard_sigmoid', lambda x: jnp.clip(x / 6.0 + 0.5, 0.0, 1.0))
hardswish = unary('hard_swish', jax.nn.hard_swish)
mish = unary('mish', lambda x: x * jnp.tanh(jax.nn.softplus(x)))
tanhshrink = unary('tanh_shrink', lambda x: x - jnp.tanh(x))


def gelu(x, approximate=False, name=None):
    x = as_tensor(x)
    return run_op('gelu', lambda a: jax.nn.gelu(a, approximate=approximate), [x])


def bias_gelu(x, bias=None, approximate=False, name=None):
    """Fused bias-add + GELU (TPP, ops/pallas/fused_elementwise.py):
    y = gelu(x + bias). Transformer FFNs call this with the first
    linear's bias left unapplied so the add fuses into the activation
    kernel on TPU; the reference route runs the identical jnp
    expression (nn.Linear's `matmul + bias` then `gelu`), so routing is
    a pure performance choice. bias=None degrades to plain gelu."""
    x = as_tensor(x)
    if bias is None:
        return gelu(x, approximate=approximate)
    bias = as_tensor(bias)
    from .pallas import fused_elementwise as _fe
    if _fe.use_fused('bias_gelu'):
        fn = lambda a, b: _fe.bias_gelu(a, b, approximate)
    else:
        fn = lambda a, b: _fe.bias_gelu_reference(a, b, approximate)
    return run_op('bias_gelu', fn, [x, bias])


def dropout_add(x, residual, p=0.5, training=True,
                mode='upscale_in_train', name=None):
    """Fused dropout + residual add (TPP): the transformer residual
    join `residual + dropout(x)`. Draws the SAME bernoulli key/shape
    the plain `dropout` op would at this point in the RNG stream, so
    replacing `add(residual, dropout(x))` call sites is bit-exact on
    the reference route; the Pallas route fuses select + upscale + add
    into one pass (ops/pallas/fused_elementwise.py).

    The attention-prob analogue lives in
    ops/pallas/flash_attention.causal_attention(dropout=..., key=...):
    the keep mask is likewise drawn OUTSIDE the kernel at the dense
    path's RNG-stream point and streamed through the fused fwd/bwd
    kernels (docs/performance.md#fused-primitives). Under
    sequence-parallel activation sharding
    (docs/performance.md#sequence-parallel-activations) this join runs
    on the local token slice: the draw folds the mp rank into the
    stream key so slices get INDEPENDENT masks — deterministic, but
    not mask-identical to the replicated route when p > 0."""
    x, residual = as_tensor(x), as_tensor(residual)
    if not training or p == 0.0:
        if mode == 'upscale_in_train':
            return run_op('dropout_add', lambda a, r: a + r,
                          [x, residual])
        return run_op('dropout_add', lambda a, r: a * (1 - p) + r,
                      [x, residual])
    if mode != 'upscale_in_train':
        from . import math as _m
        return _m.add(dropout(x, p=p, training=training, mode=mode),
                      residual)
    key = rng.next_key()
    from ..distributed import collective as _C
    if _C.mp_seq_sharded() and 'mp' in _C.current_spmd_axes():
        # sequence-parallel activation sharding: this join runs on a
        # DISTINCT token slice per mp rank — fold the rank into the key
        # so slices draw independent masks (the shared key would stamp
        # the same pattern onto every slice, a cross-slice correlation
        # the replicated route never has). Replicated-region draws
        # (e.g. the pre-slice embedding dropout) keep the shared key.
        from jax import lax as _lax
        key = jax.random.fold_in(key, _lax.axis_index('mp'))
    from .pallas import fused_elementwise as _fe
    fused = _fe.use_fused('dropout_add')

    def fn(a, r):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        m = keep.astype(jnp.float32)
        if fused:
            return _fe.dropout_add(a, r, m, p)
        return _fe.dropout_add_reference(a, r, m, p)
    return run_op('dropout_add', fn, [x, residual])


def elu(x, alpha=1.0, name=None):
    x = as_tensor(x)
    return run_op('elu', lambda a: jax.nn.elu(a, alpha=alpha), [x])


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    x = as_tensor(x)
    return run_op('selu', lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), [x])


def celu(x, alpha=1.0, name=None):
    x = as_tensor(x)
    return run_op('celu', lambda a: jax.nn.celu(a, alpha=alpha), [x])


def leaky_relu(x, negative_slope=0.01, name=None):
    x = as_tensor(x)
    return run_op('leaky_relu', lambda a: jax.nn.leaky_relu(a, negative_slope), [x])


def prelu(x, weight, data_format='NCHW', name=None):
    x, weight = as_tensor(x), as_tensor(weight)
    def fn(a, w):
        if w.size > 1 and a.ndim > 1:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format == 'NCHW' else a.ndim - 1
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(a > 0, a, a * w)
    return run_op('prelu', fn, [x, weight])


def softplus(x, beta=1.0, threshold=20.0, name=None):
    x = as_tensor(x)
    def fn(a):
        scaled = beta * a
        return jnp.where(scaled > threshold, a, jax.nn.softplus(scaled) / beta)
    return run_op('softplus', fn, [x])


def hardtanh(x, min=-1.0, max=1.0, name=None):
    x = as_tensor(x)
    return run_op('brelu', lambda a: jnp.clip(a, min, max), [x])


def hardshrink(x, threshold=0.5, name=None):
    x = as_tensor(x)
    return run_op('hard_shrink', lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), [x])


def softshrink(x, threshold=0.5, name=None):
    x = as_tensor(x)
    return run_op('softshrink',
                  lambda a: jnp.where(a > threshold, a - threshold,
                                      jnp.where(a < -threshold, a + threshold, 0.0)), [x])


def thresholded_relu(x, threshold=1.0, name=None):
    x = as_tensor(x)
    return run_op('thresholded_relu', lambda a: jnp.where(a > threshold, a, 0.0), [x])


def log_sigmoid(x, name=None):
    x = as_tensor(x)
    return run_op('logsigmoid', jax.nn.log_sigmoid, [x])


def maxout(x, groups, axis=1, name=None):
    x = as_tensor(x)
    def fn(a):
        c = a.shape[axis]
        new_shape = list(a.shape)
        new_shape[axis] = c // groups
        new_shape.insert(axis + 1, groups)
        return jnp.max(a.reshape(new_shape), axis=axis + 1)
    return run_op('maxout', fn, [x])


def softmax(x, axis=-1, dtype=None, name=None):
    x = as_tensor(x)
    out = run_op('softmax', lambda a: jax.nn.softmax(a, axis=axis), [x])
    return out.astype(dtype) if dtype is not None else out


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = as_tensor(x)
    out = run_op('log_softmax', lambda a: jax.nn.log_softmax(a, axis=axis), [x])
    return out.astype(dtype) if dtype is not None else out


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    x = as_tensor(x)
    key = rng.next_key()
    def fn(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            y_hard = jax.nn.one_hot(jnp.argmax(y, axis=axis), a.shape[axis],
                                    dtype=a.dtype, axis=axis)
            y = y_hard + y - jax.lax.stop_gradient(y)
        return y
    return run_op('gumbel_softmax', fn, [x])

# ---- normalization ---------------------------------------------------------
def layer_norm(x, normalized_shape=None, weight=None, bias=None, epsilon=1e-05,
               name=None):
    """Parity: operators/layer_norm_op."""
    x = as_tensor(x)
    if normalized_shape is None:
        normalized_shape = x.shape[-1:]
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(normalized_shape)
    axes = tuple(range(x.ndim - n_axes, x.ndim))
    tensors = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        tensors.append(as_tensor(weight))
    if has_b:
        tensors.append(as_tensor(bias))

    # fused Pallas route (ops/pallas/fused_norm.py): the GPT/BERT shape —
    # last-axis normalization with affine — runs the one-pass fwd/bwd
    # kernels on TPU (reference jnp below on CPU; FLAGS_fused_layer_norm
    # forces either way and tests force the kernel under interpret mode)
    from .pallas import fused_norm as _fln
    # dtype gate: the reference path PROMOTES when weight/bias are wider
    # than x (bf16 xhat * fp32 w -> fp32 out); the kernel stores in
    # x.dtype, so mixed dtypes keep the jnp path
    fused_ok = (n_axes == 1 and has_w and has_b and x.ndim >= 2
                and tuple(normalized_shape) == (x.shape[-1],)
                and tensors[1].data.dtype == x.data.dtype
                and tensors[2].data.dtype == x.data.dtype)
    if _fln.use_fused(supported=fused_ok):
        return run_op('layer_norm',
                      lambda a, w, b: _fln.fused_layer_norm(a, w, b,
                                                            epsilon),
                      tensors)

    def fn(*args):
        a = args[0]
        w = args[1] if has_w else None
        b = args[1 + has_w] if has_b else None
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        return out
    return run_op('layer_norm', fn, tensors)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format='NCHW', use_global_stats=None, name=None):
    """Parity: operators/batch_norm_op. Running stats update is an eager
    side-effect on the passed mean/var tensors (as in paddle)."""
    x = as_tensor(x)
    ch_axis = 1 if data_format.startswith('NC') and x.ndim > 1 else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]

    use_batch_stats = training and not use_global_stats
    if use_batch_stats:
        xf = x.data.astype(jnp.float32)
        batch_mean = jnp.mean(xf, axis=reduce_axes)
        batch_var = jnp.var(xf, axis=reduce_axes)
        if running_mean is not None:
            running_mean.set_value(momentum * running_mean.data
                                   + (1 - momentum) * batch_mean)
            running_var.set_value(momentum * running_var.data
                                  + (1 - momentum) * batch_var)
        mean_arr, var_arr = batch_mean, batch_var
    else:
        mean_arr, var_arr = running_mean.data, running_var.data

    tensors = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        tensors.append(as_tensor(weight))
    if has_b:
        tensors.append(as_tensor(bias))

    def fn(*args):
        a = args[0]
        w = args[1] if has_w else None
        b = args[1 + has_w] if has_b else None
        if use_batch_stats:
            af = a.astype(jnp.float32)
            m = jnp.mean(af, axis=reduce_axes).reshape(shape)
            v = jnp.var(af, axis=reduce_axes).reshape(shape)
        else:
            m = mean_arr.reshape(shape)
            v = var_arr.reshape(shape)
        out = (a - m.astype(a.dtype)) * jax.lax.rsqrt(v + epsilon).astype(a.dtype)
        if w is not None:
            out = out * w.reshape(shape)
        if b is not None:
            out = out + b.reshape(shape)
        return out
    return run_op('batch_norm', fn, tensors)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format='NCHW', name=None):
    x = as_tensor(x)
    tensors = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        tensors.append(as_tensor(weight))
    if has_b:
        tensors.append(as_tensor(bias))

    def fn(*args):
        a = args[0]
        w = args[1] if has_w else None
        b = args[1 + has_w] if has_b else None
        n, c = a.shape[0], a.shape[1]
        g = a.reshape(n, num_groups, c // num_groups, *a.shape[2:])
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a.shape)
        shape = [1, c] + [1] * (a.ndim - 2)
        if w is not None:
            out = out * w.reshape(shape)
        if b is not None:
            out = out + b.reshape(shape)
        return out
    return run_op('group_norm', fn, tensors)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, eps=1e-05, momentum=0.9, data_format='NCHW'):
    x = as_tensor(x)
    tensors = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        tensors.append(as_tensor(weight))
    if has_b:
        tensors.append(as_tensor(bias))

    def fn(*args):
        a = args[0]
        w = args[1] if has_w else None
        b = args[1 + has_w] if has_b else None
        axes = tuple(range(2, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + eps)
        shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        if w is not None:
            out = out * w.reshape(shape)
        if b is not None:
            out = out + b.reshape(shape)
        return out
    return run_op('instance_norm', fn, tensors)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format='NCHW'):
    x = as_tensor(x)
    def fn(a):
        sq = a * a
        half = size // 2
        pad = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (a.ndim - 2)
        padded = jnp.pad(sq, pad)
        acc = jnp.zeros_like(a)
        for i in range(size):
            acc = acc + jax.lax.slice_in_dim(padded, i, i + a.shape[1], axis=1)
        return a / jnp.power(k + alpha * acc, beta)
    return run_op('lrn', fn, [x])


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    x = as_tensor(x)
    def fn(a):
        nrm = jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=axis, keepdims=True), 1.0 / p)
        return a / jnp.maximum(nrm, epsilon)
    return run_op('normalize', fn, [x])

# ---- linear / conv / pool --------------------------------------------------
def linear(x, weight, bias=None, name=None):
    """Parity: operators/ matmul_v2 + elementwise_add fusion (fc)."""
    x, weight = as_tensor(x), as_tensor(weight)
    if bias is not None:
        bias = as_tensor(bias)
        return run_op('linear', lambda a, w, b: jnp.matmul(a, w) + b,
                      [x, weight, bias])
    return run_op('linear', lambda a, w: jnp.matmul(a, w), [x, weight])


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


def _conv_padding(padding, k, stride, dilation, nd=2):
    if isinstance(padding, str):
        return padding.upper()  # 'SAME' / 'VALID'
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if len(padding) == nd:
        return [(p, p) for p in padding]
    if len(padding) == 2 * nd:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(nd)]
    raise ValueError(f"bad padding {padding}")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCHW', name=None):
    """Parity: operators/conv_op (+conv_cudnn) → lax.conv_general_dilated
    (MXU path)."""
    x, weight = as_tensor(x), as_tensor(weight)
    stride = _pair(stride)
    dilation = _pair(dilation)
    pad = _conv_padding(padding, weight.shape[2:], stride, dilation)
    dn = ('NCHW', 'OIHW', 'NCHW') if data_format == 'NCHW' else ('NHWC', 'HWIO', 'NHWC')
    tensors = [x, weight]
    has_b = bias is not None
    if has_b:
        tensors.append(as_tensor(bias))

    def fn(a, w, *rest):
        # no preferred_element_type: jax's conv vjp mixes the preferred
        # f32 cotangent with bf16 operands and errors; the TPU MXU
        # accumulates bf16 convs in f32 regardless
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups)
        out = out.astype(a.dtype)
        if rest:
            b = rest[0]
            shape = [1, b.shape[0], 1, 1] if data_format == 'NCHW' else [1, 1, 1, b.shape[0]]
            out = out + b.reshape(shape)
        return out
    return run_op('conv2d', fn, tensors)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCL', name=None):
    x, weight = as_tensor(x), as_tensor(weight)
    from . import manip
    x4 = run_op('unsqueeze2', lambda a: jnp.expand_dims(a, -1), [x])
    w4 = run_op('unsqueeze2', lambda a: jnp.expand_dims(a, -1), [weight])
    s = _pair(stride, 1) + (1,) if not isinstance(stride, (list, tuple)) else tuple(stride) + (1,)
    p = padding if isinstance(padding, str) else (
        [(padding, padding), (0, 0)] if isinstance(padding, int)
        else [(padding[0], padding[0]), (0, 0)])
    d = (dilation if isinstance(dilation, int) else dilation[0], 1)
    out = conv2d(x4, w4, bias, stride=(s[0], 1), padding=p, dilation=d, groups=groups)
    return run_op('squeeze2', lambda a: jnp.squeeze(a, -1), [out])


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCDHW', name=None):
    x, weight = as_tensor(x), as_tensor(weight)
    stride = _pair(stride, 3)
    dilation = _pair(dilation, 3)
    pad = _conv_padding(padding, weight.shape[2:], stride, dilation, nd=3)
    dn = ('NCDHW', 'OIDHW', 'NCDHW')
    tensors = [x, weight]
    has_b = bias is not None
    if has_b:
        tensors.append(as_tensor(bias))

    def fn(a, w, *rest):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups)
        if rest:
            out = out + rest[0].reshape(1, -1, 1, 1, 1)
        return out
    return run_op('conv3d', fn, tensors)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format='NCHW', name=None):
    """Parity: operators/conv_transpose_op. weight layout IOHW (paddle)."""
    x, weight = as_tensor(x), as_tensor(weight)
    stride = _pair(stride)
    dilation = _pair(dilation)
    opad = _pair(output_padding)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _conv_padding(padding, weight.shape[2:], stride, dilation)
        kh, kw = weight.shape[2], weight.shape[3]
        # transpose conv padding transform: lo = k-1-p_lo, hi = k-1-p_hi+opad
        pad = [(dilation[0] * (kh - 1) - p[0][0],
                dilation[0] * (kh - 1) - p[0][1] + opad[0]),
               (dilation[1] * (kw - 1) - p[1][0],
                dilation[1] * (kw - 1) - p[1][1] + opad[1])]
    tensors = [x, weight]
    has_b = bias is not None
    if has_b:
        tensors.append(as_tensor(bias))

    def fn(a, w, *rest):
        # IOHW → OIHW flipped = standard transpose-conv as dilated conv
        w2 = jnp.flip(w, axis=(2, 3)).transpose(1, 0, 2, 3)
        if groups > 1:
            ci = w.shape[0]
            w_g = w.reshape(groups, ci // groups, *w.shape[1:])
            w2 = jnp.concatenate([jnp.flip(g, axis=(3,)).transpose(1, 0, 2, 3)
                                  for g in [wg for wg in w_g]], axis=0) if False else w2
        out = jax.lax.conv_general_dilated(
            a, w2, window_strides=(1, 1), padding=pad,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=('NCHW', 'OIHW', 'NCHW'),
            feature_group_count=groups)
        if rest:
            out = out + rest[0].reshape(1, -1, 1, 1)
        return out
    return run_op('conv2d_transpose', fn, tensors)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format='NCHW',
               name=None):
    """Parity: operators/pool_op (avg)."""
    x = as_tensor(x)
    k = _pair(kernel_size)
    s = _pair(stride) if stride is not None else k
    pad = _conv_padding(padding, k, s, (1, 1))
    if isinstance(pad, str):
        pads = pad
    else:
        pads = [(0, 0), (0, 0)] + list(pad)

    def fn(a):
        window = (1, 1) + k
        strides = (1, 1) + s
        summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pads)
        if divisor_override:
            return summed / divisor_override
        if exclusive and pads != 'VALID' and not isinstance(pads, str):
            ones = jnp.ones_like(a)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pads)
            return summed / counts
        return summed / (k[0] * k[1])
    return run_op('pool2d_avg', fn, [x])


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCHW', name=None):
    x = as_tensor(x)
    k = _pair(kernel_size)
    s = _pair(stride) if stride is not None else k
    pad = _conv_padding(padding, k, s, (1, 1))
    pads = pad if isinstance(pad, str) else [(0, 0), (0, 0)] + list(pad)

    if not return_mask:
        def fn(a):
            return jax.lax.reduce_window(a, -jnp.inf, jax.lax.max,
                                         (1, 1) + k, (1, 1) + s, pads)
        return run_op('pool2d_max', fn, [x])

    # with-index variant (parity: max_pool2d_with_index op): indices are
    # flat positions in the per-channel H*W map, the unpool contract
    if isinstance(pad, str):
        raise NotImplementedError(
            "max_pool2d(return_mask=True) needs explicit padding")
    (p0, p1), (p2, p3) = pad

    def fn_idx(a):
        N, Cc, H, W = a.shape
        av = jnp.pad(a, ((0, 0), (0, 0), (p0, p1), (p2, p3)),
                     constant_values=-jnp.inf)
        pos = jnp.broadcast_to(
            jnp.arange(H * W, dtype=jnp.float32).reshape(1, 1, H, W),
            (N, Cc, H, W))
        pv = jnp.pad(pos, ((0, 0), (0, 0), (p0, p1), (p2, p3)),
                     constant_values=-1.0)
        def patches(arr):
            # HIGHEST precision: the patch extractor is a matmul under the
            # hood — TPU's default bf16 multiplies would round values AND
            # corrupt position indices > 256
            pt = jax.lax.conv_general_dilated_patches(
                arr, k, s, [(0, 0), (0, 0)],
                dimension_numbers=('NCHW', 'OIHW', 'NCHW'),
                precision=jax.lax.Precision.HIGHEST)
            oh, ow = pt.shape[2], pt.shape[3]
            return pt.reshape(N, Cc, k[0] * k[1], oh, ow)
        vals = patches(av)
        poss = patches(pv)
        am = jnp.argmax(vals, axis=2)
        out = jnp.take_along_axis(vals, am[:, :, None], axis=2)[:, :, 0]
        idx = jnp.take_along_axis(poss, am[:, :, None], axis=2)[:, :, 0]
        return out, idx.astype(jnp.int32)
    return run_op('pool2d_max_with_index', fn_idx, [x])


def adaptive_avg_pool2d(x, output_size, data_format='NCHW', name=None):
    x = as_tensor(x)
    oh, ow = _pair(output_size)
    h, w = x.shape[2], x.shape[3]
    if oh is None:
        oh = h
    if ow is None:
        ow = w
    if h % oh == 0 and w % ow == 0:
        k = (h // oh, w // ow)
        return avg_pool2d(x, k, stride=k, padding=0, exclusive=False)

    def fn(a):
        # general adaptive: mean over variable windows
        out = jnp.zeros(a.shape[:2] + (oh, ow), a.dtype)
        rows = [(int(np.floor(i * h / oh)), int(np.ceil((i + 1) * h / oh))) for i in range(oh)]
        cols = [(int(np.floor(j * w / ow)), int(np.ceil((j + 1) * w / ow))) for j in range(ow)]
        parts = []
        for r0, r1 in rows:
            row_parts = []
            for c0, c1 in cols:
                row_parts.append(jnp.mean(a[:, :, r0:r1, c0:c1], axis=(2, 3)))
            parts.append(jnp.stack(row_parts, axis=-1))
        return jnp.stack(parts, axis=-2)
    return run_op('adaptive_avg_pool2d', fn, [x])


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    x = as_tensor(x)
    oh, ow = _pair(output_size)
    h, w = x.shape[2], x.shape[3]
    if h % oh == 0 and w % ow == 0:
        k = (h // oh, w // ow)
        return max_pool2d(x, k, stride=k, padding=0, return_mask=return_mask)
    raise NotImplementedError("non-divisible adaptive max pool")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    x = as_tensor(x)
    x4 = run_op('unsqueeze2', lambda a: jnp.expand_dims(a, -1), [x])
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = stride if stride is not None else k
    s = s if isinstance(s, int) else s[0]
    p = padding if isinstance(padding, int) else padding[0]
    out = avg_pool2d(x4, (k, 1), (s, 1), [(p, p), (0, 0)], exclusive=exclusive)
    return run_op('squeeze2', lambda a: jnp.squeeze(a, -1), [out])


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    x = as_tensor(x)
    x4 = run_op('unsqueeze2', lambda a: jnp.expand_dims(a, -1), [x])
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = stride if stride is not None else k
    s = s if isinstance(s, int) else s[0]
    p = padding if isinstance(padding, int) else padding[0]
    out = max_pool2d(x4, (k, 1), (s, 1), [(p, p), (0, 0)])
    return run_op('squeeze2', lambda a: jnp.squeeze(a, -1), [out])


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """Parity: operators/unfold_op (im2col)."""
    x = as_tensor(x)
    k = _pair(kernel_sizes)
    s = _pair(strides)
    p = _pair(paddings)
    d = _pair(dilations)

    def fn(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
        oh = (a.shape[2] - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        ow = (a.shape[3] - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        cols = []
        for i in range(k[0]):
            for j in range(k[1]):
                patch = a[:, :, i * d[0]: i * d[0] + oh * s[0]: s[0],
                          j * d[1]: j * d[1] + ow * s[1]: s[1]]
                cols.append(patch)
        out = jnp.stack(cols, axis=2)  # N, C, k*k, OH, OW
        return out.reshape(n, c * k[0] * k[1], oh * ow)
    return run_op('unfold', fn, [x])

# ---- dropout / embedding ---------------------------------------------------
def dropout(x, p=0.5, axis=None, training=True, mode='upscale_in_train',
            name=None):
    """Parity: operators/dropout_op."""
    x = as_tensor(x)
    if not training or p == 0.0:
        return x if mode == 'upscale_in_train' else run_op(
            'dropout', lambda a: a * (1 - p), [x])
    key = rng.next_key()

    def fn(a):
        shape = a.shape if axis is None else tuple(
            a.shape[i] if i in ([axis] if isinstance(axis, int) else axis) else 1
            for i in range(a.ndim))
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        if mode == 'upscale_in_train':
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)
    return run_op('dropout', fn, [x])


def dropout2d(x, p=0.5, training=True, data_format='NCHW', name=None):
    return dropout(x, p=p, axis=[0, 1] if data_format == 'NCHW' else [0, 3],
                   training=training)


def alpha_dropout(x, p=0.5, training=True):
    x = as_tensor(x)
    if not training or p == 0.0:
        return x
    key = rng.next_key()
    alpha = 1.6732632423543772
    scale_ = 1.0507009873554805
    alpha_p = -alpha * scale_

    def fn(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        coef_a = (q + alpha_p ** 2 * q * p) ** -0.5
        coef_b = -coef_a * alpha_p * p
        return coef_a * jnp.where(keep, a, alpha_p) + coef_b
    return run_op('alpha_dropout', fn, [x])


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Parity: operators/lookup_table_v2_op."""
    x, weight = as_tensor(x), as_tensor(weight)

    def fn(w, idx):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None and padding_idx >= 0:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return run_op('lookup_table_v2', fn, [weight, x], n_nondiff=1)

# ---- losses ----------------------------------------------------------------
def _reduce_loss(loss, reduction):
    if reduction == 'mean':
        return jnp.mean(loss)
    if reduction == 'sum':
        return jnp.sum(loss)
    return loss


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fused_hard_xent(lg, idx, ignore_index):
    """Fused hard-label softmax-xent over the last axis: lg [N, C], idx [N]
    → loss [N] fp32. The custom VJP keeps only the (low-precision) logits
    and the [N] logsumexp as residuals and recomputes the softmax in the
    backward — log_softmax's own VJP would pin a full fp32 [N, C]
    log-probability tensor in HBM (4 GB at BERT's 32k×30k MLM head),
    forcing XLA into rematerialization."""
    return _fused_hard_xent_fwd(lg, idx, ignore_index)[0]


def _fused_hard_xent_fwd(lg, idx, ignore_index):
    lg32 = lg.astype(jnp.float32)
    m = jnp.max(lg32, axis=-1, keepdims=True)
    lse = m + jnp.log(jnp.sum(jnp.exp(lg32 - m), axis=-1, keepdims=True))
    picked = jnp.take_along_axis(lg32, idx[:, None], axis=-1)
    loss = (lse - picked)[:, 0]
    loss = jnp.where(idx == ignore_index, 0.0, loss)
    return loss, (lg, idx, lse)


def _fused_hard_xent_bwd(ignore_index, res, g):
    lg, idx, lse = res
    p = jnp.exp(lg.astype(jnp.float32) - lse)            # softmax, recomputed
    cols = jax.lax.broadcasted_iota(jnp.int32, lg.shape, lg.ndim - 1)
    grad = p - (cols == idx[:, None]).astype(jnp.float32)
    valid = (idx != ignore_index).astype(jnp.float32)
    dlg = (g * valid)[:, None] * grad
    return dlg.astype(lg.dtype), None


_fused_hard_xent.defvjp(_fused_hard_xent_fwd, _fused_hard_xent_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _linear_xent(x, w, idx, ignore_index, chunks, transpose_y):
    """Chunked fused projection + hard-label softmax-xent:
    x [N, H], w [V, H] (transpose_y=True, tied-embedding layout) or [H, V]
    (transpose_y=False, Linear layout), idx [N] → loss [N] fp32.
    The [N, V] logits never persist: the forward scans over N/chunks-row
    chunks keeping only per-row logsumexp, and the backward recomputes each
    chunk's logits. For BERT's MLM head (N=32k, V=30k) this trades ~5% extra
    matmul FLOPs for a 2 GB residual, which is what forces XLA into
    rematerialization of the encoder stack."""
    return _linear_xent_fwd(x, w, idx, ignore_index, chunks, transpose_y)[0]


def _lg_dims(transpose_y):
    # contracting dims for logits = x @ w(T)
    return (((1,), (1,)), ((), ())) if transpose_y else (((1,), (0,)), ((), ()))


def _linear_xent_fwd(x, w, idx, ignore_index, chunks, transpose_y):
    N, H = x.shape
    n = N // chunks
    xs = x.reshape(chunks, n, H)
    idxs = idx.reshape(chunks, n)

    def f(_, inp):
        xc, ic = inp
        lg = jax.lax.dot_general(xc, w, _lg_dims(transpose_y),
                                 preferred_element_type=jnp.float32)
        m = jnp.max(lg, axis=-1, keepdims=True)
        lse = m + jnp.log(jnp.sum(jnp.exp(lg - m), axis=-1, keepdims=True))
        picked = jnp.take_along_axis(lg, ic[:, None], axis=-1)
        loss = (lse - picked)[:, 0]
        loss = jnp.where(ic == ignore_index, 0.0, loss)
        return 0, (loss, lse[:, 0])

    _, (loss, lse) = jax.lax.scan(f, 0, (xs, idxs))
    return loss.reshape(N), (x, w, idx, lse.reshape(N))


def _linear_xent_bwd(ignore_index, chunks, transpose_y, res, g):
    x, w, idx, lse = res
    N, H = x.shape
    n = N // chunks
    xs = x.reshape(chunks, n, H)
    idxs = idx.reshape(chunks, n)
    lses = lse.reshape(chunks, n)
    gs = g.reshape(chunks, n)

    def f(dw, inp):
        xc, ic, lsec, gc = inp
        lg = jax.lax.dot_general(xc, w, _lg_dims(transpose_y),
                                 preferred_element_type=jnp.float32)
        p = jnp.exp(lg - lsec[:, None])
        cols = jax.lax.broadcasted_iota(jnp.int32, lg.shape, 1)
        valid = (ic != ignore_index).astype(jnp.float32)
        dl = (p - (cols == ic[:, None]).astype(jnp.float32)) \
            * (gc * valid)[:, None]
        dlc = dl.astype(x.dtype)
        if transpose_y:
            dxc = jax.lax.dot_general(dlc, w, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            dw_c = jax.lax.dot_general(dlc, xc, (((0,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32)
        else:
            dxc = jax.lax.dot_general(dlc, w, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            dw_c = jax.lax.dot_general(xc, dlc, (((0,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32)
        return dw + dw_c, dxc.astype(x.dtype)

    dw, dx = jax.lax.scan(f, jnp.zeros(w.shape, jnp.float32),
                          (xs, idxs, lses, gs))
    return dx.reshape(N, H), dw.astype(w.dtype), None


_linear_xent.defvjp(_linear_xent_fwd, _linear_xent_bwd)


def fused_linear_cross_entropy(x, weight, label, ignore_index=-100,
                               reduction='mean', chunks=8,
                               transpose_y=True):
    """Tied-projection cross-entropy without materializing [N, V] logits:
    x [..., H] @ weight^T (weight [V, H], transpose_y=True) or x @ weight
    (weight [H, V], transpose_y=False) → softmax-xent against label [...].
    TPU-native analogue of the reference's fused softmax_with_cross_entropy
    applied to the LM head (operators/softmax_with_cross_entropy_op) — the
    chunking serves XLA memory planning instead of CUDA shared memory."""
    x, weight, label = as_tensor(x), as_tensor(weight), as_tensor(label)
    H = x.shape[-1]

    def fn(xa, wa, lb):
        lead = xa.shape[:-1]
        N = int(np.prod(lead))
        c = chunks
        while N % c:
            c -= 1
        out = _linear_xent(xa.reshape(N, H), wa,
                           lb.reshape(N).astype(jnp.int32), ignore_index, c,
                           transpose_y)
        out = out.reshape(lead)
        return _reduce_loss(out, reduction)
    return run_op('fused_linear_cross_entropy', fn, [x, weight, label],
                  n_nondiff=1)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    """Parity: operators/softmax_with_cross_entropy_op (fused, numerically
    stable log-softmax + NLL)."""
    logits, label = as_tensor(logits), as_tensor(label)

    if soft_label:
        def fn(lg, lb):
            logp = jax.nn.log_softmax(lg, axis=axis)
            return -jnp.sum(lb * logp, axis=axis, keepdims=True)
        loss = run_op('softmax_with_cross_entropy', fn, [logits, label])
    else:
        nd_axis = axis % logits.ndim

        def fn(lg, lb):
            idx = lb.astype(jnp.int32)
            squeezed = idx.shape == (lg.shape[:nd_axis]
                                     + lg.shape[nd_axis + 1:])
            if nd_axis == lg.ndim - 1 and squeezed:
                # fast path: fused kernel over [N, C]
                C = lg.shape[-1]
                out = _fused_hard_xent(lg.reshape(-1, C),
                                       idx.reshape(-1), ignore_index)
                return jnp.expand_dims(out.reshape(idx.shape),
                                       -1).astype(lg.dtype)
            logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=axis)
            idx_exp = jnp.expand_dims(idx, axis) if squeezed else idx
            picked = jnp.take_along_axis(logp, idx_exp, axis=axis)
            loss = -picked
            loss = jnp.where(idx_exp == ignore_index, 0.0, loss)
            return loss.astype(lg.dtype)
        loss = run_op('softmax_with_cross_entropy', fn, [logits, label], n_nondiff=1)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction='mean', soft_label=False, axis=-1,
                  use_softmax=True, name=None):
    """Parity: nn/functional/loss.py cross_entropy → softmax_with_cross_entropy."""
    input, label = as_tensor(input), as_tensor(label)
    if label.ndim == input.ndim and not soft_label and label.shape[axis % input.ndim] == 1:
        from . import manip
        label = manip.squeeze(label, axis=axis)
    if use_softmax:
        loss = softmax_with_cross_entropy(input, label, soft_label=soft_label,
                                          ignore_index=ignore_index, axis=axis)
    else:
        def fn(lg, lb):
            logp = jnp.log(jnp.clip(lg, 1e-12, None))
            idx_exp = jnp.expand_dims(lb.astype(jnp.int32), axis)
            return -jnp.take_along_axis(logp, idx_exp, axis=axis)
        loss = run_op('cross_entropy2', fn, [input, label], n_nondiff=1)

    if weight is not None:
        weight = as_tensor(weight)
        def wfn(ls, w, lb):
            wt = jnp.take(w, lb.astype(jnp.int32))
            return ls * jnp.expand_dims(wt, axis)
        loss = run_op('ce_weight', wfn, [loss, weight, label], n_nondiff=1)

    if reduction == 'none':
        return loss
    def rfn(ls):
        return _reduce_loss(jnp.squeeze(ls, axis=axis) if ls.ndim > label.ndim else ls,
                            reduction)
    return run_op('reduce_loss', rfn, [loss])


def nll_loss(input, label, weight=None, ignore_index=-100, reduction='mean',
             name=None):
    input, label = as_tensor(input), as_tensor(label)
    def fn(lg, lb):
        idx = jnp.expand_dims(lb.astype(jnp.int32), 1)
        picked = -jnp.take_along_axis(lg, idx, axis=1)[:, 0]
        return _reduce_loss(picked, reduction)
    return run_op('nll_loss', fn, [input, label], n_nondiff=1)


def mse_loss(input, label, reduction='mean', name=None):
    input = as_tensor(input)
    label = as_tensor(label, ref=input)
    return run_op('mse_loss',
                  lambda a, b: _reduce_loss((a - b) ** 2, reduction),
                  [input, label])


def l1_loss(input, label, reduction='mean', name=None):
    input = as_tensor(input)
    label = as_tensor(label, ref=input)
    return run_op('l1_loss',
                  lambda a, b: _reduce_loss(jnp.abs(a - b), reduction),
                  [input, label])


def smooth_l1_loss(input, label, reduction='mean', delta=1.0, name=None):
    input = as_tensor(input)
    label = as_tensor(label, ref=input)
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce_loss(loss, reduction)
    return run_op('smooth_l1_loss', fn, [input, label])


def binary_cross_entropy(input, label, weight=None, reduction='mean', name=None):
    input = as_tensor(input)
    label = as_tensor(label, ref=input)
    def fn(a, b):
        a = jnp.clip(a, 1e-12, 1.0 - 1e-7)
        loss = -(b * jnp.log(a) + (1 - b) * jnp.log(1 - a))
        if weight is not None:
            loss = loss * (weight.data if isinstance(weight, Tensor) else weight)
        return _reduce_loss(loss, reduction)
    return run_op('bce_loss', fn, [input, label])


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction='mean', pos_weight=None,
                                     name=None):
    """Parity: operators/sigmoid_cross_entropy_with_logits_op."""
    logit = as_tensor(logit)
    label = as_tensor(label, ref=logit)
    def fn(a, b):
        maxv = jnp.maximum(a, 0)
        loss = maxv - a * b + jnp.log1p(jnp.exp(-jnp.abs(a)))
        if pos_weight is not None:
            pw = pos_weight.data if isinstance(pos_weight, Tensor) else pos_weight
            log_w = (pw - 1) * b + 1
            loss = loss * log_w
        if weight is not None:
            loss = loss * (weight.data if isinstance(weight, Tensor) else weight)
        return _reduce_loss(loss, reduction)
    return run_op('sigmoid_cross_entropy_with_logits', fn, [logit, label])

sigmoid_cross_entropy_with_logits = binary_cross_entropy_with_logits


def kl_div(input, label, reduction='mean', name=None):
    input = as_tensor(input)
    label = as_tensor(label, ref=input)
    def fn(a, b):
        loss = b * (jnp.log(jnp.clip(b, 1e-12, None)) - a)
        if reduction == 'batchmean':
            return jnp.sum(loss) / a.shape[0]
        return _reduce_loss(loss, reduction)
    return run_op('kldiv_loss', fn, [input, label])


def hinge_loss(input, label):
    input = as_tensor(input)
    label = as_tensor(label, ref=input)
    return run_op('hinge_loss',
                  lambda a, b: jnp.maximum(0.0, 1.0 - (2 * b - 1) * a),
                  [input, label])


def margin_ranking_loss(input, other, label, margin=0.0, reduction='mean'):
    input = as_tensor(input)
    other = as_tensor(other, ref=input)
    label = as_tensor(label, ref=input)
    return run_op('margin_rank_loss',
                  lambda a, b, l: _reduce_loss(
                      jnp.maximum(0.0, -l * (a - b) + margin), reduction),
                  [input, other, label])


def log_loss(input, label, epsilon=1e-4):
    input = as_tensor(input)
    label = as_tensor(label, ref=input)
    return run_op('log_loss',
                  lambda a, b: -b * jnp.log(a + epsilon)
                  - (1 - b) * jnp.log(1 - a + epsilon),
                  [input, label])


def square_error_cost(input, label):
    input = as_tensor(input)
    label = as_tensor(label, ref=input)
    return run_op('squared_l2_distance', lambda a, b: (a - b) ** 2, [input, label])


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    x1 = as_tensor(x1)
    x2 = as_tensor(x2, ref=x1)
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return run_op('cos_sim', fn, [x1, x2])


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = as_tensor(label)
    def fn(lb):
        k = lb.shape[-1]
        if prior_dist is not None:
            pd = prior_dist.data if isinstance(prior_dist, Tensor) else prior_dist
            return (1 - epsilon) * lb + epsilon * pd
        return (1 - epsilon) * lb + epsilon / k
    return run_op('label_smooth', fn, [label])

# ---- misc nn ---------------------------------------------------------------
def interpolate(x, size=None, scale_factor=None, mode='nearest',
                align_corners=False, align_mode=0, data_format='NCHW',
                name=None):
    """Parity: operators/interpolate_v2_op (nearest/bilinear)."""
    x = as_tensor(x)
    n, c, h, w = x.shape
    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        oh, ow = int(size[0]), int(size[1])
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else (scale_factor, scale_factor)
        oh, ow = int(h * sf[0]), int(w * sf[1])

    method = {'nearest': 'nearest', 'bilinear': 'linear', 'bicubic': 'cubic',
              'area': 'linear'}[mode]

    def fn(a):
        a_ = jnp.transpose(a, (0, 2, 3, 1))
        out = jax.image.resize(a_, (n, oh, ow, c), method=method)
        return jnp.transpose(out, (0, 3, 1, 2)).astype(a.dtype)
    return run_op('interpolate_v2', fn, [x])


upsample = interpolate


def grid_sample(x, grid, mode='bilinear', padding_mode='zeros',
                align_corners=True):
    x, grid = as_tensor(x), as_tensor(grid)
    def fn(a, g):
        n, c, h, w = a.shape
        gx = (g[..., 0] + 1) * (w - 1) / 2 if align_corners else ((g[..., 0] + 1) * w - 1) / 2
        gy = (g[..., 1] + 1) * (h - 1) / 2 if align_corners else ((g[..., 1] + 1) * h - 1) / 2
        import functools
        def sample_one(img, cx, cy):
            coords = jnp.stack([cy.reshape(-1), cx.reshape(-1)])
            out = jax.vmap(lambda ch: jax.scipy.ndimage.map_coordinates(
                ch, coords, order=1, mode='constant'))(img)
            return out.reshape(c, *cx.shape)
        return jax.vmap(sample_one)(a, gx, gy)
    return run_op('grid_sampler', fn, [x, grid])


def affine_grid(theta, out_shape, align_corners=True):
    theta = as_tensor(theta)
    n, c, h, w = [int(v) for v in (out_shape.tolist() if isinstance(out_shape, Tensor) else out_shape)]
    def fn(th):
        ys = jnp.linspace(-1, 1, h) if align_corners else jnp.linspace(-1 + 1 / h, 1 - 1 / h, h)
        xs = jnp.linspace(-1, 1, w) if align_corners else jnp.linspace(-1 + 1 / w, 1 - 1 / w, w)
        gy, gx = jnp.meshgrid(ys, xs, indexing='ij')
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # H,W,3
        return jnp.einsum('nij,hwj->nhwi', th, base)
    return run_op('affine_grid', fn, [theta])


def fused_softmax_mask_upper_triangle(x):
    """Parity: operators/fused_softmax_mask_upper_triangle_op (causal mask)."""
    x = as_tensor(x)
    def fn(a):
        L = a.shape[-1]
        mask = jnp.tril(jnp.ones((L, L), bool))
        return jax.nn.softmax(jnp.where(mask, a, -1e9), axis=-1)
    return run_op('fused_softmax_mask_upper_triangle', fn, [x])


def temporal_shift(x, seg_num, shift_ratio=0.25):
    x = as_tensor(x)
    def fn(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        a = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([a[:, 1:, :fold], jnp.zeros_like(a[:, :1, :fold])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(a[:, :1, fold:2 * fold]),
                                 a[:, :-1, fold:2 * fold]], axis=1)
        rest = a[:, :, 2 * fold:]
        return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)
    return run_op('temporal_shift', fn, [x])


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    anchor, positive = as_tensor(anchor), as_tensor(positive)
    labels = as_tensor(labels)
    def fn(a, p, lb):
        sim = jnp.matmul(a, p.T)
        lbl = lb.reshape(-1, 1)
        tgt = (lbl == lbl.T).astype(a.dtype)
        tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1))
                        + jnp.mean(jnp.sum(p * p, axis=1))) / 2
        return ce + reg
    return run_op('npair_loss', fn, [anchor, positive, labels], n_nondiff=1)


def one_hot(x, num_classes):
    from . import manip
    return manip.one_hot(x, num_classes)


def sequence_mask(lengths, maxlen=None, dtype='int64'):
    lengths = as_tensor(lengths)
    ml = int(maxlen) if maxlen is not None else int(np.asarray(lengths.data).max())
    def fn(l):
        return (jnp.arange(ml)[None, :] < l[:, None])
    out = fn(lengths.data.reshape(-1))
    out = out.reshape(tuple(lengths.shape) + (ml,))
    from ..core import dtypes as _dt
    return Tensor(out.astype(_dt.convert_dtype(dtype)))


# ---- loss/functional long tail --------------------------------------------
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction='mean'):
    input = as_tensor(input)
    positive = as_tensor(positive, ref=input)
    negative = as_tensor(negative, ref=input)
    def fn(a, pos, neg):
        def dist(x, y):
            return jnp.power(jnp.sum(jnp.power(jnp.abs(x - y) + epsilon, p),
                                     axis=-1), 1.0 / p)
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        return _reduce_loss(jnp.maximum(d_pos - d_neg + margin, 0.0),
                            reduction)
    return run_op('triplet_margin_loss', fn, [input, positive, negative])


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction='mean'):
    input1 = as_tensor(input1)
    input2 = as_tensor(input2, ref=input1)
    label = as_tensor(label)
    def fn(a, b, l):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(l > 0, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce_loss(loss, reduction)
    return run_op('cosine_embedding_loss', fn, [input1, input2, label],
                  n_nondiff=1)


def soft_margin_loss(input, label, reduction='mean'):
    input = as_tensor(input)
    label = as_tensor(label, ref=input)
    return run_op('soft_margin_loss',
                  lambda a, l: _reduce_loss(jnp.log1p(jnp.exp(-l * a)),
                                            reduction), [input, label])


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction='mean'):
    input = as_tensor(input)
    label = as_tensor(label)
    def fn(a, l):
        n, c = a.shape
        correct = jnp.take_along_axis(a, l[:, None].astype(jnp.int32),
                                      axis=1)
        loss = jnp.power(jnp.maximum(margin - correct + a, 0.0), p)
        mask = jax.nn.one_hot(l, c) == 0
        return _reduce_loss(jnp.sum(loss * mask, 1) / c, reduction)
    return run_op('multi_margin_loss', fn, [input, label], n_nondiff=1)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction='mean'):
    """Parity: operators/warpctc_op — CTC via dynamic programming in
    log-space (lax.scan over time)."""
    log_probs = as_tensor(log_probs)   # [T, B, C]
    labels = as_tensor(labels)         # [B, S]
    input_lengths = as_tensor(input_lengths)
    label_lengths = as_tensor(label_lengths)

    def fn(lp, lb, il, ll):
        T, B, C = lp.shape
        S = lb.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lb.astype(jnp.int32))
        L = 2 * S + 1
        neg = -1e30
        alpha0 = jnp.full((B, L), neg)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0])

        def lse2(a, b):
            m = jnp.maximum(a, b)
            return m + jnp.log(jnp.exp(a - m) + jnp.exp(b - m))

        def step(alpha, t):
            prev1 = jnp.concatenate([jnp.full((B, 1), neg),
                                     alpha[:, :-1]], 1)
            prev2 = jnp.concatenate([jnp.full((B, 2), neg),
                                     alpha[:, :-2]], 1)
            can_skip = jnp.concatenate(
                [jnp.zeros((B, 2), bool),
                 (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2])], 1)
            a = lse2(alpha, prev1)
            a = jnp.where(can_skip, lse2(a, prev2), a)
            emit = jnp.take_along_axis(lp[t], ext, axis=1)
            new = a + emit
            return jnp.where(t < il[:, None], new, alpha), None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        end1 = 2 * ll.astype(jnp.int32)
        end2 = end1 - 1
        a1 = jnp.take_along_axis(alpha, end1[:, None], 1)[:, 0]
        a2 = jnp.take_along_axis(alpha, jnp.maximum(end2, 0)[:, None],
                                 1)[:, 0]
        nll = -lse2(a1, a2)
        return _reduce_loss(nll / jnp.maximum(ll.astype(jnp.float32), 1.0),
                            reduction)
    return run_op('warpctc', fn, [log_probs, labels, input_lengths,
                                  label_lengths], n_nondiff=3)


def glu(x, axis=-1):
    x = as_tensor(x)
    def fn(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)
    return run_op('glu', fn, [x])


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    x = as_tensor(x)
    y = as_tensor(y, ref=x)
    return run_op('pairwise_distance',
                  lambda a, b: jnp.power(
                      jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p),
                              axis=-1, keepdims=keepdim), 1.0 / p), [x, y])


def pixel_unshuffle(x, downscale_factor, data_format='NCHW'):
    x = as_tensor(x)
    r = downscale_factor
    def fn(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // r, r, w // r, r)
        a = a.transpose(0, 1, 3, 5, 2, 4)
        return a.reshape(n, c * r * r, h // r, w // r)
    return run_op('pixel_unshuffle', fn, [x])


def channel_shuffle(x, groups, data_format='NCHW'):
    x = as_tensor(x)
    def fn(a):
        n, c, h, w = a.shape
        a = a.reshape(n, groups, c // groups, h, w)
        return a.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
    return run_op('channel_shuffle', fn, [x])


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """Parity: operators/fold_op (col2im) — adjoint of unfold."""
    x = as_tensor(x)
    oh, ow = _pair(output_sizes)
    k = _pair(kernel_sizes)
    s = _pair(strides)
    p = _pair(paddings)
    d = _pair(dilations)

    def fn(a):
        n, ckk, l = a.shape
        c = ckk // (k[0] * k[1])
        hh = oh + 2 * p[0]
        ww = ow + 2 * p[1]
        nh = (hh - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        nw = (ww - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        out = jnp.zeros((n, c, hh, ww), a.dtype)
        cols = a.reshape(n, c, k[0], k[1], nh, nw)
        for i in range(k[0]):
            for j in range(k[1]):
                out = out.at[:, :, i * d[0]: i * d[0] + nh * s[0]: s[0],
                             j * d[1]: j * d[1] + nw * s[1]: s[1]].add(
                    cols[:, :, i, j])
        return out[:, :, p[0]:p[0] + oh, p[1]:p[1] + ow]
    return run_op('fold', fn, [x])


# ---------------------------------------------------------------------------
# 1-D / 3-D pooling + transpose-conv remainder (paddle.nn.functional sheet)
# ---------------------------------------------------------------------------

def _pool_nd(x, nd, ksize, stride, padding, kind, ceil_mode, exclusive):
    """Shared reduce_window pooling for 1-D/3-D (2-D rides the tuned
    max_pool2d/avg_pool2d paths). ceil_mode adds high-side padding;
    exclusive average divides by the real (unpadded) window count."""
    x = as_tensor(x)
    def tolist(v):
        return [v] * nd if isinstance(v, int) else list(v)
    ksize, stride, padding = tolist(ksize), \
        tolist(stride if stride is not None else ksize), tolist(padding)

    def fn(a):
        dims = (1, 1) + tuple(ksize)
        strides = (1, 1) + tuple(stride)
        spatial = a.shape[2:]
        hi = []
        for d, k, st, p in zip(spatial, ksize, stride, padding):
            if ceil_mode:
                out = -(-(d + 2 * p - k) // st) + 1
                hi.append(max(int((out - 1) * st + k - d - p), p))
            else:
                hi.append(p)
        pads = ((0, 0), (0, 0)) + tuple(
            (p, h) for p, h in zip(padding, hi))
        if kind == 'max':
            return jax.lax.reduce_window(
                a, -jnp.inf, jax.lax.max, dims, strides, pads)
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add, dims, strides,
                                  pads)
        if kind == 'sum':
            return s
        if exclusive and (any(padding) or any(
                h != p for p, h in zip(padding, hi))):
            cnt = jax.lax.reduce_window(
                jnp.ones_like(a), 0.0, jax.lax.add, dims, strides, pads)
            return s / jnp.maximum(cnt, 1.0)
        return s / float(np.prod(ksize))
    return run_op(f'pool{nd}d_{kind}', fn, [x])


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format='NCDHW', name=None):
    """paddle.nn.functional.max_pool3d (operators/pool_op.cc 3-D)."""
    if return_mask:
        raise NotImplementedError("max_pool3d return_mask: use the 2-D "
                                  "path per-slice if indices are needed")
    return _pool_nd(x, 3, kernel_size, stride, padding, 'max',
                    ceil_mode, True)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None,
               data_format='NCDHW', name=None):
    """paddle.nn.functional.avg_pool3d. divisor_override divides the
    raw window SUM (paddle semantics) — it replaces both the kernel
    volume and the exclusive count."""
    if divisor_override is not None:
        out = _pool_nd(x, 3, kernel_size, stride, padding, 'sum',
                       ceil_mode, False)
        from .common import as_tensor as _at
        return out * (1.0 / float(divisor_override))
    return _pool_nd(x, 3, kernel_size, stride, padding, 'avg',
                    ceil_mode, exclusive)


def _adaptive_pool_nd(x, nd, output_size, kind):
    """Adaptive pooling with the reference's floor/ceil bin edges:
    bin i covers [floor(i*D/od), ceil((i+1)*D/od)). Output sizes are
    static, so each bin is a static slice reduce — XLA fuses the
    (small) slice set; uneven bins are exact, not approximated."""
    x = as_tensor(x)
    sizes = [output_size] * nd if isinstance(output_size, int) else \
        list(output_size)

    def fn(a):
        out = a
        for ax in range(nd):
            axis = 2 + ax
            D = out.shape[axis]
            od = int(sizes[ax])
            slabs = []
            for i in range(od):
                lo = (i * D) // od
                hi = -(-((i + 1) * D) // od)
                sl = jax.lax.slice_in_dim(out, lo, hi, axis=axis)
                red = sl.max(axis=axis, keepdims=True) if kind == 'max' \
                    else sl.mean(axis=axis, keepdims=True)
                slabs.append(red)
            out = jnp.concatenate(slabs, axis=axis)
        return out
    return run_op(f'adaptive_pool{nd}d_{kind}', fn, [x])


def adaptive_avg_pool1d(x, output_size, name=None):
    """paddle.nn.functional.adaptive_avg_pool1d ([N, C, L])."""
    return _adaptive_pool_nd(x, 1, output_size, 'avg')


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    """paddle.nn.functional.adaptive_max_pool1d."""
    if return_mask:
        raise NotImplementedError("adaptive_max_pool1d return_mask")
    return _adaptive_pool_nd(x, 1, output_size, 'max')


def adaptive_avg_pool3d(x, output_size, data_format='NCDHW', name=None):
    """paddle.nn.functional.adaptive_avg_pool3d ([N, C, D, H, W])."""
    return _adaptive_pool_nd(x, 3, output_size, 'avg')


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    """paddle.nn.functional.adaptive_max_pool3d."""
    if return_mask:
        raise NotImplementedError("adaptive_max_pool3d return_mask")
    return _adaptive_pool_nd(x, 3, output_size, 'max')


def _opad_from_output_size(in_sizes, k, stride, padding, dilation,
                           opad, output_size):
    """Derive output_padding from a requested output_size (paddle
    derives it as output_size - default_size and validates
    0 <= opad < stride)."""
    if output_size is None:
        return opad
    sizes = [output_size] * len(in_sizes) \
        if isinstance(output_size, int) else list(output_size)
    out = []
    for d, kk, st, p, dil, want in zip(in_sizes, k, stride, padding,
                                       dilation, sizes):
        base = (int(d) - 1) * st - 2 * p + dil * (kk - 1) + 1
        extra = int(want) - base
        if not 0 <= extra < st:
            raise ValueError(
                f"output_size {want} unreachable: base {base}, "
                f"stride {st} (need base <= output_size < base+stride)")
        out.append(extra)
    return tuple(out)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format='NCL', name=None):
    """paddle.nn.functional.conv1d_transpose — rides the 2-D kernel
    with a singleton height."""
    from .manip import squeeze, unsqueeze
    if output_size is not None:
        output_padding = _opad_from_output_size(
            [as_tensor(x).shape[2]], [as_tensor(weight).shape[2]],
            [stride if isinstance(stride, int) else stride[0]],
            [padding if isinstance(padding, int) else padding[0]],
            [dilation if isinstance(dilation, int) else dilation[0]],
            output_padding, output_size)[0]
    x4 = unsqueeze(x, 2)                       # [N, C, 1, L]
    w = as_tensor(weight)
    from ..core.tensor import Tensor as _T
    w4 = _T(w.data[:, :, None, :])             # [I, O, 1, K]
    out = conv2d_transpose(x4, w4, bias, stride=(1, stride),
                           padding=(0, padding),
                           output_padding=(0, output_padding),
                           dilation=(1, dilation), groups=groups)
    return squeeze(out, 2)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format='NCDHW', name=None):
    """paddle.nn.functional.conv3d_transpose (weight layout IODHW):
    conv_general_dilated with the lo/hi = dilation*(k-1) - p transpose
    transform and lhs_dilation = stride (same convention as the 2-D
    path above)."""
    x, weight = as_tensor(x), as_tensor(weight)
    def to3(v):
        return (v, v, v) if isinstance(v, int) else tuple(v)
    stride, dilation, padding = to3(stride), to3(dilation), to3(padding)
    opad = to3(output_padding)
    k = weight.shape[2:]
    if output_size is not None:
        opad = _opad_from_output_size(x.shape[2:], k, stride, padding,
                                      dilation, opad, output_size)
    pads = [(d * (kk - 1) - p, d * (kk - 1) - p + op)
            for d, kk, p, op in zip(dilation, k, padding, opad)]
    cin = int(weight.shape[0])
    tensors = [x, weight] + ([as_tensor(bias)] if bias is not None
                             else [])

    def fn(a, w, *rest):
        w2 = jnp.flip(w, axis=(2, 3, 4))
        if groups > 1:
            wg = w2.reshape(groups, cin // groups, *w2.shape[1:])
            w2 = jnp.concatenate(
                [g.transpose(1, 0, 2, 3, 4) for g in wg], axis=0)
        else:
            w2 = w2.transpose(1, 0, 2, 3, 4)
        out = jax.lax.conv_general_dilated(
            a, w2, window_strides=(1, 1, 1), padding=pads,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=('NCDHW', 'OIDHW', 'NCDHW'),
            feature_group_count=groups)
        if rest:
            out = out + rest[0].reshape(1, -1, 1, 1, 1)
        return out
    return run_op('conv3d_transpose', fn, tensors)


def bilinear(x1, x2, weight, bias=None, name=None):
    """paddle.nn.functional.bilinear (operators/bilinear_tensor_product
    _op.cc): out[n, o] = x1[n, :] @ W[o] @ x2[n, :] (+ bias)."""
    x1, x2 = as_tensor(x1), as_tensor(x2)
    weight = as_tensor(weight, ref=x1)
    tensors = [x1, x2, weight] + ([as_tensor(bias)] if bias is not None
                                  else [])

    def fn(a, b, w, *rest):
        out = jnp.einsum('ni,oij,nj->no', a, w, b)
        if rest:
            out = out + rest[0].reshape(1, -1)
        return out
    return run_op('bilinear', fn, tensors)


def dropout3d(x, p=0.5, training=True, data_format='NCDHW', name=None):
    """paddle.nn.functional.dropout3d — drops whole channels of the
    5-D input (the 3-D analogue of dropout2d)."""
    x = as_tensor(x)
    if not training or p == 0.0:
        return x
    from ..core import rng as rng_mod
    key = rng_mod.next_key()

    def fn(a):
        keep = jax.random.bernoulli(key, 1.0 - p,
                                    (a.shape[0], a.shape[1], 1, 1, 1))
        return jnp.where(keep, a / (1.0 - p), 0.0)
    return run_op('dropout3d', fn, [x])


def dice_loss(input, label, epsilon=1e-5):
    """paddle.nn.functional.dice_loss: 1 - 2|X∩Y| / (|X|+|Y|) over the
    trailing class axis (operators/dice_loss semantics; the static
    fluid spelling lives in static/nn.py)."""
    input = as_tensor(input)
    label = as_tensor(label, ref=input)

    def fn(p, l):
        l = l.astype(p.dtype)
        if l.shape[-1] == 1 and p.shape[-1] > 1:
            l = jax.nn.one_hot(l[..., 0].astype(jnp.int32),
                               p.shape[-1], dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = (p * l).sum(red)
        union = p.sum(red) + l.sum(red)
        return (1.0 - (2.0 * inter + epsilon)
                / (union + epsilon)).mean()
    return run_op('dice_loss', fn, [input, label], n_nondiff=1)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25,
                       gamma=2.0, reduction='sum', name=None):
    """paddle.nn.functional.sigmoid_focal_loss (nn/functional/loss.py:
    1555 — the 2.x API: float one-hot labels, optional normalizer,
    reduction; the fluid fg_num spelling lives in vision.detection)."""
    logit = as_tensor(logit)
    label = as_tensor(label, ref=logit)
    tensors = [logit, label] + ([as_tensor(normalizer)]
                                if normalizer is not None else [])

    def fn(x, y, *rest):
        y = y.astype(x.dtype)
        sig = jax.nn.sigmoid(x)
        ls = jax.nn.log_sigmoid(x)
        lns = jax.nn.log_sigmoid(-x)
        loss = -y * alpha * (1 - sig) ** gamma * ls \
            - (1 - y) * (1 - alpha) * sig ** gamma * lns
        if rest:
            loss = loss / rest[0].reshape(())
        if reduction == 'sum':
            return loss.sum()
        if reduction == 'mean':
            return loss.mean()
        return loss
    return run_op('sigmoid_focal_loss_v2', fn, tensors, n_nondiff=1)


# in-place spellings: compute out-of-place (JAX buffers are immutable)
# and rebind the input tensor's buffer via the shared inplace_rebind,
# which grafts the alias into the autograd tape (gradients through
# later uses of x stay exact) — same contract as the api_tail spellings
def relu_(x, name=None):
    from ..core.tensor import inplace_rebind
    return inplace_rebind(x, relu(x))


def softmax_(x, axis=-1, dtype=None, name=None):
    from ..core.tensor import inplace_rebind
    return inplace_rebind(x, softmax(x, axis=axis))
