"""Ring attention — sequence/context parallelism over the 'sp' mesh axis.

NET-NEW capability (the reference has none — SURVEY.md §5.7 verified absent);
designed TPU-first per the survey's recommendation: sequence dim sharded over
a mesh axis, K/V blocks rotating around the ICI ring via
`lax.ppermute` while each device accumulates its queries' attention with an
online softmax (blockwise/flash-style), so attention over a sequence of
length L costs O(L/sp) memory per chip and the K/V transfer fully overlaps
with per-block compute under XLA's async collectives.

Causality across the ring: each device holds a contiguous sequence chunk
(chunk index = axis position). A rotating K/V block is
  * fully visible   if src_chunk <  my_chunk
  * causal-diagonal if src_chunk == my_chunk (lower-triangular in-block)
  * invisible       if src_chunk >  my_chunk  (skipped via mask)
"""
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..core.autograd import run_op

NEG_INF = -1e30


def _ring_attention_arrays(q, k, v, axis_name, causal=True, sp=None,
                           dropout=0.0, key=None):
    """q/k/v: [B, nh, Lc, hd] local chunks; returns [B, nh, Lc, hd]."""
    if sp is None:
        sp = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, nh, Lc, hd = q.shape
    scale = 1.0 / math.sqrt(hd)
    qf = q.astype(jnp.float32) * scale
    if dropout > 0.0 and key is None:
        from ..core import rng as rng_mod
        key = rng_mod.next_key()

    m0 = jnp.full((B, nh, Lc, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, nh, Lc, 1), jnp.float32)
    acc0 = jnp.zeros((B, nh, Lc, hd), jnp.float32)

    def compute_block(kk, vv, m, l, acc, src):
        s = jnp.einsum('bhqd,bhkd->bhqk', qf, kk.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        if causal:
            rows = lax.broadcasted_iota(jnp.int32, (Lc, Lc), 0) + my * Lc
            cols = lax.broadcasted_iota(jnp.int32, (Lc, Lc), 1) + src * Lc
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        # normalizer uses the UNdropped probs (standard attention-dropout
        # semantics: mask applied to softmax output, denominator unchanged)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        if dropout > 0.0:
            bk = jax.random.fold_in(jax.random.fold_in(key, my), src)
            keep = jax.random.bernoulli(bk, 1.0 - dropout, p.shape)
            p = jnp.where(keep, p / (1.0 - dropout), 0.0)
        acc_new = acc * alpha + jnp.einsum(
            'bhqk,bhkd->bhqd', p, vv.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    def step(carry, i):
        kk, vv, m, l, acc = carry
        src = (my + i) % sp  # which chunk kk/vv currently holds
        if causal:
            # invisible blocks (src > my): skip the attention math entirely
            # (≈half the ring FLOPs); predicate is per-device but contains
            # no collectives, so cond is safe under shard_map.
            m, l, acc = lax.cond(
                src <= my,
                lambda args: compute_block(*args, src),
                lambda args: (args[2], args[3], args[4]),
                (kk, vv, m, l, acc))
        else:
            m, l, acc = compute_block(kk, vv, m, l, acc, src)
        # rotate K/V to the next device (overlaps with next block's matmul);
        # the final rotation's result is never read but keeping it
        # unconditional keeps the collective schedule uniform across devices
        perm = [(j, (j - 1) % sp) for j in range(sp)]
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return (kk, vv, m, l, acc), None

    (kk, vv, m, l, acc), _ = lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(sp))
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_attention(q, k, v, axis_name='sp', causal=True, sp=None):
    """Tensor-level op: q/k/v [B, nh, Lc, hd] (sequence-chunk local)."""
    def fn(qa, ka, va):
        return _ring_attention_arrays(qa, ka, va, axis_name, causal=causal,
                                      sp=sp)
    return run_op('ring_attention', fn, [q, k, v])


def ring_causal_qkv(qkv, num_heads, head_dim, axis_name='sp', sp=None,
                    dropout=0.0):
    """GPTAttention entry: qkv [B, Lc, nh*3*hd] ((head,3,hd) packing) →
    [B, Lc, nh*hd]."""
    if dropout > 0.0:
        from ..core import rng as rng_mod
        key = rng_mod.next_key()
    else:
        key = None

    def fn(a):
        B, Lc, _ = a.shape
        x = a.reshape(B, Lc, num_heads, 3, head_dim)
        q = x[:, :, :, 0].transpose(0, 2, 1, 3)
        k = x[:, :, :, 1].transpose(0, 2, 1, 3)
        v = x[:, :, :, 2].transpose(0, 2, 1, 3)
        o = _ring_attention_arrays(q, k, v, axis_name, causal=True, sp=sp,
                                   dropout=dropout, key=key)
        return o.transpose(0, 2, 1, 3).reshape(B, Lc, num_heads * head_dim)
    return run_op('ring_attention_qkv', fn, [qkv])


# ---- all-to-all sequence parallelism (DeepSpeed-Ulysses style) -------------
def ulysses_attention(qkv, num_heads, head_dim, axis_name='sp', sp=None):
    """Alternative long-context scheme: all-to-all swaps the sequence
    sharding for a head sharding, runs FULL-sequence attention on nh/sp
    local heads, and swaps back — 2 AllToAlls instead of a ring, better when
    nh ≥ sp and per-chip memory allows L-length scores blocks.
    qkv [B, Lc, nh*3*hd] → [B, Lc, nh*hd]."""
    if sp is None:
        from ..distributed import topology_runtime
        sp = topology_runtime.axis_size(axis_name)
    if sp and num_heads % sp != 0:
        raise ValueError(
            f"ulysses_attention: num_heads ({num_heads}) must be divisible "
            f"by the sequence-parallel degree ({sp})")

    def fn(a):
        B, Lc, _ = a.shape
        x = a.reshape(B, Lc, num_heads, 3 * head_dim)
        # [B, Lc, nh, 3hd] → all-to-all: split heads, concat sequence
        x = lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                           tiled=True)  # [B, L, nh/sp, 3hd]
        L = x.shape[1]
        nh_loc = x.shape[2]
        x5 = x.reshape(B, L, nh_loc, 3, head_dim)
        q = x5[:, :, :, 0].transpose(0, 2, 1, 3)
        k = x5[:, :, :, 1].transpose(0, 2, 1, 3)
        v = x5[:, :, :, 2].transpose(0, 2, 1, 3)
        s = jnp.einsum('bhqd,bhkd->bhqk', q.astype(jnp.float32),
                       k.astype(jnp.float32)) / math.sqrt(head_dim)
        mask = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(mask, s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum('bhqk,bhkd->bhqd', p, v.astype(jnp.float32))
        o = o.astype(a.dtype).transpose(0, 2, 1, 3)  # B, L, nh/sp, hd
        # swap back: split sequence, concat heads
        o = lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                           tiled=True)  # B, Lc, nh, hd
        return o.reshape(B, Lc, num_heads * head_dim)
    return run_op('ulysses_attention', fn, [qkv])
