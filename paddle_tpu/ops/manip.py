"""Shape/layout manipulation ops.

Reference parity: operators/ reshape, transpose, concat, split, stack, squeeze,
unsqueeze, expand_v2, tile, flip, roll, gather(_nd), scatter(_nd_add), slice,
strided_slice, index_select, masked_select, tril_triu, unbind, unique, cast,
one_hot_v2 (SURVEY.md Appendix B). All are pure jnp views/copies — XLA fuses.
"""
import builtins

import jax
import jax.numpy as jnp
import numpy as np

from .common import as_tensor, register
from ..core import dtypes
from ..core.autograd import run_op
from ..core.tensor import Tensor


def _norm_shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    return [int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape]


def cast(x, dtype):
    x = as_tensor(x)
    dt = dtypes.convert_dtype(dtype)
    if dt == x.data.dtype:
        return x
    if dtypes.is_floating(dt) and dtypes.is_floating(x.data.dtype):
        return run_op('cast', lambda a: a.astype(dt), [x])
    if getattr(x, '_is_symbolic', False):   # static mode records the op
        return run_op('cast', lambda a: a.astype(dt), [x])
    return Tensor(x.data.astype(dt), stop_gradient=True)
register('cast', cast)


def reshape(x, shape, name=None):
    x = as_tensor(x)
    shape = _norm_shape(shape)
    # paddle semantics: 0 means copy the input dim at that position
    out_shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)] \
        if any(s == 0 for s in shape) else shape
    return run_op('reshape2', lambda a: jnp.reshape(a, out_shape), [x])
register('reshape2', reshape)


def transpose(x, perm, name=None):
    x = as_tensor(x)
    return run_op('transpose2', lambda a: jnp.transpose(a, tuple(perm)), [x])
register('transpose2', transpose)


def moveaxis(x, source, destination):
    x = as_tensor(x)
    return run_op('moveaxis', lambda a: jnp.moveaxis(a, source, destination), [x])


def swapaxes(x, axis0, axis1):
    x = as_tensor(x)
    return run_op('swapaxes', lambda a: jnp.swapaxes(a, axis0, axis1), [x])

transpose_ = transpose


def squeeze(x, axis=None, name=None):
    x = as_tensor(x)
    if isinstance(axis, int):
        axis = [axis]
    def fn(a):
        if axis is None:
            return jnp.squeeze(a)
        ax = tuple(i for i in axis if a.shape[i] == 1)
        return jnp.squeeze(a, axis=ax) if ax else a
    return run_op('squeeze2', fn, [x])


def unsqueeze(x, axis, name=None):
    x = as_tensor(x)
    if isinstance(axis, int):
        axis = [axis]
    axis = [int(a.item()) if isinstance(a, Tensor) else int(a) for a in axis]
    def fn(a):
        out = a
        for ax in sorted(axis):
            out = jnp.expand_dims(out, ax)
        return out
    return run_op('unsqueeze2', fn, [x])


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = as_tensor(x)
    nd = x.ndim
    sa = start_axis % nd if nd else 0
    ea = stop_axis % nd if nd else 0
    new_shape = x.shape[:sa] + [-1] + x.shape[ea + 1:]
    return run_op('flatten_contiguous_range',
                  lambda a: jnp.reshape(a, new_shape), [x])


def concat(x, axis=0, name=None):
    tensors = [as_tensor(t) for t in x]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return run_op('concat', lambda *arrs: jnp.concatenate(arrs, axis=axis), tensors)
register('concat', concat)


def stack(x, axis=0, name=None):
    tensors = [as_tensor(t) for t in x]
    return run_op('stack', lambda *arrs: jnp.stack(arrs, axis=axis), tensors)


def split(x, num_or_sections, axis=0, name=None):
    """Parity: operators/split_op."""
    x = as_tensor(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        n_neg = sizes.count(-1)
        if n_neg:
            rest = dim - builtins.sum(s for s in sizes if s != -1)
            sizes = [rest if s == -1 else s for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1]).tolist()

    def fn(a):
        return tuple(jax.lax.slice_in_dim(a, o, o + s, axis=axis)
                     for o, s in zip(offsets, sizes))
    return list(run_op('split', fn, [x]))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


def unstack(x, axis=0, num=None):
    x = as_tensor(x)
    n = num or x.shape[axis]
    def fn(a):
        return tuple(jnp.squeeze(s, axis=axis)
                     for s in jnp.split(a, n, axis=axis))
    return list(run_op('unstack', fn, [x]))


def unbind(x, axis=0):
    return unstack(x, axis=axis)


def tile(x, repeat_times, name=None):
    x = as_tensor(x)
    reps = _norm_shape(repeat_times)
    return run_op('tile', lambda a: jnp.tile(a, tuple(reps)), [x])


def expand(x, shape, name=None):
    x = as_tensor(x)
    shape = _norm_shape(shape)
    tgt = [x.shape[i - (len(shape) - x.ndim)] if s == -1 else s
           for i, s in enumerate(shape)]
    return run_op('expand_v2', lambda a: jnp.broadcast_to(a, tgt), [x])


def expand_as(x, y, name=None):
    return expand(x, as_tensor(y).shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs):
    arrs = jnp.broadcast_arrays(*[as_tensor(t).data for t in inputs])
    return [Tensor(a) for a in arrs]


def flip(x, axis, name=None):
    x = as_tensor(x)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return run_op('flip', lambda a: jnp.flip(a, axis=ax), [x])


def roll(x, shifts, axis=None, name=None):
    x = as_tensor(x)
    return run_op('roll', lambda a: jnp.roll(a, shifts, axis=axis), [x])


def rot90(x, k=1, axes=(0, 1)):
    x = as_tensor(x)
    return run_op('rot90', lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), [x])


# ---- gather / scatter ------------------------------------------------------
def gather(x, index, axis=0, name=None):
    """Parity: operators/gather_op — select rows of `axis` by 1-D index."""
    x, index = as_tensor(x), as_tensor(index)
    def fn(a, idx):
        return jnp.take(a, idx.reshape(-1), axis=axis)
    return run_op('gather', fn, [x, index], n_nondiff=1)


def gather_nd(x, index, name=None):
    x, index = as_tensor(x), as_tensor(index)
    def fn(a, idx):
        return a[tuple(jnp.moveaxis(idx, -1, 0))]
    return run_op('gather_nd', fn, [x, index], n_nondiff=1)


def take_along_axis(x, indices, axis):
    x, indices = as_tensor(x), as_tensor(indices)
    def fn(a, idx):
        return jnp.take_along_axis(a, idx, axis=axis)
    return run_op('take_along_axis', fn, [x, indices], n_nondiff=1)


def put_along_axis(x, indices, values, axis, reduce='assign'):
    x, indices = as_tensor(x), as_tensor(indices)
    values = as_tensor(values, ref=x)
    def fn(a, v, idx):
        if reduce == 'add':
            return a.at[_along_axis_index(a, idx, axis)].add(v)
        return a.at[_along_axis_index(a, idx, axis)].set(v)
    return run_op('put_along_axis', fn, [x, values, indices], n_nondiff=1)


def _along_axis_index(a, idx, axis):
    ix = []
    for d in range(a.ndim):
        if d == axis:
            ix.append(idx)
        else:
            shape = [1] * a.ndim
            shape[d] = a.shape[d]
            ix.append(jnp.arange(a.shape[d]).reshape(shape))
    return tuple(ix)


def scatter(x, index, updates, overwrite=True, name=None):
    """Parity: operators/scatter_op — rows of x at `index` set/added."""
    x = as_tensor(x)
    updates = as_tensor(updates, ref=x)
    index = as_tensor(index)
    def fn(a, u, idx):
        idx = idx.reshape(-1)
        if overwrite:
            return a.at[idx].set(u)
        base = a.at[idx].set(jnp.zeros_like(u))
        return base.at[idx].add(u)
    return run_op('scatter', fn, [x, updates, index], n_nondiff=1)


def scatter_nd_add(x, index, updates, name=None):
    x = as_tensor(x)
    updates = as_tensor(updates, ref=x)
    index = as_tensor(index)
    def fn(a, u, idx):
        return a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u)
    return run_op('scatter_nd_add', fn, [x, updates, index], n_nondiff=1)


def scatter_nd(index, updates, shape, name=None):
    updates = as_tensor(updates)
    zeros = Tensor(jnp.zeros(_norm_shape(shape), updates.dtype))
    return scatter_nd_add(zeros, index, updates)


def index_select(x, index, axis=0, name=None):
    x, index = as_tensor(x), as_tensor(index)
    def fn(a, idx):
        return jnp.take(a, idx.reshape(-1), axis=axis)
    return run_op('index_select', fn, [x, index], n_nondiff=1)


def index_sample(x, index):
    """Parity: operators/index_sample_op — per-row gather."""
    x, index = as_tensor(x), as_tensor(index)
    def fn(a, idx):
        return jnp.take_along_axis(a, idx.astype(jnp.int32), axis=1)
    return run_op('index_sample', fn, [x, index], n_nondiff=1)


def masked_select(x, mask, name=None):
    x, mask = as_tensor(x), as_tensor(mask)
    arr = np.asarray(x.data)
    m = np.asarray(mask.data)
    return Tensor(arr[np.broadcast_to(m, arr.shape)])


def masked_fill(x, mask, value):
    x, mask = as_tensor(x), as_tensor(mask)
    def fn(a, m):
        return jnp.where(m, jnp.asarray(value, a.dtype), a)
    return run_op('masked_fill', fn, [x, mask], n_nondiff=1)


# ---- slicing ---------------------------------------------------------------
def slice(x, axes, starts, ends, name=None):
    """Parity: operators/slice_op."""
    x = as_tensor(x)
    starts = _norm_shape(starts)
    ends = _norm_shape(ends)
    def fn(a):
        out = a
        for ax, st, en in zip(axes, starts, ends):
            dim = a.shape[ax]
            st2 = st + dim if st < 0 else min(st, dim)
            en2 = en + dim if en < 0 else min(en, dim)
            out = jax.lax.slice_in_dim(out, st2, en2, axis=ax)
        return out
    return run_op('slice', fn, [x])


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = as_tensor(x)
    def fn(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, st, en, sd in zip(axes, _norm_shape(starts), _norm_shape(ends),
                                  _norm_shape(strides)):
            idx[ax] = builtins.slice(st, en, sd)
        return a[tuple(idx)]
    return run_op('strided_slice', fn, [x])


def getitem(x, idx):
    x = as_tensor(x)
    if isinstance(idx, Tensor):
        if idx.dtype == jnp.bool_:
            return masked_select(x, idx)
        idx_arr = idx.data
        return run_op('getitem', lambda a, i: a[i], [x, idx], n_nondiff=1)
    if isinstance(idx, tuple):
        idx = tuple(i.data if isinstance(i, Tensor) else i for i in idx)
    return run_op('getitem', lambda a: a[idx], [x])


def tril(x, diagonal=0, name=None):
    x = as_tensor(x)
    return run_op('tril_triu', lambda a: jnp.tril(a, k=diagonal), [x])


def triu(x, diagonal=0, name=None):
    x = as_tensor(x)
    return run_op('tril_triu', lambda a: jnp.triu(a, k=diagonal), [x])


def diagonal(x, offset=0, axis1=0, axis2=1):
    x = as_tensor(x)
    return run_op('diagonal',
                  lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2), [x])


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype='int64', name=None):
    x = as_tensor(x)
    res = np.unique(np.asarray(x.data), return_index=return_index,
                    return_inverse=return_inverse, return_counts=return_counts,
                    axis=axis)
    if not isinstance(res, tuple):
        return Tensor(res)
    outs = [Tensor(res[0])]
    for r in res[1:]:
        outs.append(Tensor(r.astype(np.int64)))
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None):
    x = as_tensor(x)
    arr = np.asarray(x.data)
    if axis is None:
        arr = arr.reshape(-1)
    keep = np.concatenate([[True], arr[1:] != arr[:-1]]) if arr.ndim == 1 else None
    out = arr[keep]
    outs = [Tensor(out)]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(inv.astype(np.int64)))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.concatenate([idx, [len(arr)]]))
        outs.append(Tensor(counts.astype(np.int64)))
    return outs[0] if len(outs) == 1 else tuple(outs)


# ---- padding ---------------------------------------------------------------
def pad(x, pad, mode='constant', value=0.0, data_format='NCHW', name=None):
    """Parity: operators/pad3d / pad2d / pad_op."""
    x = as_tensor(x)
    pad = _norm_shape(pad)
    nd = x.ndim
    if len(pad) == 2 * nd:
        widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle NCHW convention: pad is [left, right, top, bottom, ...] on
        # trailing spatial dims, reversed axis order
        n_spatial = len(pad) // 2
        widths = [(0, 0)] * (nd - n_spatial)
        spatial = []
        for i in range(n_spatial):
            spatial.append((pad[2 * i], pad[2 * i + 1]))
        widths += spatial[::-1]
    jmode = {'constant': 'constant', 'reflect': 'reflect',
             'replicate': 'edge', 'circular': 'wrap'}[mode]
    def fn(a):
        if jmode == 'constant':
            return jnp.pad(a, widths, mode='constant', constant_values=value)
        return jnp.pad(a, widths, mode=jmode)
    return run_op('pad3d', fn, [x])


def one_hot(x, num_classes, name=None):
    x = as_tensor(x)
    return Tensor(jax.nn.one_hot(x.data, num_classes))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Parity: operators/shard_index_op.cc — used by c_embedding."""
    input = as_tensor(input)
    shard_size = (index_num + nshards - 1) // nshards
    def fn(idx):
        lo = shard_id * shard_size
        hi = (shard_id + 1) * shard_size
        in_range = (idx >= lo) & (idx < hi)
        return jnp.where(in_range, idx - lo, ignore_value)
    return Tensor(fn(input.data))


def meshgrid(*args, **kwargs):
    tensors = [as_tensor(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    outs = jnp.meshgrid(*[t.data for t in tensors], indexing='ij')
    return [Tensor(o) for o in outs]


def repeat_interleave(x, repeats, axis=None):
    x = as_tensor(x)
    r = repeats.data if isinstance(repeats, Tensor) else repeats
    return run_op('repeat_interleave',
                  lambda a: jnp.repeat(a, r, axis=axis), [x])


def as_complex(x):
    x = as_tensor(x)
    return run_op('as_complex', lambda a: jax.lax.complex(a[..., 0], a[..., 1]), [x])


def as_real(x):
    x = as_tensor(x)
    return run_op('as_real', lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), [x])


def real(x):
    x = as_tensor(x)
    return run_op('real', jnp.real, [x])


def imag(x):
    x = as_tensor(x)
    return run_op('imag', jnp.imag, [x])


def numel(x):
    x = as_tensor(x)
    return Tensor(jnp.asarray(x.size, dtype=jnp.int64))


def shape(x):
    x = as_tensor(x)
    return Tensor(np.asarray(x.shape, dtype=np.int32))


def space_to_depth(x, blocksize):
    x = as_tensor(x)
    def fn(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // blocksize, blocksize, w // blocksize, blocksize)
        a = a.transpose(0, 3, 5, 1, 2, 4)
        return a.reshape(n, c * blocksize * blocksize, h // blocksize, w // blocksize)
    return run_op('space_to_depth', fn, [x])


def pixel_shuffle(x, upscale_factor, data_format='NCHW'):
    x = as_tensor(x)
    r = upscale_factor
    def fn(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c // (r * r), r, r, h, w)
        a = a.transpose(0, 1, 4, 2, 5, 3)
        return a.reshape(n, c // (r * r), h * r, w * r)
    return run_op('pixel_shuffle', fn, [x])
