"""Op-definition helpers.

Reference parity: the role of paddle/fluid/framework/op_registry.h +
pybind/op_function_generator.cc (build-time `core.ops.*` fast paths). Here each
op is a jax-traceable function; `unary`/`binary`/`defop` wrap it with Tensor
boxing/unboxing and tape recording via core.autograd.run_op. The registry dict
maps op name → callable so the static Program executor (paddle_tpu.static) can
look ops up by name, like the reference's OpRegistry.
"""
import jax.numpy as jnp

from ..core import dtypes
from ..core.autograd import run_op
from ..core.tensor import Tensor

OP_REGISTRY = {}


def register(name, fn):
    OP_REGISTRY[name] = fn
    return fn


def as_tensor(x, ref=None):
    if isinstance(x, Tensor) or getattr(x, '_is_symbolic', False):
        return x
    dtype = None
    if ref is not None and isinstance(x, (int, float, bool)) and not isinstance(x, bool):
        dtype = ref.dtype
    return Tensor(jnp.asarray(x, dtype=dtype))


def _autocast(name, tensors):
    """AMP hook — parity with imperative/tracer.cc:176-181 (AmpAutoCast)."""
    from ..amp import amp_state, maybe_autocast_args
    if not amp_state()['enabled']:
        return tensors
    return maybe_autocast_args(name, tensors)


def defop(name, fn, n_nondiff=0):
    """Wrap a jax function `fn(*arrays, **kwargs)` as a Tensor op."""
    def op(*args, **kwargs):
        tensors = []
        for a in args:
            tensors.append(as_tensor(a, ref=tensors[0] if tensors else None))
        return run_op(name, fn, _autocast(name, tensors), kwargs,
                      n_nondiff=n_nondiff)
    op.__name__ = name
    return register(name, op)


def unary(name, fn):
    def op(x, name=None, **kwargs):
        kwargs.pop('name', None)
        return run_op(name_, fn, _autocast(name_, [as_tensor(x)]), kwargs)
    name_ = name
    op.__name__ = name
    return register(name, op)


def _promote(x, y):
    """Binary dtype promotion: scalars follow the tensor operand."""
    if isinstance(x, Tensor) and not isinstance(y, Tensor):
        y = as_tensor(y, ref=x)
    elif isinstance(y, Tensor) and not isinstance(x, Tensor):
        x = as_tensor(y, ref=y) if False else as_tensor(x, ref=y)
    else:
        x, y = as_tensor(x), as_tensor(y)
    return x, y


def binary(name, fn):
    def op(x, y, name=None, **kwargs):
        kwargs.pop('name', None)
        tx, ty = _promote(x, y)
        return run_op(name_, fn, _autocast(name_, [tx, ty]), kwargs)
    name_ = name
    op.__name__ = name
    return register(name, op)
