"""Math / reduction / comparison / logic ops.

Reference parity: paddle/fluid/operators root op families (Appendix B of
SURVEY.md) — elementwise_*, reduce_*, activation, matmul_v2, argsort/top_k,
compare/logical ops — re-expressed as XLA-traceable jnp functions; grads come
from jax.vjp instead of hand-registered GradOpMakers.
"""
import jax
import jax.numpy as jnp
import numpy as np

from .common import defop, unary, binary, as_tensor, register
from ..core.autograd import run_op
from ..core.tensor import Tensor

# ---- elementwise binary (operators/elementwise/) --------------------------
add = binary('elementwise_add', lambda x, y: x + y)
subtract = binary('elementwise_sub', lambda x, y: x - y)
multiply = binary('elementwise_mul', lambda x, y: x * y)
divide = binary('elementwise_div', lambda x, y: x / y)
floor_divide = binary('elementwise_floordiv', lambda x, y: jnp.floor_divide(x, y))
remainder = binary('elementwise_mod', lambda x, y: jnp.remainder(x, y))
pow = binary('elementwise_pow', lambda x, y: jnp.power(x, y))
maximum = binary('elementwise_max', jnp.maximum)
minimum = binary('elementwise_min', jnp.minimum)
fmax = binary('elementwise_fmax', jnp.fmax)
fmin = binary('elementwise_fmin', jnp.fmin)
atan2 = binary('atan2', jnp.arctan2)
hypot = binary('hypot', jnp.hypot)

mod = remainder
floor_mod = remainder

# ---- unary math (operators/activation_op.cc etc.) -------------------------
exp = unary('exp', jnp.exp)
expm1 = unary('expm1', jnp.expm1)
log = unary('log', jnp.log)
log2 = unary('log2', jnp.log2)
log10 = unary('log10', jnp.log10)
log1p = unary('log1p', jnp.log1p)
sqrt = unary('sqrt', jnp.sqrt)
rsqrt = unary('rsqrt', jax.lax.rsqrt)
square = unary('square', jnp.square)
abs = unary('abs', jnp.abs)
sign = unary('sign', jnp.sign)
floor = unary('floor', jnp.floor)
ceil = unary('ceil', jnp.ceil)
round = unary('round', jnp.round)
trunc = unary('trunc', jnp.trunc)
reciprocal = unary('reciprocal', lambda x: 1.0 / x)
neg = unary('neg', jnp.negative)
sin = unary('sin', jnp.sin)
cos = unary('cos', jnp.cos)
tan = unary('tan', jnp.tan)
asin = unary('asin', jnp.arcsin)
acos = unary('acos', jnp.arccos)
atan = unary('atan', jnp.arctan)
sinh = unary('sinh', jnp.sinh)
cosh = unary('cosh', jnp.cosh)
tanh = unary('tanh', jnp.tanh)
asinh = unary('asinh', jnp.arcsinh)
acosh = unary('acosh', jnp.arccosh)
atanh = unary('atanh', jnp.arctanh)
sigmoid = unary('sigmoid', jax.nn.sigmoid)
erf = unary('erf', jax.scipy.special.erf)
lgamma = unary('lgamma', jax.scipy.special.gammaln)
digamma = unary('digamma', jax.scipy.special.digamma)

# ---- scale / clip / assign ------------------------------------------------
scale = defop('scale', lambda x, scale=1.0, bias=0.0, bias_after_scale=True:
              x * scale + bias if bias_after_scale else (x + bias) * scale)
clip = defop('clip', lambda x, min=None, max=None: jnp.clip(x, min, max))
assign = defop('assign', lambda x: x + 0)
increment = defop('increment', lambda x, value=1.0: x + value)
stanh = defop('stanh', lambda x, scale_a=0.67, scale_b=1.7159:
              scale_b * jnp.tanh(scale_a * x))


def clip_by_norm(x, max_norm):
    x = as_tensor(x)
    def fn(a):
        norm = jnp.sqrt(jnp.sum(a * a))
        return jnp.where(norm > max_norm, a * (max_norm / norm), a)
    return run_op('clip_by_norm', fn, [x])


# ---- matmul family --------------------------------------------------------
def _matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        axes = list(range(x.ndim)); axes[-1], axes[-2] = axes[-2], axes[-1]
        x = jnp.transpose(x, axes)
    if transpose_y:
        axes = list(range(y.ndim)); axes[-1], axes[-2] = axes[-2], axes[-1]
        y = jnp.transpose(y, axes)
    return jnp.matmul(x, y)

matmul = binary('matmul_v2', _matmul)
bmm = binary('bmm', jnp.matmul)
mm = matmul
dot = binary('dot', lambda x, y: jnp.sum(x * y, axis=-1))
inner = binary('inner', jnp.inner)
outer = binary('outer', jnp.outer)
kron = binary('kron', jnp.kron)
cross = binary('cross', jnp.cross)
mv = binary('mv', jnp.matmul)

def addmm(input, x, y, beta=1.0, alpha=1.0):
    return add(scale(as_tensor(input), beta), scale(matmul(x, y), alpha))

def multiply_(x, y):
    return multiply(x, y)

# ---- reductions (operators/reduce_ops/) -----------------------------------
def _reduce(name, jfn):
    def op(x, axis=None, keepdim=False, name=None):
        x = as_tensor(x)
        if isinstance(axis, (list, tuple)):
            axis = tuple(axis) if len(axis) else None
        return run_op(name_, lambda a, axis, keepdims: jfn(a, axis=axis, keepdims=keepdims),
                      [x], {'axis': axis, 'keepdims': keepdim})
    name_ = name
    op.__name__ = name
    return register(name, op)

sum = _reduce('reduce_sum', jnp.sum)
mean = _reduce('reduce_mean', jnp.mean)
max = _reduce('reduce_max', jnp.max)
min = _reduce('reduce_min', jnp.min)
prod = _reduce('reduce_prod', jnp.prod)
amax = max
amin = min
nansum = _reduce('nansum', jnp.nansum)
nanmean = _reduce('nanmean', jnp.nanmean)
logsumexp = _reduce('logsumexp', jax.scipy.special.logsumexp)


def all(x, axis=None, keepdim=False, name=None):
    x = as_tensor(x)
    return Tensor(jnp.all(x.data, axis=axis if not isinstance(axis, list) else tuple(axis),
                          keepdims=keepdim))


def any(x, axis=None, keepdim=False, name=None):
    x = as_tensor(x)
    return Tensor(jnp.any(x.data, axis=axis if not isinstance(axis, list) else tuple(axis),
                          keepdims=keepdim))


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = as_tensor(x)
    ddof = 1 if unbiased else 0
    return run_op('std', lambda a: jnp.std(a, axis=axis, ddof=ddof, keepdims=keepdim), [x])


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = as_tensor(x)
    ddof = 1 if unbiased else 0
    return run_op('var', lambda a: jnp.var(a, axis=axis, ddof=ddof, keepdims=keepdim), [x])


def median(x, axis=None, keepdim=False, name=None):
    x = as_tensor(x)
    return run_op('median', lambda a: jnp.median(a, axis=axis, keepdims=keepdim), [x])


def mode(x, axis=-1, keepdim=False, name=None):
    x = as_tensor(x)
    arr = np.asarray(x.data)
    vals, counts = None, None
    def _mode_1d(a):
        u, c = np.unique(a, return_counts=True)
        return u[np.argmax(c)]
    out = np.apply_along_axis(_mode_1d, axis, arr)
    if keepdim:
        out = np.expand_dims(out, axis)
    return Tensor(out)


def quantile(x, q, axis=None, keepdim=False):
    x = as_tensor(x)
    return run_op('quantile', lambda a: jnp.quantile(a, q, axis=axis, keepdims=keepdim), [x])

# ---- cum ops --------------------------------------------------------------
cumsum_ = lambda a, axis: jnp.cumsum(a, axis=axis)


def cumsum(x, axis=None, dtype=None, name=None):
    x = as_tensor(x)
    def fn(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1))
        return jnp.cumsum(a, axis=axis)
    out = run_op('cumsum', fn, [x])
    return out.astype(dtype) if dtype is not None else out


def cumprod(x, dim=None, dtype=None, name=None):
    x = as_tensor(x)
    out = run_op('cumprod', lambda a: jnp.cumprod(a, axis=dim), [x])
    return out.astype(dtype) if dtype is not None else out

# ---- arg / sort / topk ----------------------------------------------------
def argmax(x, axis=None, keepdim=False, dtype='int64', name=None):
    x = as_tensor(x)
    out = jnp.argmax(x.data, axis=axis, keepdims=keepdim if axis is not None else False)
    return Tensor(out.astype(jnp.dtype(dtype) if isinstance(dtype, str) else dtype))


def argmin(x, axis=None, keepdim=False, dtype='int64', name=None):
    x = as_tensor(x)
    out = jnp.argmin(x.data, axis=axis, keepdims=keepdim if axis is not None else False)
    return Tensor(out.astype(jnp.dtype(dtype) if isinstance(dtype, str) else dtype))


def argsort(x, axis=-1, descending=False, name=None):
    x = as_tensor(x)
    idx = jnp.argsort(x.data, axis=axis, descending=descending)
    return Tensor(idx.astype(jnp.int64))


def sort(x, axis=-1, descending=False, name=None):
    x = as_tensor(x)
    return run_op('argsort', lambda a: jnp.sort(a, axis=axis, descending=descending), [x])


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    """Parity: operators/top_k_v2_op."""
    x = as_tensor(x)
    if isinstance(k, Tensor):
        k = int(k.item())
    ax = axis if axis is not None else x.ndim - 1

    def fn(a):
        arr = jnp.moveaxis(a, ax, -1)
        src = arr if largest else -arr
        vals, idx = jax.lax.top_k(src, k)
        if not largest:
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax)

    vals, idx = run_op('top_k_v2', fn, [x])
    return vals, idx.astype('int64')   # works in both eager and static


def nonzero(x, as_tuple=False):
    x = as_tensor(x)
    nz = np.nonzero(np.asarray(x.data))
    if as_tuple:
        return tuple(Tensor(n.astype(np.int64)) for n in nz)
    return Tensor(np.stack(nz, axis=1).astype(np.int64))

# ---- comparison (operators/controlflow/compare_op.cc) ---------------------
def _cmp(name, fn):
    def op(x, y, name=None):
        tx = as_tensor(x)
        ty = as_tensor(y, ref=tx)
        # through run_op so static mode records a compare op (while/cond
        # conditions) instead of evaluating on symbolic avals
        return run_op(name, fn, [tx, ty])
    op.__name__ = name
    return register(name, op)

equal = _cmp('equal', lambda x, y: x == y)
not_equal = _cmp('not_equal', lambda x, y: x != y)
less_than = _cmp('less_than', lambda x, y: x < y)
less_equal = _cmp('less_equal', lambda x, y: x <= y)
greater_than = _cmp('greater_than', lambda x, y: x > y)
greater_equal = _cmp('greater_equal', lambda x, y: x >= y)


def equal_all(x, y, name=None):
    x, y = as_tensor(x), as_tensor(y)
    return Tensor(jnp.array_equal(x.data, y.data))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = as_tensor(x), as_tensor(y)
    return Tensor(jnp.allclose(x.data, y.data, rtol=rtol, atol=atol, equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = as_tensor(x), as_tensor(y)
    return Tensor(jnp.isclose(x.data, y.data, rtol=rtol, atol=atol, equal_nan=equal_nan))

# ---- logic / bitwise ------------------------------------------------------
logical_and = _cmp('logical_and', jnp.logical_and)
logical_or = _cmp('logical_or', jnp.logical_or)
logical_xor = _cmp('logical_xor', jnp.logical_xor)
bitwise_and = _cmp('bitwise_and', lambda x, y: x & y)
bitwise_or = _cmp('bitwise_or', lambda x, y: x | y)
bitwise_xor = _cmp('bitwise_xor', lambda x, y: x ^ y)


def logical_not(x, name=None):
    return Tensor(jnp.logical_not(as_tensor(x).data))


def bitwise_not(x, name=None):
    return Tensor(~as_tensor(x).data)

# ---- isnan family (operators/isfinite_v2_op.cc) ---------------------------
def isnan(x, name=None):
    return Tensor(jnp.isnan(as_tensor(x).data))


def isinf(x, name=None):
    return Tensor(jnp.isinf(as_tensor(x).data))


def isfinite(x, name=None):
    return Tensor(jnp.isfinite(as_tensor(x).data))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    x = as_tensor(x)
    return run_op('nan_to_num',
                  lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), [x])

# ---- norms ----------------------------------------------------------------
def norm(x, p='fro', axis=None, keepdim=False, name=None):
    """Parity: operators/p_norm_op.cc + norm_op.cc."""
    x = as_tensor(x)
    def fn(a):
        if p in ('fro', 2) and axis is None:
            return jnp.sqrt(jnp.sum(a * a))
        if axis is None:
            flat = a.reshape(-1)
            return jnp.linalg.norm(flat, ord=p)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        return jnp.linalg.norm(a, ord=p if p != 'fro' else None, axis=ax, keepdims=keepdim)
    return run_op('p_norm', fn, [x])


def dist(x, y, p=2, name=None):
    x, y = as_tensor(x), as_tensor(y)
    def fn(a, b):
        d = (a - b).reshape(-1)
        if p == 0:
            return jnp.sum(d != 0).astype(a.dtype)
        if p == float('inf'):
            return jnp.max(jnp.abs(d))
        if p == float('-inf'):
            return jnp.min(jnp.abs(d))
        return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)
    return run_op('dist', fn, [x, y])

# ---- where / select -------------------------------------------------------
def where(condition, x=None, y=None, name=None):
    condition = as_tensor(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    tx = as_tensor(x)
    ty = as_tensor(y, ref=tx)
    return run_op('where', lambda c, a, b: jnp.where(c, a, b),
                  [condition, tx, ty], n_nondiff=0)


def multiplex(inputs, index, name=None):
    index = as_tensor(index)
    stacked = jnp.stack([as_tensor(i).data for i in inputs], axis=0)
    idx = index.data.reshape(-1).astype(jnp.int32)
    rows = jnp.arange(idx.shape[0])
    return Tensor(stacked[idx, rows])

# ---- misc -----------------------------------------------------------------
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    x = as_tensor(x)
    return run_op('trace', lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), [x])


def diag(x, offset=0, padding_value=0, name=None):
    x = as_tensor(x)
    def fn(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(a), k=offset)
                out = out + (1 - mask) * padding_value
            return out
        return jnp.diagonal(a, offset=offset)
    return run_op('diag_v2', fn, [x])


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    x = as_tensor(x)
    return run_op('diag_embed',
                  lambda a: jnp.apply_along_axis(jnp.diag, -1, a) if offset == 0 and dim1 == -2 and dim2 == -1
                  else jnp.vectorize(lambda v: jnp.diag(v, k=offset), signature='(n)->(m,m)')(a),
                  [x])


def lerp(x, y, weight, name=None):
    x, y = as_tensor(x), as_tensor(y)
    w = weight.data if isinstance(weight, Tensor) else weight
    return run_op('lerp', lambda a, b: a + w * (b - a), [x, y])


def frac(x):
    x = as_tensor(x)
    return run_op('frac', lambda a: a - jnp.trunc(a), [x])


def rad2deg(x):
    return scale(as_tensor(x), 180.0 / np.pi)


def deg2rad(x):
    return scale(as_tensor(x), np.pi / 180.0)


def gcd(x, y):
    x, y = as_tensor(x), as_tensor(y)
    return Tensor(jnp.gcd(x.data, y.data))


def lcm(x, y):
    x, y = as_tensor(x), as_tensor(y)
    return Tensor(jnp.lcm(x.data, y.data))


def count_nonzero(x, axis=None, keepdim=False):
    x = as_tensor(x)
    return Tensor(jnp.count_nonzero(x.data, axis=axis, keepdims=keepdim).astype(jnp.int64))


def heaviside(x, y):
    x, y = as_tensor(x), as_tensor(y)
    return run_op('heaviside', jnp.heaviside, [x, y])


def histogram(input, bins=100, min=0, max=0):
    input = as_tensor(input)
    arr = np.asarray(input.data)
    lo, hi = (min, max) if (min != 0 or max != 0) else (arr.min(), arr.max())
    hist, _ = np.histogram(arr, bins=bins, range=(lo, hi))
    return Tensor(hist.astype(np.int64))


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


# ---- tier-2 additions (Appendix B coverage) -------------------------------
def bincount(x, weights=None, minlength=0):
    x = as_tensor(x)
    if weights is not None:
        w = as_tensor(weights)
        return Tensor(jnp.bincount(x.data.reshape(-1), w.data.reshape(-1),
                                   minlength=minlength))
    return Tensor(jnp.bincount(x.data.reshape(-1), minlength=minlength))


def bucketize(x, sorted_sequence, out_int32=False, right=False):
    x, s = as_tensor(x), as_tensor(sorted_sequence)
    side = 'right' if right else 'left'
    out = jnp.searchsorted(s.data, x.data, side=side)
    return Tensor(out.astype(jnp.int32 if out_int32 else jnp.int64))


def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    return bucketize(values, sorted_sequence, out_int32, right)


def take(x, index, mode='raise'):
    x, index = as_tensor(x), as_tensor(index)
    def fn(a, idx):
        return jnp.take(a.reshape(-1), idx, mode='clip')
    return run_op('take', fn, [x, index], n_nondiff=1)


def tensordot(x, y, axes=2, name=None):
    x, y = as_tensor(x), as_tensor(y)
    return run_op('tensordot', lambda a, b: jnp.tensordot(a, b, axes=axes),
                  [x, y])


def logcumsumexp(x, axis=None, name=None):
    x = as_tensor(x)
    def fn(a):
        if axis is None:
            a = a.reshape(-1)
            ax = 0
        else:
            ax = axis
        m = jnp.max(a, axis=ax, keepdims=True)
        return jnp.log(jnp.cumsum(jnp.exp(a - m), axis=ax)) + m
    return run_op('logcumsumexp', fn, [x])


def renorm(x, p, axis, max_norm):
    x = as_tensor(x)
    def fn(a):
        moved = jnp.moveaxis(a, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.power(jnp.sum(jnp.power(jnp.abs(flat), p), axis=1),
                          1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        out = flat * factor[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)
    return run_op('renorm', fn, [x])


def diff(x, n=1, axis=-1, prepend=None, append=None):
    x = as_tensor(x)
    pre = prepend.data if isinstance(prepend, Tensor) else prepend
    app = append.data if isinstance(append, Tensor) else append
    return run_op('diff', lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre,
                                             append=app), [x])


def trapezoid(y, x=None, dx=None, axis=-1):
    y = as_tensor(y)
    if x is not None:
        x = as_tensor(x)
        return run_op('trapezoid',
                      lambda a, b: jnp.trapezoid(a, b, axis=axis), [y, x])
    return run_op('trapezoid',
                  lambda a: jnp.trapezoid(a, dx=dx or 1.0, axis=axis), [y])


def vander(x, n=None, increasing=False):
    x = as_tensor(x)
    return run_op('vander',
                  lambda a: jnp.vander(a, N=n, increasing=increasing), [x])


def angle(x, name=None):
    x = as_tensor(x)
    return run_op('angle', jnp.angle, [x])


def conj(x, name=None):
    x = as_tensor(x)
    return run_op('conj', jnp.conj, [x])


def polar(abs, angle):
    abs, angle = as_tensor(abs), as_tensor(angle)
    return run_op('polar',
                  lambda r, t: r * jnp.exp(1j * t.astype(jnp.complex64)),
                  [abs, angle])


def crop(x, shape=None, offsets=None):
    from . import manip
    x = as_tensor(x)
    shape_ = [x.shape[i] if s == -1 else s for i, s in enumerate(shape)]
    offsets = offsets or [0] * x.ndim
    axes = list(range(x.ndim))
    starts = offsets
    ends = [o + s for o, s in zip(offsets, shape_)]
    return manip.slice(x, axes, starts, ends)


def inner_outer_placeholder():
    pass
