"""Ragged paged attention — Pallas TPU kernel for the serving engine.

Role (Ragged Paged Attention, arXiv:2604.15464): one kernel serves a
MIXED batch of in-flight requests — decode rows (one new token) and
chunked-prefill rows (a window of new tokens) — whose KV history lives
in a block-paged pool (`serving/kv_pool.py`) instead of a dense
[B, max_len] cache. Each batch row carries its own context length and a
page table; the kernel gathers that row's pages and applies causal
attention *within the sequence*, so the compiled step has one fixed
shape regardless of how ragged the batch is.

TPU-native shape: a `PrefetchScalarGridSpec` grid over (batch_row,
page). The page table and the per-row lengths are scalar-prefetched, so
the BlockSpec index map for K/V resolves `page_tables[b, p]` *before*
the kernel body runs — the pages stream HBM→VMEM exactly like the flash
kernel's K/V blocks, no host gather and no [B, max_len, H*D]
materialization (that is the dense fallback below). Online-softmax
state (running max / normalizer / fp32 accumulator) persists in VMEM
scratch across a row's page steps; heads run as static column slices of
the packed [T, H*D] slab (the flash_attention.py packed-layout idiom —
Tensor Processing Primitives, arXiv:2104.05755: one small reusable
kernel beside the existing ones, not a monolith).

Routing mirrors nn/layer/transformer.py's flash routing: the Pallas
kernel on TPU, a dense `lax` fallback on CPU / tiny shapes, overridable
with FLAGS_paged_attention_kernel. On CPU the kernel still runs under
Pallas interpret mode so CI covers the same body that lowers on TPU.

Layouts:
  q           [B, T, H*D]   new-token queries, right-padded to T per row
  k_pages     [N_pages, page_size, H*D]   the pool's device arrays
  v_pages     [N_pages, page_size, H*D]
  page_tables int32 [B, pages_per_seq]    pool page ids (unused slots
                                          must hold a valid id, e.g. 0)
  seq_lens    int32 [B]  context length INCLUDING this step's new tokens
  q_lens      int32 [B]  valid new tokens this step (<= T)

Query t of row b sits at global position seq_lens[b] - q_lens[b] + t and
attends keys at positions <= its own (causal) and < seq_lens[b].

Multi-query verify rows (ISSUE 9, speculative decoding): the serving
engine's [max_batch, spec_k+1] verify step feeds each greedy request's
last token plus its k draft tokens as one ragged row — q_len = 1+k,
seq_len = context+k. That is exactly the chunked-prefill shape this
kernel (and the dense fallback) already serves: the
causal-within-sequence mask scores every draft against the real
context plus the earlier drafts in ONE dispatch, so no verify-specific
kernel body exists. Rejected drafts leave stale K/V in their slots;
the seq_len mask keeps them invisible until the step that overwrites
them (engine._decode_step documents the rollback invariant).

Quantized pages (ISSUE 7, `kv_dtype='int8'`): k_pages/v_pages are int8
and carry sibling fp32 scale buffers `[N_pages, page_size, H]` — one
abs-max scale per (token slot, head). `write_kv_pages_quantized`
quantizes each new token's per-head K/V row at scatter time;
dequantization happens INSIDE the kernel (per-page VMEM block, one
multiply per head slice — free next to the MXU dot) and inside the
dense fallback, so attention math stays fp32 while the pool pays 1
byte/element + 4 bytes/head/slot. On TPU note the int8 min tile is
(32, 128): page_size >= 32 keeps the int8 page blocks tile-aligned.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import scaffold

NEG_INF = -1e30

# interpret-mode forcing shared with every primitive in this package
_interpret = scaffold.interpret_mode


def _ragged_paged_kernel(pt_ref, ln_ref, q_ref, k_ref, v_ref, *rest,
                         page_size, num_heads, head_dim, pages_per_seq,
                         quantized=False):
    """One (batch_row, page) program.

    pt_ref/ln_ref are scalar-prefetched (page tables, [B, 2] lens); the
    K/V BlockSpecs already resolved this program's page id, so k_ref /
    v_ref hold one [page_size, H*D] page in VMEM. Scratch carries the
    online-softmax state across a row's page steps (the page grid
    iterates fastest, so p==0 re-arms and the last page finalizes).
    With `quantized` the K/V blocks are int8 and two extra refs hold
    this page's [page_size, H] fp32 scales; dequantization is one
    broadcast multiply per head slice, fused into the fp32 upcast the
    kernel already pays.
    """
    if quantized:
        ks_ref, vs_ref, o_ref, m_s, l_s, acc_s = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_s, l_s, acc_s = rest
    b = pl.program_id(0)
    p = pl.program_id(1)
    T = q_ref.shape[0]
    D = head_dim
    seq_len = ln_ref[b, 0]
    q_len = ln_ref[b, 1]
    page_start = p * page_size
    scale = 1.0 / math.sqrt(D)

    @pl.when(p == 0)
    def _():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    @pl.when(page_start < seq_len)
    def _():
        # global positions: rows = this step's queries, cols = this
        # page's keys; causal within the sequence + ragged length mask
        q_pos = (seq_len - q_len
                 + jax.lax.broadcasted_iota(jnp.int32, (T, page_size), 0))
        key_pos = page_start + jax.lax.broadcasted_iota(
            jnp.int32, (T, page_size), 1)
        valid = (key_pos < seq_len) & (key_pos <= q_pos)
        for h in range(num_heads):
            q = q_ref[:, h * D:(h + 1) * D].astype(jnp.float32) * scale
            k = k_ref[:, h * D:(h + 1) * D].astype(jnp.float32)
            v = v_ref[:, h * D:(h + 1) * D].astype(jnp.float32)
            if quantized:
                k = k * ks_ref[:, h:h + 1]
                v = v * vs_ref[:, h:h + 1]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            s = jnp.where(valid, s, NEG_INF)
            m_prev = m_s[:, h:h + 1]
            l_prev = l_s[:, h:h + 1]
            m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
            pexp = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            acc = acc_s[:, h * D:(h + 1) * D]
            acc_s[:, h * D:(h + 1) * D] = \
                acc * alpha + jax.lax.dot_general(
                    pexp, v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
            m_s[:, h:h + 1] = m_new
            l_s[:, h:h + 1] = alpha * l_prev + jnp.sum(pexp, -1,
                                                       keepdims=True)

    @pl.when(p == pages_per_seq - 1)
    def _():
        l_safe = jnp.maximum(l_s[:], 1e-30)
        for h in range(num_heads):
            o_ref[:, h * D:(h + 1) * D] = (
                acc_s[:, h * D:(h + 1) * D] / l_safe[:, h:h + 1]
            ).astype(o_ref.dtype)


def ragged_paged_attention_pallas(q, k_pages, v_pages, page_tables,
                                  seq_lens, q_lens, *, num_heads,
                                  head_dim, k_scales=None,
                                  v_scales=None, interpret=None):
    """Pallas route (interpret-mode on CPU). See module docstring for
    layouts; k_scales/v_scales engage the int8 dequantizing body."""
    B, T, HD = q.shape
    ps = k_pages.shape[1]
    P = page_tables.shape[1]
    quantized = k_scales is not None
    lens = jnp.stack([seq_lens.astype(jnp.int32),
                      q_lens.astype(jnp.int32)], axis=1)       # [B, 2]
    # unused page-table slots may carry sentinels; the index map still
    # fetches them, so clamp to valid pool ids (compute is masked off)
    pt = jnp.clip(page_tables.astype(jnp.int32), 0,
                  k_pages.shape[0] - 1)
    page_spec = pl.BlockSpec((None, ps, HD),
                             lambda b, p, pt, ln: (pt[b, p], 0, 0))
    in_specs = [
        pl.BlockSpec((None, T, HD), lambda b, p, pt, ln: (b, 0, 0)),
        page_spec,
        page_spec,
    ]
    inputs = [pt, lens, q, k_pages, v_pages]
    if quantized:
        scale_spec = pl.BlockSpec(
            (None, ps, num_heads), lambda b, p, pt, ln: (pt[b, p], 0, 0))
        in_specs += [scale_spec, scale_spec]
        inputs += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, P),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, T, HD),
                               lambda b, p, pt, ln: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((T, num_heads), jnp.float32),   # running max
            pltpu.VMEM((T, num_heads), jnp.float32),   # normalizer
            pltpu.VMEM((T, HD), jnp.float32),          # accumulator
        ],
    )
    kernel = functools.partial(
        _ragged_paged_kernel, page_size=ps, num_heads=num_heads,
        head_dim=head_dim, pages_per_seq=P, quantized=quantized)
    out_dtype = q.dtype
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, T, HD), out_dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(*inputs)


def _dequant_gathered(pages, scales, H):
    """[B, P, ps, H*D] int8 + [B, P, ps, H] fp32 -> fp32 pages."""
    B, P, ps, HD = pages.shape
    D = HD // H
    return (pages.astype(jnp.float32).reshape(B, P, ps, H, D)
            * scales.astype(jnp.float32)[..., None]) \
        .reshape(B, P, ps, HD)


def ragged_paged_attention_dense(q, k_pages, v_pages, page_tables,
                                 seq_lens, q_lens, *, num_heads,
                                 head_dim, k_scales=None, v_scales=None):
    """Dense lax fallback: gather each row's pages into a [B, P*ps, H*D]
    context and run masked attention. O(B * pages_per_seq * page_size)
    memory — correct everywhere (the CPU serving path and the numerics
    oracle for the kernel), not the TPU hot path. Int8 pages are
    dequantized right after the gather (same per-(slot, head) scales
    the kernel applies in VMEM)."""
    B, T, HD = q.shape
    ps = k_pages.shape[1]
    P = page_tables.shape[1]
    D = head_dim
    pt = jnp.clip(page_tables.astype(jnp.int32), 0,
                  k_pages.shape[0] - 1)
    if k_scales is not None:
        k = _dequant_gathered(k_pages[pt], k_scales[pt], num_heads) \
            .reshape(B, P * ps, HD)
        v = _dequant_gathered(v_pages[pt], v_scales[pt], num_heads) \
            .reshape(B, P * ps, HD)
    else:
        k = k_pages[pt].reshape(B, P * ps, HD).astype(jnp.float32)
        v = v_pages[pt].reshape(B, P * ps, HD).astype(jnp.float32)
    scale = 1.0 / math.sqrt(D)
    q_pos = (seq_lens[:, None] - q_lens[:, None]
             + jnp.arange(T, dtype=jnp.int32)[None, :])        # [B, T]
    key_pos = jnp.arange(P * ps, dtype=jnp.int32)[None, None, :]
    valid = (key_pos < seq_lens[:, None, None]) & \
            (key_pos <= q_pos[:, :, None])                     # [B, T, K]
    outs = []
    for h in range(num_heads):
        qh = q[:, :, h * D:(h + 1) * D].astype(jnp.float32) * scale
        kh = k[:, :, h * D:(h + 1) * D]
        vh = v[:, :, h * D:(h + 1) * D]
        s = jnp.einsum('btd,bkd->btk', qh, kh,
                       preferred_element_type=jnp.float32)
        s = jnp.where(valid, s, NEG_INF)
        probs = jax.nn.softmax(s, axis=-1)
        outs.append(jnp.einsum('btk,bkd->btd', probs, vh))
    return jnp.concatenate(outs, axis=-1).astype(q.dtype)


def use_pallas_route():
    """Auto-selection through the shared scaffolding (scaffold.py):
    the Pallas kernel on TPU, the dense fallback on CPU (interpret-mode
    per-token decode is test machinery, not a serving path). Force with
    FLAGS_paged_attention_kernel=True/False; decisions are counted in
    ptpu_pallas_{kernel,fallback}_invocations_total."""
    return scaffold.use_kernel('paged_attention',
                               'FLAGS_paged_attention_kernel')


def ragged_paged_attention(q, k_pages, v_pages, page_tables, seq_lens,
                           q_lens=None, *, num_heads, head_dim,
                           k_scales=None, v_scales=None):
    """Auto-routed entry (array-level; used inside the serving engine's
    jitted steps). Pass k_scales/v_scales for int8 pages."""
    if q_lens is None:
        q_lens = jnp.full((q.shape[0],), q.shape[1], jnp.int32)
    fn = (ragged_paged_attention_pallas if use_pallas_route()
          else ragged_paged_attention_dense)
    return fn(q, k_pages, v_pages, page_tables, seq_lens, q_lens,
              num_heads=num_heads, head_dim=head_dim,
              k_scales=k_scales, v_scales=v_scales)


def _flat_slots(page_tables, seq_lens, q_lens, T, N, ps):
    """[B*T] flat pool slot per new token (OOB sentinel for padding —
    dropped by the scatter). Token t of row b lands at global position
    seq_lens[b] - q_lens[b] + t, i.e. flat slot
    page_tables[b, pos // ps] * ps + pos % ps."""
    pos = (seq_lens[:, None] - q_lens[:, None]
           + jnp.arange(T, dtype=jnp.int32)[None, :])          # [B, T]
    valid = jnp.arange(T, dtype=jnp.int32)[None, :] < q_lens[:, None]
    page_idx = jnp.take_along_axis(
        jnp.clip(page_tables, 0, N - 1), pos // ps, axis=1)    # [B, T]
    flat = page_idx * ps + pos % ps
    flat = jnp.where(valid, flat, N * ps)      # OOB -> dropped
    return flat.reshape(-1)


def write_kv_pages(k_pages, v_pages, k_new, v_new, page_tables,
                   seq_lens, q_lens):
    """Scatter this step's new K/V rows into the paged pool (pure array
    op, jit/donation-friendly).

    k_new/v_new: [B, T, H*D] right-padded like q; padded tokens are
    routed to an out-of-range index and dropped by the scatter.
    """
    N, ps, HD = k_pages.shape
    B, T, _ = k_new.shape
    flat = _flat_slots(page_tables, seq_lens, q_lens, T, N, ps)
    k2 = k_pages.reshape(N * ps, HD).at[flat].set(
        k_new.reshape(B * T, HD).astype(k_pages.dtype), mode='drop')
    v2 = v_pages.reshape(N * ps, HD).at[flat].set(
        v_new.reshape(B * T, HD).astype(v_pages.dtype), mode='drop')
    return k2.reshape(N, ps, HD), v2.reshape(N, ps, HD)


def quantize_kv_rows(x, num_heads):
    """[B, T, H*D] float -> (int8 [B, T, H*D], fp32 scales [B, T, H]):
    symmetric abs-max per (token, head) — the granularity the pool's
    scale buffers store, chosen so a token's scales are final the
    moment it is written (no rescaling of already-resident slots)."""
    B, T, HD = x.shape
    D = HD // num_heads
    xf = x.astype(jnp.float32).reshape(B, T, num_heads, D)
    amax = jnp.max(jnp.abs(xf), axis=-1)                       # [B,T,H]
    scale = jnp.maximum(amax, 1e-30) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127) \
        .astype(jnp.int8)
    return q.reshape(B, T, HD), scale


def write_kv_pages_quantized(k_pages, v_pages, k_scales, v_scales,
                             k_new, v_new, page_tables, seq_lens,
                             q_lens, *, num_heads):
    """Quantizing twin of write_kv_pages for int8 pools: each new
    token's K/V row is abs-max-quantized per head and scattered as int8
    + fp32 scales into the sibling scale buffers (same flat slots)."""
    N, ps, HD = k_pages.shape
    B, T, _ = k_new.shape
    H = num_heads
    flat = _flat_slots(page_tables, seq_lens, q_lens, T, N, ps)
    kq, ks = quantize_kv_rows(k_new, H)
    vq, vs = quantize_kv_rows(v_new, H)
    k2 = k_pages.reshape(N * ps, HD).at[flat].set(
        kq.reshape(B * T, HD), mode='drop')
    v2 = v_pages.reshape(N * ps, HD).at[flat].set(
        vq.reshape(B * T, HD), mode='drop')
    ks2 = k_scales.reshape(N * ps, H).at[flat].set(
        ks.reshape(B * T, H).astype(k_scales.dtype), mode='drop')
    vs2 = v_scales.reshape(N * ps, H).at[flat].set(
        vs.reshape(B * T, H).astype(v_scales.dtype), mode='drop')
    return (k2.reshape(N, ps, HD), v2.reshape(N, ps, HD),
            ks2.reshape(N, ps, H), vs2.reshape(N, ps, H))
