"""Fused elementwise transformer blocks — bias+GELU and
dropout+residual-add — on the shared Pallas scaffolding (TPP,
arXiv:2104.05755).

bias_gelu: y = gelu(x + bias). The forward kernel computes the add and
the activation in the INPUT dtype via `jax.nn.gelu` traced into the
kernel body — the same expression the reference path runs, so routes
agree at the bf16 cast points. The backward kernel recomputes u = x + b
once, applies the analytic gelu derivative in fp32, streams dx out per
row block, and accumulates dbias across the sequential grid in VMEM
scratch (one pass; XLA autodiff instead re-materializes tanh and runs a
separate reduction).

dropout_add: y = where(keep, x / (1-p), 0) + residual (paddle's
upscale_in_train). The keep mask is drawn OUTSIDE the kernel with the
same `jax.random.bernoulli(key, 1-p, shape)` the reference dropout
uses — stateless threefry keys give fused and reference routes the
SAME drop pattern for the same RNG stream (values agree to 1 ulp; XLA
contracts the divide/add chain differently inside one kernel body),
and the kernel fuses the select + scale + residual add into one pass
(backward: one masked scale, d(residual) = g). The mask travels as
fp32 0/1 so the custom VJP has a well-formed (zero) cotangent slot
for it.

Routing: `FLAGS_fused_elementwise` (None = auto), recorded as
primitives 'bias_gelu' and 'dropout_add'. `ops.nn_ops` owns the
functional entries (`bias_gelu`, `dropout_add`) that route here.
"""
import functools
import math

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import scaffold

FLAG = 'FLAGS_fused_elementwise'
ROW_BLOCK = 128


def use_fused(primitive, supported=True):
    return scaffold.use_kernel(primitive, FLAG, supported=supported)


def _gelu_grad(u, approximate):
    """d gelu(u) / du in fp32 (u fp32)."""
    if approximate:
        c = math.sqrt(2.0 / math.pi)
        inner = c * (u + 0.044715 * u ** 3)
        t = jnp.tanh(inner)
        return 0.5 * (1.0 + t) + 0.5 * u * (1.0 - t * t) * c * \
            (1.0 + 3 * 0.044715 * u ** 2)
    phi = jnp.exp(-0.5 * u * u) * (1.0 / math.sqrt(2.0 * math.pi))
    cdf = 0.5 * (1.0 + jax.lax.erf(u * (1.0 / math.sqrt(2.0))))
    return cdf + u * phi


# ---------------------------------------------------------------------------
# bias + gelu
# ---------------------------------------------------------------------------
def _bg_fwd_kernel(x_ref, b_ref, o_ref, *, approximate):
    o_ref[...] = jax.nn.gelu(x_ref[...] + b_ref[...],
                             approximate=approximate)


def _bg_bwd_kernel(x_ref, b_ref, dy_ref, dx_ref, db_ref, db_s, *,
                   approximate):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        db_s[...] = jnp.zeros_like(db_s)
    u = (x_ref[...] + b_ref[...]).astype(jnp.float32)
    du = dy_ref[...].astype(jnp.float32) * _gelu_grad(u, approximate)
    dx_ref[...] = du.astype(dx_ref.dtype)
    db_s[...] += jnp.sum(du, axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        db_ref[...] = db_s[...]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def bias_gelu(x, bias, approximate):
    """Array-level fused entry: x [..., N], bias [N]."""
    return _bg_fwd_impl(x, bias, approximate)


def _bg_fwd_impl(x, bias, approximate):
    shape = x.shape
    N = shape[-1]
    br = scaffold.pick_block_rows(N, ROW_BLOCK)
    x2 = scaffold.pad_rows(x.reshape(-1, N), br)
    rows = x2.shape[0]
    o = pl.pallas_call(
        functools.partial(_bg_fwd_kernel, approximate=approximate),
        grid=(rows // br,),
        in_specs=[scaffold.row_spec(br, N), scaffold.bcast_spec(1, N)],
        out_specs=scaffold.row_spec(br, N),
        out_shape=jax.ShapeDtypeStruct((rows, N), x.dtype),
        interpret=scaffold.interpret_mode(),
    )(x2, bias.astype(x.dtype).reshape(1, N))
    R = x.reshape(-1, N).shape[0]
    return o[:R].reshape(shape)


def _bg_fwd(x, bias, approximate):
    return _bg_fwd_impl(x, bias, approximate), (x, bias)


def _bg_bwd(approximate, res, g):
    x, bias = res
    shape = x.shape
    N = shape[-1]
    br = scaffold.pick_block_rows(N, ROW_BLOCK)
    x2 = scaffold.pad_rows(x.reshape(-1, N), br)
    dy2 = scaffold.pad_rows(g.reshape(-1, N), br)
    rows = x2.shape[0]
    dx, db = pl.pallas_call(
        functools.partial(_bg_bwd_kernel, approximate=approximate),
        grid=(rows // br,),
        in_specs=[scaffold.row_spec(br, N), scaffold.bcast_spec(1, N),
                  scaffold.row_spec(br, N)],
        out_specs=(scaffold.row_spec(br, N), scaffold.bcast_spec(1, N)),
        out_shape=(jax.ShapeDtypeStruct((rows, N), x.dtype),
                   jax.ShapeDtypeStruct((1, N), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((1, N), jnp.float32)],
        interpret=scaffold.interpret_mode(),
    )(x2, bias.astype(x.dtype).reshape(1, N), dy2)
    R = x.reshape(-1, N).shape[0]
    return dx[:R].reshape(shape), db.reshape(N).astype(bias.dtype)


bias_gelu.defvjp(_bg_fwd, _bg_bwd)


def bias_gelu_reference(x, bias, approximate):
    """The unfused jnp path — identical expression to nn.Linear's
    bias-add followed by ops.nn_ops.gelu."""
    return jax.nn.gelu(x + bias.astype(x.dtype), approximate=approximate)


# ---------------------------------------------------------------------------
# dropout + residual add
# ---------------------------------------------------------------------------
def _da_fwd_kernel(x_ref, r_ref, m_ref, o_ref, *, keep_prob):
    x = x_ref[...]
    dropped = jnp.where(m_ref[...] > 0.5, x / keep_prob,
                        jnp.zeros_like(x)).astype(x.dtype)
    o_ref[...] = dropped + r_ref[...]


def _da_bwd_kernel(m_ref, dy_ref, dx_ref, *, keep_prob):
    dy = dy_ref[...]
    dx_ref[...] = jnp.where(m_ref[...] > 0.5, dy / keep_prob,
                            jnp.zeros_like(dy)).astype(dx_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def dropout_add(x, residual, mask, p):
    """y = upscale-dropout(x) + residual; mask is the fp32 0/1 keep
    mask (drawn by the caller so fused and reference routes share the
    exact bernoulli draw)."""
    return _da_fwd_impl(x, residual, mask, p)


def _da_call(kernel, args, shape, dtype, n_in):
    N = shape[-1]
    rows = args[0].shape[0]
    br = scaffold.pick_block_rows(N, ROW_BLOCK)
    return pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=[scaffold.row_spec(br, N)] * n_in,
        out_specs=scaffold.row_spec(br, N),
        out_shape=jax.ShapeDtypeStruct((rows, N), dtype),
        interpret=scaffold.interpret_mode(),
    )(*args)


def _da_fwd_impl(x, residual, mask, p):
    shape = x.shape
    N = shape[-1]
    br = scaffold.pick_block_rows(N, ROW_BLOCK)
    pad = lambda a: scaffold.pad_rows(a.reshape(-1, N), br)
    o = _da_call(functools.partial(_da_fwd_kernel, keep_prob=1.0 - p),
                 [pad(x), pad(residual), pad(mask)], shape, x.dtype, 3)
    R = x.reshape(-1, N).shape[0]
    return o[:R].reshape(shape)


def _da_fwd(x, residual, mask, p):
    return _da_fwd_impl(x, residual, mask, p), mask


def _da_bwd(p, mask, g):
    shape = g.shape
    N = shape[-1]
    br = scaffold.pick_block_rows(N, ROW_BLOCK)
    pad = lambda a: scaffold.pad_rows(a.reshape(-1, N), br)
    dx = _da_call(functools.partial(_da_bwd_kernel, keep_prob=1.0 - p),
                  [pad(mask), pad(g)], shape, g.dtype, 2)
    R = g.reshape(-1, N).shape[0]
    return dx[:R].reshape(shape), g, jnp.zeros_like(mask)


dropout_add.defvjp(_da_fwd, _da_bwd)


def dropout_add_reference(x, residual, mask, p):
    """The unfused jnp path — the exact expression ops.nn_ops.dropout
    (upscale_in_train) followed by the residual add runs."""
    return jnp.where(mask > 0.5, x / (1.0 - p),
                     jnp.zeros_like(x)).astype(x.dtype) + residual
