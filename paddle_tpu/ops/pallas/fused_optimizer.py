"""Fused one-pass optimizer step over flat buckets (TPP, arXiv:2104.05755).

The PR-4 bucketed engines already coalesced the optimizer phase into a
few flat, dtype-homogeneous 1-D buckets, but each bucket's update was
still a CHAIN of small XLA elementwise ops: unscale multiply, nonfinite
reduction, global-clip sum-of-squares, two moment updates, bias
corrections, the parameter step, the fp32-master cast-back — each a
separate HBM round-trip over the bucket. The two kernels here collapse
that chain into one read and one write per operand:

  * `grad_stats` — ONE pass over a gradient bucket producing the two
    scalars every step needs before it can touch the params: the
    global-clip sum-of-squares contribution and the nonfinite count
    (GradScaler found-inf). Accumulates across the sequential TPU grid
    into (1, 1) outputs.
  * `fused_shard_update` — ONE pass per bucket shard applying
    unscale/clip prefactor + decay-into-grad + the optimizer's own
    `update` rule + the found-inf no-op guard + the fp32-master
    cast-back, reading each state exactly once and writing each exactly
    twice (param dtype + master).

The update kernel is GENERIC over elementwise optimizers: the kernel
body calls `optimizer.update(p32, g32, state, lr)` directly — for an
elementwise rule that is pure jnp elementwise code, which Pallas traces
into the kernel like any other body. Vector states stream as row blocks
beside the params; scalar states (Adam beta powers) ride in a packed
(1, NS) fp32 block and their updated values are written through (1, 1)
accumulator outputs (every grid step writes the same value). Optimizers
opt in with `_pallas_fusible = True` (optimizer.py tags SGD, Momentum,
Adam/AdamW, Adamax, Adagrad, RMSProp, Adadelta, DecayedAdagrad);
anything untagged —
or non-elementwise — keeps the XLA chain and is counted as a fallback
route.

Numerics contract (tests/test_fused_primitives.py): in fp32 the fused
update is BIT-identical to `core.bucketing.shard_update` on the same
inputs — the kernel body runs the same ops in the same order, and
chunking a strictly-per-element rule cannot reorder anything. The one
place op order does change is `grad_stats`' sum-of-squares (blockwise
accumulation vs one whole-array reduction), so clip factors agree to
float tolerance, not bitwise.

Routing: `FLAGS_fused_optimizer` (None = auto: TPU kernel / CPU
reference), via scaffold.use_kernel — decisions are visible as
`ptpu_pallas_*_invocations_total{primitive='optimizer_step'|'grad_stats'}`.
"""
import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

from . import scaffold

STEP = 'optimizer_step'
STATS = 'grad_stats'
FLAG = 'FLAGS_fused_optimizer'


def fusible(optimizer):
    """Optimizers whose flat update may run inside the Pallas kernel:
    strictly elementwise AND tagged `_pallas_fusible` (the tag asserts
    the `update` body is pure jnp elementwise code with only scalar
    side states — verified by the parity tests)."""
    return bool(getattr(optimizer, '_elementwise', False)) and \
        bool(getattr(optimizer, '_pallas_fusible', False))


def use_fused_update(optimizer):
    return scaffold.use_kernel(STEP, FLAG, supported=fusible(optimizer))


def use_fused_stats():
    return scaffold.use_kernel(STATS, FLAG)


# ---------------------------------------------------------------------------
# grad_stats: one pass -> (sum of squares, nonfinite count)
# ---------------------------------------------------------------------------
def _stats_kernel(x_ref, sum_ref, cnt_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        sum_ref[0, 0] = 0.0
        cnt_ref[0, 0] = 0.0
    x = x_ref[...].astype(jnp.float32)
    # NOT masked: a nonfinite gradient must poison the sum exactly like
    # the unfused jnp.sum(g*g) does (the clip factor then trips the
    # numerics guards); the count reports it separately for found-inf
    sum_ref[0, 0] += jnp.sum(x * x)
    cnt_ref[0, 0] += jnp.sum((~jnp.isfinite(x)).astype(jnp.float32))


def grad_stats_pallas(flat):
    """(sum_sq fp32 scalar, nonfinite count fp32 scalar) of a flat
    array in one pass. Zero row-padding adds 0 to both."""
    x2 = scaffold.to_rows(flat.reshape(-1))
    rows = x2.shape[0]
    br = min(scaffold.ROW_BLOCK, rows)
    s, c = pl.pallas_call(
        _stats_kernel,
        grid=(rows // br,),
        in_specs=[scaffold.row_spec(br, scaffold.LANES)],
        out_specs=(scaffold.acc_spec(), scaffold.acc_spec()),
        out_shape=(jax.ShapeDtypeStruct((1, 1), jnp.float32),
                   jax.ShapeDtypeStruct((1, 1), jnp.float32)),
        interpret=scaffold.interpret_mode(),
    )(x2)
    return s[0, 0], c[0, 0]


# ---------------------------------------------------------------------------
# fused shard update
# ---------------------------------------------------------------------------
def _update_kernel(*refs, opt, vec_keys, scalar_keys, has_master,
                   use_pref, use_fi, wd):
    """One row block of the bucket shard: unscale/clip -> decay-into-grad
    -> optimizer.update -> found-inf guard -> param-dtype + master
    writes. Scalar layout in sc_ref: [lr, prefactor, found_inf,
    *scalar_states]."""
    n_vec = len(vec_keys)
    sc_ref, p_ref, g_ref = refs[0], refs[1], refs[2]
    k = 3
    master_ref = refs[k] if has_master else None
    k += 1 if has_master else 0
    vec_refs = refs[k:k + n_vec]
    outs = refs[k + n_vec:]

    lr = sc_ref[0, 0]
    g32 = g_ref[...].astype(jnp.float32)
    if use_pref:
        g32 = g32 * sc_ref[0, 1]
    p32 = master_ref[...] if has_master \
        else p_ref[...].astype(jnp.float32)
    if wd:
        g32 = g32 + wd * p32
    state = {key: r[...] for key, r in zip(vec_keys, vec_refs)}
    for j, key in enumerate(scalar_keys):
        state[key] = sc_ref[0, 3 + j]
    new32, ns = opt.update(p32, g32, state, lr)
    new_p = new32.astype(p_ref.dtype)
    if use_fi:
        skip = sc_ref[0, 2] > 0.5
        new_p = jnp.where(skip, p_ref[...], new_p)
        new32 = jnp.where(skip, p32, new32)
        ns = {key: jnp.where(skip, state[key], ns[key])
              for key in ns}
    o = 0
    outs[o][...] = new_p
    o += 1
    if has_master:
        outs[o][...] = new32
        o += 1
    for key in vec_keys:
        outs[o][...] = ns[key].astype(outs[o].dtype)
        o += 1
    for key in scalar_keys:
        outs[o][0, 0] = ns[key].astype(jnp.float32)
        o += 1


def fused_shard_update(optimizer, p_shard, g32_shard, st, lr,
                       prefactor=None, found_inf=None):
    """Drop-in fused twin of `core.bucketing.shard_update` (same
    signature and state contract), with the unscale/clip `prefactor`
    multiply and the GradScaler `found_inf` no-op guard folded into the
    same pass. Returns (new_p_shard, new_state)."""
    st = dict(st)
    master = st.pop('master', None)
    low = p_shard.dtype != jnp.float32
    has_master = master is not None or (
        low and getattr(optimizer, '_multi_precision', True))
    if master is None and has_master:
        master = p_shard.astype(jnp.float32)
    vec_keys = sorted(k for k in st if jnp.ndim(st[k]) >= 1)
    scalar_keys = sorted(k for k in st if jnp.ndim(st[k]) == 0)
    wd = getattr(optimizer, '_weight_decay', None)
    wd = float(wd) if (wd and optimizer._decay_into_grad()) else 0.0

    L = p_shard.shape[0]
    vecs = [p_shard, g32_shard] + ([master] if has_master else []) \
        + [st[k] for k in vec_keys]
    vecs2d = [scaffold.to_rows(v) for v in vecs]
    rows = vecs2d[0].shape[0]
    br = min(scaffold.ROW_BLOCK, rows)
    scalars = [jnp.asarray(lr, jnp.float32),
               jnp.asarray(1.0 if prefactor is None else prefactor,
                           jnp.float32),
               (jnp.asarray(found_inf).astype(jnp.float32)
                if found_inf is not None
                else jnp.asarray(0.0, jnp.float32))]
    scalars += [jnp.asarray(st[k], jnp.float32) for k in scalar_keys]
    sc = jnp.stack(scalars).reshape(1, -1)

    blk = scaffold.row_spec(br, scaffold.LANES)
    in_specs = [scaffold.bcast_spec(1, sc.shape[1])] \
        + [blk] * len(vecs2d)
    out_specs = [blk] * (1 + (1 if has_master else 0) + len(vec_keys)) \
        + [scaffold.acc_spec()] * len(scalar_keys)
    shp2d = vecs2d[0].shape
    out_shape = [jax.ShapeDtypeStruct(shp2d, p_shard.dtype)]
    if has_master:
        out_shape.append(jax.ShapeDtypeStruct(shp2d, jnp.float32))
    out_shape += [jax.ShapeDtypeStruct(shp2d, st[k].dtype)
                  for k in vec_keys]
    out_shape += [jax.ShapeDtypeStruct((1, 1), jnp.float32)
                  for _ in scalar_keys]

    kernel = functools.partial(
        _update_kernel, opt=optimizer, vec_keys=tuple(vec_keys),
        scalar_keys=tuple(scalar_keys), has_master=has_master,
        use_pref=prefactor is not None, use_fi=found_inf is not None,
        wd=wd)
    outs = pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        interpret=scaffold.interpret_mode(),
    )(sc, *vecs2d)

    o = 0
    new_p = scaffold.from_rows(outs[o], L)
    o += 1
    ns = {}
    if has_master:
        ns['master'] = scaffold.from_rows(outs[o], L)
        o += 1
    for k in vec_keys:
        ns[k] = scaffold.from_rows(outs[o], L)
        o += 1
    for j, k in enumerate(scalar_keys):
        val = outs[o + j][0, 0]
        ns[k] = val.astype(jnp.asarray(st[k]).dtype)
    return new_p, ns
