"""Shared scaffolding for the Pallas primitives library (TPP,
arXiv:2104.05755).

Every fused primitive in this package — flash/paged attention, the fused
optimizer step, LayerNorm, bias+GELU, dropout+residual — shares the same
skeleton:

  * an AUTO-ROUTE: the Pallas kernel on TPU, a pure-`jnp` reference path
    on CPU, force-overridable per primitive with a `FLAGS_*` flag (tests
    force the kernel on the CPU mesh, where it runs under Pallas
    interpret mode so CI exercises the body that lowers on TPU);
  * 1-D -> lane-tiled 2-D reshaping for flat-buffer kernels (the fused
    optimizer step streams [rows, 128] blocks of a bucket shard);
  * row-grid BlockSpec builders for "grid over row blocks, broadcast
    row for weights, (1, 1) accumulator" kernels;
  * routing OBSERVABILITY: every route decision bumps
    `ptpu_pallas_{kernel,fallback}_invocations_total{primitive=...}`
    through core.monitor, so a silently-degraded fallback (e.g. a flag
    typo sending the optimizer step back to the XLA op chain) is
    visible in StepTelemetry.snapshot()['pallas'] and
    `tools/health_dump.py pallas`. Routes are decided at TRACE time
    (the compiled step replays the chosen route every step), so the
    counters count routing decisions, not per-step executions — same
    convention as the trace-time ptpu_comm_* byte model. Primitives:
    flash_attention, flash_dropout (the dropout-fused causal kernels —
    ISSUE 12), paged_attention, optimizer_step, grad_stats,
    layer_norm, bias_gelu, dropout_add.

Adding a kernel on this scaffolding costs the kernel body plus a
~20-line wrapper: pick a primitive name, call `use_kernel(name, flag)`
to route, `to_rows`/`from_rows` or `row_spec`/`bcast_spec` for layout,
and pass `interpret=interpret_mode()` to `pl.pallas_call`
(docs/performance.md#fused-primitives walks through one).
"""
import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

# f32 VPU lane width; flat-buffer kernels reshape 1-D buckets to
# [rows, LANES] so blocks are tile-aligned on TPU
LANES = 128
# default rows per grid step for flat-buffer kernels: 256 x 128 f32
# blocks = 128 KB per operand ref — comfortably inside VMEM with the
# ~10 operand/output refs the fused optimizer step carries
ROW_BLOCK = 256

KERNEL = 'kernel'
FALLBACK = 'fallback'


def interpret_mode():
    """Pallas TPU kernels only lower on TPU; under the CPU test mesh the
    same kernel bodies run in interpret mode so CI covers them."""
    return jax.default_backend() == 'cpu'


def fit_block(block, n):
    """Largest power-of-two shrink of `block` that divides `n` (shared by
    the flash kernels' tile fitting — a block that does not divide the
    sequence length would silently misalign in-kernel position iotas
    against pl.ds clamping)."""
    block = min(block, n)
    while block > 1 and n % block:
        block //= 2
    return block if block >= 1 and n % block == 0 else n


def record_route(primitive, used_kernel):
    """Count one routing decision for `primitive` (trace-time)."""
    from ...core import monitor as _m
    name = ('ptpu_pallas_kernel_invocations_total' if used_kernel
            else 'ptpu_pallas_fallback_invocations_total')
    _m.counter(
        name,
        help='Pallas-primitive routing decisions (trace-time), by '
             'primitive: kernel = fused Pallas body, fallback = '
             'reference jnp/XLA path',
        labelnames=('primitive',)).inc(1, primitive=primitive)


def use_kernel(primitive, flag=None, supported=True, record=True):
    """The flash/paged-style auto-route: Pallas kernel on TPU, reference
    path on CPU; `flag` (a FLAGS_* name, None = auto) forces either way;
    `supported=False` pins the fallback (unsupported shape/optimizer)
    regardless of the flag. Records the decision unless `record=False`.
    """
    use = False
    if supported:
        forced = None
        if flag is not None:
            from ...core import flags as _flags
            forced = _flags.flag(flag, None)
        use = bool(forced) if forced is not None \
            else jax.default_backend() == 'tpu'
    if record:
        record_route(primitive, use)
    return use


# ---------------------------------------------------------------------------
# layout helpers
# ---------------------------------------------------------------------------
def to_rows(flat, block_rows=ROW_BLOCK, lanes=LANES):
    """Zero-pad a 1-D array and reshape to [rows, lanes] with rows a
    multiple of `block_rows` — the flat-buffer kernel layout. Zero pad
    is safe for every current kernel: stats add 0, optimizer updates of
    (p=0, g=0, m=0) stay 0, and callers slice the pad off with
    `from_rows`."""
    n = flat.shape[0]
    rows = -(-n // lanes)
    # zero-size inputs still get one (all-pad) block so the grid is
    # never empty; callers slice the pad off, so the result is exact
    rows = max(-(-rows // block_rows) * block_rows, block_rows)
    pad = rows * lanes - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(rows, lanes)


def from_rows(arr2d, n):
    """Inverse of `to_rows`: back to 1-D, pad dropped."""
    return arr2d.reshape(-1)[:n]


def pad_rows(x2d, block_rows):
    """Zero-pad a [R, N] array so R divides into `block_rows` blocks
    (R = 0 still yields one all-pad block — the grid is never empty;
    pad rows are inert in every kernel and sliced off by callers)."""
    r = x2d.shape[0]
    rows = max(-(-r // block_rows) * block_rows, block_rows)
    if rows != r:
        x2d = jnp.concatenate(
            [x2d, jnp.zeros((rows - r,) + x2d.shape[1:], x2d.dtype)])
    return x2d


def pick_block_rows(ncols, want):
    """Rows per grid block for a [R, ncols] kernel, shrunk so one block
    stays around `want` x LANES elements regardless of the feature dim
    (a fixed row count would grow VMEM use linearly with ncols — at
    ffn_hidden 32k a 128-row fp32 block is 16 MB per ref). Floor of 8
    keeps f32 sublane tiling."""
    return min(want, max(8, (want * LANES) // max(ncols, 1)))


def row_spec(block_rows, ncols):
    """Grid-blocked rows: program i sees rows [i*block_rows, ...)."""
    return pl.BlockSpec((block_rows, ncols), lambda i: (i, 0))


def bcast_spec(nrows, ncols):
    """Same block for every program (weights, packed scalars)."""
    return pl.BlockSpec((nrows, ncols), lambda i: (0, 0))


def acc_spec():
    """(1, 1) accumulator output revisited by every program (the
    sequential TPU grid keeps it resident; interpret mode matches)."""
    return pl.BlockSpec((1, 1), lambda i: (0, 0))


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------
def routes_snapshot():
    """{primitive: {'kernel': n, 'fallback': n}} from the monitor
    counters (JSON-ready; bench legs and StepTelemetry embed it)."""
    from ...core import monitor as _m
    reg = _m.metrics()
    out = {}
    for name, key in (('ptpu_pallas_kernel_invocations_total', KERNEL),
                      ('ptpu_pallas_fallback_invocations_total',
                       FALLBACK)):
        m = reg.get(name)
        if m is None:
            continue
        for labels, child in m._series().items():
            prim = labels[0] if labels else ''
            out.setdefault(prim, {KERNEL: 0, FALLBACK: 0})[key] = \
                int(child.value())
    return out


def active_primitives():
    """Primitives whose Pallas kernel route was taken at least once —
    the bench record's `detail.fused_primitives` evidence list."""
    return sorted(p for p, c in routes_snapshot().items()
                  if c.get(KERNEL, 0) > 0)


def snapshot():
    """StepTelemetry.snapshot()['pallas'] payload."""
    routes = routes_snapshot()
    if not routes:
        return None
    return {'routes': routes, 'active': active_primitives()}
