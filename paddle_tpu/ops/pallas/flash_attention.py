"""Flash attention — Pallas TPU kernel (causal / non-causal, optional mask).

Reference parity: operators/fused/fused_attention_op +
fused_softmax_mask_upper_triangle (N27) — the attention fusions the reference
hand-writes in CUDA. TPU-native: a blockwise online-softmax kernel
(Flash-style) so the [L, L] score matrix never materializes in HBM; each
grid step streams K/V blocks through VMEM and keeps fp32 running max /
normalizer / accumulator in VMEM scratch. Q/K/V tiles are MXU-shaped
(block × head_dim with head_dim 64/128).

Mask support (BERT/encoder path): an additive key-padding bias of shape
[B, L_k] (0 at kept keys, large-negative at padded keys) streams through the
same kernels — the [B, 1, 1, L] additive masks nn.MultiHeadAttention
produces reduce to this form, so masked encoder attention runs flash instead
of falling back to the materializing dense path (reference parity:
fused_softmax_mask_op.cu, the padding-mask softmax fusion).

Backward: fully fused Pallas kernels (no [L, L] materialization): the
forward also emits per-row logsumexp; dq streams K/V blocks per q-block and
dk/dv stream Q/dO blocks per kv-block (the standard two-pass flash backward),
each O(L) memory. 8.6x faster than XLA's materializing backward at L=8192
and exact to fp32 noise (verified vs reference at HIGHEST precision).

On CPU (tests) the kernels run under Pallas interpret mode, so the same
code paths are exercised by the CI suite on the virtual-device mesh.
"""
import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...core.tensor import Tensor
from ...core.autograd import run_op
from . import scaffold

NEG_INF = -1e30

# default VMEM tile extents — 512x512 measured best at GPT shapes
# (L=2048, d=128): 64.7% vs 58.8% step MFU with 256 tiles (fewer grid
# programs + fori iterations per program amortize the per-block
# epilogue). Env override for experiments, read once at import; a
# malformed value falls back instead of breaking package import.


def _env_block(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


_BLOCK_Q = _env_block('PTPU_FLASH_BLOCK_Q', 512)
_BLOCK_K = _env_block('PTPU_FLASH_BLOCK_K', 512)


# tile fitting + interpret-mode forcing live in the shared scaffolding
# (scaffold.py) — a block that does not divide L would make pl.ds clamp
# the last slice start while the in-kernel position iota keeps counting,
# silently misaligning the mask (true for ANY block size)
_fit_block = scaffold.fit_block
_interpret = scaffold.interpret_mode


def _flash_fwd_kernel(*refs, block_k, seq_len, scale, causal, has_bias,
                      has_dropout=False, inv_keep=1.0):
    """One (batch*head, q_block) program: stream K/V blocks, online softmax.

    q_ref: [block_q, d]; k_ref/v_ref: [seq_len, d]; bias_ref (optional):
    [1, seq_len] additive key bias for this batch row; mask_ref (optional,
    attention-prob dropout): [block_q, seq_len] int8 keep mask for this
    q block — the softmax normalizer uses the UNdropped probs (standard
    attention-dropout semantics: the mask applies to the softmax output,
    upscaled by 1/keep); o_ref: [block_q, d]; lse_ref: [block_q, 1]
    per-row logsumexp (saved for the fused backward).
    """
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    bias_ref = next(it) if has_bias else None
    mask_ref = next(it) if has_dropout else None
    o_ref, lse_ref = next(it), next(it)
    block_q = q_ref.shape[0]
    d = q_ref.shape[1]
    qi = pl.program_id(1)
    q_offset = qi * block_q

    q = q_ref[:].astype(jnp.float32) * scale

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        # only blocks overlapping [0, q_offset + block_q) matter
        num_k_blocks = pl.cdiv(q_offset + block_q, block_k)

    def body(ki, carry):
        m, l, acc = carry
        k_start = ki * block_k
        k = k_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        if bias_ref is not None:
            b = bias_ref[0, pl.ds(k_start, block_k)].astype(jnp.float32)
            s = s + b[None, :]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 0) + q_offset
            cols = jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 1) + k_start
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = p
        if mask_ref is not None:
            mblk = mask_ref[:, pl.ds(k_start, block_k)]
            pv = p * jnp.where(mblk != 0, inv_keep, 0.0)
        acc_new = acc * alpha + jax.lax.dot_general(
            pv, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k_blocks, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[:] = m + jnp.log(l_safe)


def _flash_bwd_dq_kernel(*refs, block_k, seq_len, scale, causal, has_bias,
                         has_dropout=False, inv_keep=1.0):
    """dq for one (bh, q_block): stream K/V blocks.
    ds = p * (d*dP - delta); dq = scale * ds @ k (d = dropout keep
    factor; delta = rowsum(dO*O) already carries the dropped probs)."""
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    bias_ref = next(it) if has_bias else None
    mask_ref = next(it) if has_dropout else None
    do_ref, lse_ref, delta_ref, dq_ref = (next(it), next(it), next(it),
                                          next(it))
    block_q = q_ref.shape[0]
    qi = pl.program_id(1)
    q_offset = qi * block_q
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:]      # [block_q, 1]
    delta = delta_ref[:]  # [block_q, 1]

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        num_k_blocks = pl.cdiv(q_offset + block_q, block_k)

    def body(ki, dq):
        k_start = ki * block_k
        k = k_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            b = bias_ref[0, pl.ds(k_start, block_k)].astype(jnp.float32)
            s = s + b[None, :]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 0) + q_offset
            cols = jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 1) + k_start
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if mask_ref is not None:
            mblk = mask_ref[:, pl.ds(k_start, block_k)]
            dp = dp * jnp.where(mblk != 0, inv_keep, 0.0)
        ds = p * (dp - delta)
        return dq + scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_k_blocks, body,
                           jnp.zeros_like(q, jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(*refs, block_q, seq_len, scale, causal, has_bias,
                          has_dropout=False, inv_keep=1.0):
    """dk/dv for one (bh, kv_block): stream Q blocks.
    dv = (p*d)^T @ do; dk = scale * ds^T @ q (d = dropout keep factor)."""
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    bias_ref = next(it) if has_bias else None
    mask_ref = next(it) if has_dropout else None
    do_ref, lse_ref, delta_ref, dk_ref, dv_ref = (
        next(it), next(it), next(it), next(it), next(it))
    block_k = k_ref.shape[0]
    ki = pl.program_id(1)
    k_start = ki * block_k
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    if bias_ref is not None:
        bias_blk = bias_ref[0, pl.ds(k_start, block_k)].astype(jnp.float32)
    else:
        bias_blk = None

    num_q_blocks = pl.cdiv(seq_len, block_q)
    first_q = (k_start // block_q) if causal else 0

    def body(qi, carry):
        dk, dv = carry
        q_offset = qi * block_q
        q = q_ref[pl.ds(q_offset, block_q), :].astype(jnp.float32)
        do = do_ref[pl.ds(q_offset, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(q_offset, block_q), :]
        delta = delta_ref[pl.ds(q_offset, block_q), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if bias_blk is not None:
            s = s + bias_blk[None, :]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 0) + q_offset
            cols = jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 1) + k_start
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)  # [bq, bk]
        if mask_ref is not None:
            mblk = mask_ref[pl.ds(q_offset, block_q), :]
            d_keep = jnp.where(mblk != 0, inv_keep, 0.0)
        else:
            d_keep = None
        dv_new = dv + jax.lax.dot_general(
            p if d_keep is None else p * d_keep, do,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if d_keep is not None:
            dp = dp * d_keep
        ds = p * (dp - delta)
        dk_new = dk + scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk0 = jnp.zeros_like(k, jnp.float32)
    dv0 = jnp.zeros_like(v, jnp.float32)
    dk, dv = jax.lax.fori_loop(first_q, num_q_blocks, body, (dk0, dv0))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _bias_spec(num_heads, L):
    # bias arrives as [B, 1, L_k] (the length-1 middle dim keeps the block's
    # trailing dims equal to the array's — Mosaic's block constraint);
    # program b covers batch row b // num_heads. lax.div (truncating)
    # instead of Python // — floor-divide lowers with a negative-rounding
    # select that Mosaic rejects in index maps.
    return pl.BlockSpec(
        (None, 1, L),
        lambda b, i, nh=num_heads: (jax.lax.div(b, jnp.int32(nh)), 0, 0))


# -- PACKED layout (transpose-free MHA path) ----------------------------------
# q/k/v as [B, L, H*D] — the natural projection output (avoiding the
# [B, nh, L, hd] physical transpose XLA materializes before a custom
# call, measured ~14% of the BERT step). One program per (batch,
# q-block) loads the full H*D row block once and runs the online-softmax
# stream per head over STATIC column slices (head loop unrolled at trace
# time) — no redundant HBM fetches, MXU-shaped (block, D) tiles.


def _flash_fwd_kernel_packed(*refs, block_k, seq_len, scale, causal,
                             has_bias, num_heads, head_dim):
    """One (batch, q_block) program over packed [L, H*D] slabs."""
    if has_bias:
        q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs
        bias_ref = None
    block_q = q_ref.shape[0]
    d = head_dim
    qi = pl.program_id(1)
    q_offset = qi * block_q
    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        num_k_blocks = pl.cdiv(q_offset + block_q, block_k)

    for h in range(num_heads):
        q = q_ref[:, h * d:(h + 1) * d].astype(jnp.float32) * scale
        m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
        l0 = jnp.zeros((block_q, 1), jnp.float32)
        acc0 = jnp.zeros((block_q, d), jnp.float32)

        def body(ki, carry, q=q, h=h):
            m, l, acc = carry
            k_start = ki * block_k
            k = k_ref[pl.ds(k_start, block_k),
                      h * d:(h + 1) * d].astype(jnp.float32)
            v = v_ref[pl.ds(k_start, block_k),
                      h * d:(h + 1) * d].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if bias_ref is not None:
                b = bias_ref[0, pl.ds(k_start,
                                      block_k)].astype(jnp.float32)
                s = s + b[None, :]
            if causal:
                rows = jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0) + q_offset
                cols = jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1) + k_start
                s = jnp.where(rows >= cols, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * alpha + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new

        m, l, acc = jax.lax.fori_loop(0, num_k_blocks, body,
                                      (m0, l0, acc0))
        l_safe = jnp.maximum(l, 1e-30)
        o_ref[:, h * d:(h + 1) * d] = (acc / l_safe).astype(o_ref.dtype)
        lse_ref[:, h:h + 1] = m + jnp.log(l_safe)


def _flash_bwd_dq_kernel_packed(*refs, block_k, seq_len, scale, causal,
                                has_bias, num_heads, head_dim):
    if has_bias:
        (q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
         dq_ref) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref = refs
        bias_ref = None
    block_q = q_ref.shape[0]
    d = head_dim
    qi = pl.program_id(1)
    q_offset = qi * block_q
    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        num_k_blocks = pl.cdiv(q_offset + block_q, block_k)

    for h in range(num_heads):
        q = q_ref[:, h * d:(h + 1) * d].astype(jnp.float32)
        do = do_ref[:, h * d:(h + 1) * d].astype(jnp.float32)
        lse = lse_ref[:, h:h + 1]
        delta = delta_ref[:, h:h + 1]

        def body(ki, dq, q=q, do=do, lse=lse, delta=delta, h=h):
            k_start = ki * block_k
            k = k_ref[pl.ds(k_start, block_k),
                      h * d:(h + 1) * d].astype(jnp.float32)
            v = v_ref[pl.ds(k_start, block_k),
                      h * d:(h + 1) * d].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if bias_ref is not None:
                b = bias_ref[0, pl.ds(k_start,
                                      block_k)].astype(jnp.float32)
                s = s + b[None, :]
            if causal:
                rows = jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0) + q_offset
                cols = jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1) + k_start
                s = jnp.where(rows >= cols, s, NEG_INF)
            p = jnp.exp(s - lse)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta)
            return dq + scale * jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        dq = jax.lax.fori_loop(0, num_k_blocks, body,
                               jnp.zeros((block_q, d), jnp.float32))
        dq_ref[:, h * d:(h + 1) * d] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel_packed(*refs, block_q, seq_len, scale, causal,
                                 has_bias, num_heads, head_dim):
    if has_bias:
        (q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref) = refs
        bias_ref = None
    block_k = k_ref.shape[0]
    d = head_dim
    ki = pl.program_id(1)
    k_start = ki * block_k
    num_q_blocks = pl.cdiv(seq_len, block_q)
    first_q = (k_start // block_q) if causal else 0
    if bias_ref is not None:
        bias_blk = bias_ref[0, pl.ds(k_start,
                                     block_k)].astype(jnp.float32)
    else:
        bias_blk = None

    for h in range(num_heads):
        k = k_ref[:, h * d:(h + 1) * d].astype(jnp.float32)
        v = v_ref[:, h * d:(h + 1) * d].astype(jnp.float32)

        def body(qi, carry, k=k, v=v, h=h):
            dk, dv = carry
            q_offset = qi * block_q
            q = q_ref[pl.ds(q_offset, block_q),
                      h * d:(h + 1) * d].astype(jnp.float32)
            do = do_ref[pl.ds(q_offset, block_q),
                        h * d:(h + 1) * d].astype(jnp.float32)
            lse = lse_ref[pl.ds(q_offset, block_q), h:h + 1]
            delta = delta_ref[pl.ds(q_offset, block_q), h:h + 1]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if bias_blk is not None:
                s = s + bias_blk[None, :]
            if causal:
                rows = jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0) + q_offset
                cols = jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1) + k_start
                s = jnp.where(rows >= cols, s, NEG_INF)
            p = jnp.exp(s - lse)
            dv_new = dv + jax.lax.dot_general(
                p, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta)
            dk_new = dk + scale * jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return dk_new, dv_new

        dk, dv = jax.lax.fori_loop(
            first_q, num_q_blocks, body,
            (jnp.zeros((block_k, d), jnp.float32),
             jnp.zeros((block_k, d), jnp.float32)))
        dk_ref[:, h * d:(h + 1) * d] = dk.astype(dk_ref.dtype)
        dv_ref[:, h * d:(h + 1) * d] = dv.astype(dv_ref.dtype)


def _flash_forward(q, k, v, bias=None, num_heads=1, causal=True,
                   block_q=None, block_k=None, with_lse=False,
                   dropout_mask=None, dropout=0.0):
    """q/k/v: [BH, L, D]; bias: optional [B, L_k] additive key bias;
    dropout_mask: optional [BH, L, L] int8 keep mask (attention-prob
    dropout at `dropout`, mask drawn by the caller OUTSIDE the kernel so
    the RNG-stream point matches the dense path)
    → [BH, L, D] (+ optional [BH, L] logsumexp)."""
    bh, L, d = q.shape
    block_q = _fit_block(block_q or _BLOCK_Q, L)
    block_k = _fit_block(block_k or _BLOCK_K, L)
    scale = 1.0 / math.sqrt(d)
    grid = (bh, pl.cdiv(L, block_q))
    has_bias = bias is not None
    has_dropout = dropout_mask is not None
    if has_bias:
        bias = bias.reshape(bias.shape[0], 1, bias.shape[-1])
    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, seq_len=L, scale=scale,
        causal=causal, has_bias=has_bias, has_dropout=has_dropout,
        inv_keep=1.0 / (1.0 - dropout) if has_dropout else 1.0)
    in_specs = [
        pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((None, L, d), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((None, L, d), lambda b, i: (b, 0, 0)),
    ]
    args = [q, k, v]
    if has_bias:
        in_specs.append(_bias_spec(num_heads, L))
        args.append(bias)
    if has_dropout:
        in_specs.append(pl.BlockSpec((None, block_q, L),
                                     lambda b, i: (b, i, 0)))
        args.append(dropout_mask)
    o, lse = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((bh, L, d), q.dtype),
                   jax.ShapeDtypeStruct((bh, L, 1), jnp.float32)),
        grid=grid,
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ),
        interpret=_interpret(),
    )(*args)
    return (o, lse) if with_lse else o


def _flash_forward_packed(q, k, v, bias=None, num_heads=1, head_dim=64,
                          causal=False, block_q=None, block_k=None,
                          with_lse=False):
    """Packed layout: q/k/v [B, L, H*D]; bias optional [B, L_k]
    → [B, L, H*D] (+ optional [B, L, H] logsumexp)."""
    B, L, hd = q.shape
    block_q = _fit_block(block_q or _BLOCK_Q, L)
    block_k = _fit_block(block_k or _BLOCK_K, L)
    scale = 1.0 / math.sqrt(head_dim)
    has_bias = bias is not None
    if has_bias:
        bias = bias.reshape(bias.shape[0], 1, bias.shape[-1])
    kernel = functools.partial(
        _flash_fwd_kernel_packed, block_k=block_k, seq_len=L,
        scale=scale, causal=causal, has_bias=has_bias,
        num_heads=num_heads, head_dim=head_dim)
    in_specs = [
        pl.BlockSpec((None, block_q, hd), lambda b, i: (b, i, 0)),
        pl.BlockSpec((None, L, hd), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((None, L, hd), lambda b, i: (b, 0, 0)),
    ]
    args = [q, k, v]
    if has_bias:
        in_specs.append(pl.BlockSpec((None, 1, L),
                                     lambda b, i: (b, 0, 0)))
        args.append(bias)
    o, lse = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((B, L, hd), q.dtype),
                   jax.ShapeDtypeStruct((B, L, num_heads), jnp.float32)),
        grid=(B, pl.cdiv(L, block_q)),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((None, block_q, hd), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, num_heads),
                         lambda b, i: (b, i, 0)),
        ),
        interpret=_interpret(),
    )(*args)
    return (o, lse) if with_lse else o


def _flash_backward_packed(q, k, v, o, lse, do, bias=None, num_heads=1,
                           head_dim=64, causal=False, block_q=None,
                           block_k=None):
    """Packed-layout fused backward: arrays [B, L, H*D], lse/delta
    [B, L, H]."""
    B, L, hd = q.shape
    d = head_dim
    block_q = _fit_block(block_q or _BLOCK_Q, L)
    block_k = _fit_block(block_k or _BLOCK_K, L)
    scale = 1.0 / math.sqrt(d)
    has_bias = bias is not None
    if has_bias:
        bias = bias.reshape(bias.shape[0], 1, bias.shape[-1])
    # D_i per head = rowsum(dO_h * O_h)
    delta = (do.astype(jnp.float32) * o.astype(jnp.float32)) \
        .reshape(B, L, num_heads, d).sum(axis=-1)        # [B, L, H]

    row_spec = pl.BlockSpec((None, block_q, hd), lambda b, i: (b, i, 0))
    full_spec = pl.BlockSpec((None, L, hd), lambda b, i: (b, 0, 0))
    stat_blk = pl.BlockSpec((None, block_q, num_heads),
                            lambda b, i: (b, i, 0))
    stat_full = pl.BlockSpec((None, L, num_heads),
                             lambda b, i: (b, 0, 0))
    kvblk_spec = pl.BlockSpec((None, block_k, hd),
                              lambda b, j: (b, j, 0))
    bias_sp = pl.BlockSpec((None, 1, L), lambda b, i: (b, 0, 0))

    dq_in_specs = [row_spec, full_spec, full_spec]
    dq_args = [q, k, v]
    if has_bias:
        dq_in_specs.append(bias_sp)
        dq_args.append(bias)
    dq_in_specs += [row_spec, stat_blk, stat_blk]
    dq_args += [do, lse, delta]
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel_packed, block_k=block_k,
                          seq_len=L, scale=scale, causal=causal,
                          has_bias=has_bias, num_heads=num_heads,
                          head_dim=d),
        out_shape=jax.ShapeDtypeStruct((B, L, hd), q.dtype),
        grid=(B, pl.cdiv(L, block_q)),
        in_specs=dq_in_specs,
        out_specs=row_spec,
        interpret=_interpret(),
    )(*dq_args)

    dkv_in_specs = [full_spec, kvblk_spec, kvblk_spec]
    dkv_args = [q, k, v]
    if has_bias:
        dkv_in_specs.append(bias_sp)
        dkv_args.append(bias)
    dkv_in_specs += [full_spec, stat_full, stat_full]
    dkv_args += [do, lse, delta]
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel_packed, block_q=block_q,
                          seq_len=L, scale=scale, causal=causal,
                          has_bias=has_bias, num_heads=num_heads,
                          head_dim=d),
        out_shape=(jax.ShapeDtypeStruct((B, L, hd), k.dtype),
                   jax.ShapeDtypeStruct((B, L, hd), v.dtype)),
        grid=(B, pl.cdiv(L, block_k)),
        in_specs=dkv_in_specs,
        out_specs=(kvblk_spec, kvblk_spec),
        interpret=_interpret(),
    )(*dkv_args)
    return dq, dk, dv


def _flash_backward(q, k, v, o, lse, do, bias=None, num_heads=1,
                    causal=True, block_q=None, block_k=None,
                    dropout_mask=None, dropout=0.0):
    """Fused flash backward: no [L, L] score materialization.
    `dropout_mask`/`dropout` mirror the forward (attention-prob dropout
    folded into the kernels); delta = rowsum(dO*O) already carries the
    dropped probs, so the outer pass is unchanged."""
    bh, L, d = q.shape
    block_q = _fit_block(block_q or _BLOCK_Q, L)
    block_k = _fit_block(block_k or _BLOCK_K, L)
    scale = 1.0 / math.sqrt(d)
    has_bias = bias is not None
    has_dropout = dropout_mask is not None
    inv_keep = 1.0 / (1.0 - dropout) if has_dropout else 1.0
    if has_bias:
        bias = bias.reshape(bias.shape[0], 1, bias.shape[-1])
    # D_i = rowsum(dO * O) — tiny elementwise pass, leave it to XLA
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [bh, L, 1]

    dq_in_specs = [
        pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((None, L, d), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((None, L, d), lambda b, i: (b, 0, 0)),
    ]
    dq_args = [q, k, v]
    if has_bias:
        dq_in_specs.append(_bias_spec(num_heads, L))
        dq_args.append(bias)
    if has_dropout:
        dq_in_specs.append(pl.BlockSpec((None, block_q, L),
                                        lambda b, i: (b, i, 0)))
        dq_args.append(dropout_mask)
    dq_in_specs += [
        pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
    ]
    dq_args += [do, lse, delta]

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k, seq_len=L,
                          scale=scale, causal=causal, has_bias=has_bias,
                          has_dropout=has_dropout, inv_keep=inv_keep),
        out_shape=jax.ShapeDtypeStruct((bh, L, d), q.dtype),
        grid=(bh, pl.cdiv(L, block_q)),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
        interpret=_interpret(),
    )(*dq_args)

    dkv_in_specs = [
        pl.BlockSpec((None, L, d), lambda b, j: (b, 0, 0)),
        pl.BlockSpec((None, block_k, d), lambda b, j: (b, j, 0)),
        pl.BlockSpec((None, block_k, d), lambda b, j: (b, j, 0)),
    ]
    dkv_args = [q, k, v]
    if has_bias:
        dkv_in_specs.append(_bias_spec(num_heads, L))
        dkv_args.append(bias)
    if has_dropout:
        dkv_in_specs.append(pl.BlockSpec((None, L, block_k),
                                         lambda b, j: (b, 0, j)))
        dkv_args.append(dropout_mask)
    dkv_in_specs += [
        pl.BlockSpec((None, L, d), lambda b, j: (b, 0, 0)),
        pl.BlockSpec((None, L, 1), lambda b, j: (b, 0, 0)),
        pl.BlockSpec((None, L, 1), lambda b, j: (b, 0, 0)),
    ]
    dkv_args += [do, lse, delta]

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q, seq_len=L,
                          scale=scale, causal=causal, has_bias=has_bias,
                          has_dropout=has_dropout, inv_keep=inv_keep),
        out_shape=(jax.ShapeDtypeStruct((bh, L, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, L, d), v.dtype)),
        grid=(bh, pl.cdiv(L, block_k)),
        in_specs=dkv_in_specs,
        out_specs=(
            pl.BlockSpec((None, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j: (b, j, 0)),
        ),
        interpret=_interpret(),
    )(*dkv_args)
    return dq, dk, dv


def _reference_attention(q, k, v, bias=None, num_heads=1, causal=True):
    """jnp reference — numerics oracle for the kernels (and the VJP
    recompute pairing). bias: optional [B, L_k] additive key bias."""
    d = q.shape[-1]
    s = jnp.einsum('bqd,bkd->bqk', q, k,
                   preferred_element_type=jnp.float32) / math.sqrt(d)
    if bias is not None:
        bh = q.shape[0]
        b = jnp.repeat(bias.astype(jnp.float32), bh // bias.shape[0], axis=0)
        s = s + b[:, None, :]
    if causal:
        L = q.shape[1]
        mask = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bqk,bkd->bqd', p.astype(q.dtype), v)


# -- causal, no mask (GPT path) ------------------------------------------------

@jax.custom_vjp
def flash_attention_bhld(q, k, v):
    return _flash_forward(q, k, v, causal=True)


def _fa_fwd(q, k, v):
    o, lse = _flash_forward(q, k, v, causal=True, with_lse=True)
    return o, (q, k, v, o, lse)


def _fa_bwd(res, g):
    q, k, v, o, lse = res
    return _flash_backward(q, k, v, o, lse, g, causal=True)


flash_attention_bhld.defvjp(_fa_fwd, _fa_bwd)


# -- causal + attention-prob dropout (GPT training path, ISSUE 12) -----------
# The int8 keep mask is drawn OUTSIDE the kernel (same RNG-stream point
# and shape as the dense path's bernoulli draw) and streamed through the
# fwd/bwd kernels in [block, L] slabs — the fp32 probs still never
# materialize, and the 1-byte mask is the only O(L^2) residual. The mask
# is non-differentiable: its cotangent is float0.

@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_attn_dropout(rate, q, k, v, mask8):
    return _flash_forward(q, k, v, causal=True, dropout_mask=mask8,
                          dropout=rate)


def _fad_fwd(rate, q, k, v, mask8):
    o, lse = _flash_forward(q, k, v, causal=True, dropout_mask=mask8,
                            dropout=rate, with_lse=True)
    return o, (q, k, v, mask8, o, lse)


def _fad_bwd(rate, res, g):
    import numpy as _np
    q, k, v, mask8, o, lse = res
    dq, dk, dv = _flash_backward(q, k, v, o, lse, g, causal=True,
                                 dropout_mask=mask8, dropout=rate)
    return dq, dk, dv, _np.zeros(mask8.shape, jax.dtypes.float0)


_flash_attn_dropout.defvjp(_fad_fwd, _fad_bwd)


# -- general: optional [B, L_k] additive key bias, causal flag ----------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _flash_attn_biased(causal, num_heads, q, k, v, bias):
    return _flash_forward(q, k, v, bias=bias, num_heads=num_heads,
                          causal=causal)


def _fab_fwd(causal, num_heads, q, k, v, bias):
    o, lse = _flash_forward(q, k, v, bias=bias, num_heads=num_heads,
                            causal=causal, with_lse=True)
    return o, (q, k, v, bias, o, lse)


def _fab_bwd(causal, num_heads, res, g):
    q, k, v, bias, o, lse = res
    dq, dk, dv = _flash_backward(q, k, v, o, lse, g, bias=bias,
                                 num_heads=num_heads, causal=causal)
    return dq, dk, dv, jnp.zeros_like(bias)


_flash_attn_biased.defvjp(_fab_fwd, _fab_bwd)


# -- packed-layout entries (transpose-free MHA path) --------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _flash_attn_packed(causal, num_heads, head_dim, q, k, v, bias):
    return _flash_forward_packed(q, k, v, bias=bias, num_heads=num_heads,
                                 head_dim=head_dim, causal=causal)


def _fap_fwd(causal, num_heads, head_dim, q, k, v, bias):
    o, lse = _flash_forward_packed(q, k, v, bias=bias,
                                   num_heads=num_heads,
                                   head_dim=head_dim, causal=causal,
                                   with_lse=True)
    return o, (q, k, v, bias, o, lse)


def _fap_bwd(causal, num_heads, head_dim, res, g):
    q, k, v, bias, o, lse = res
    dq, dk, dv = _flash_backward_packed(q, k, v, o, lse, g, bias=bias,
                                        num_heads=num_heads,
                                        head_dim=head_dim, causal=causal)
    return dq, dk, dv, jnp.zeros_like(bias)


_flash_attn_packed.defvjp(_fap_fwd, _fap_bwd)


def flash_attention_packed(q, k, v, num_heads, head_dim, bias=None,
                           causal=False):
    """Array-level entry for the natural projection layout: q/k/v
    [B, L, H*D] → [B, L, H*D] — no physical [B, H, L, D] transpose ever
    materializes; one program per (batch, q-block) runs every head over
    static column slices. bias optional [B, L_k] additive key bias."""
    if bias is None:
        bias = jnp.zeros((q.shape[0], k.shape[1]), jnp.float32)
    return _flash_attn_packed(causal, num_heads, head_dim, q, k, v,
                              bias.astype(jnp.float32))


def mha_flash_attention_blhd(q, k, v, key_bias=None, causal=False):
    """Tensor-level entry for nn.MultiHeadAttention's transpose-free
    path: q/k/v [B, L, nh, hd] → [B, L, nh, hd] (reshaped through the
    packed [B, L, nh*hd] kernel — both reshapes are free)."""
    bias_arr = None
    if key_bias is not None:
        bias_arr = key_bias.data if isinstance(key_bias, Tensor) \
            else jnp.asarray(key_bias)
    scaffold.record_route('flash_attention', True)

    def fn(qa, ka, va):
        B, L, H, D = qa.shape
        o = flash_attention_packed(
            qa.reshape(B, L, H * D), ka.reshape(B, L, H * D),
            va.reshape(B, L, H * D), H, D, bias=bias_arr, causal=causal)
        return o.reshape(B, L, H, D)
    return run_op('flash_attention_blhd', fn, [q, k, v])


def flash_attention(q, k, v, bias=None, num_heads=1, causal=True):
    """Array-level entry: q/k/v [BH, L, D]; bias optional [B, L_k] additive
    key bias (BH = B * num_heads)."""
    if bias is None:
        if causal:
            return flash_attention_bhld(q, k, v)
        # express the no-mask non-causal case through the biased kernel with
        # a zero bias (one extra [B, L] row load per block — negligible)
        bias = jnp.zeros((q.shape[0] // num_heads, k.shape[1]), jnp.float32)
    return _flash_attn_biased(causal, num_heads, q, k, v,
                              bias.astype(jnp.float32))


def causal_attention(qkv, num_heads, head_dim, dropout=0.0,
                     dropout_key=None):
    """Tensor-level entry used by GPTAttention: qkv [B, L, nh*3*hd]
    ((head, 3, hd) Megatron packing — TP-shardable) → context
    [B, L, nh*hd]. Default route is the packed transpose-free kernel
    (q/k/v stay in [B, L, H*D]; only the cheap qkv un-interleave slice
    remains); FLAGS_flash_packed_causal=False restores the BHLD route.

    Nonzero `dropout` routes through the dropout-fused BHLD kernels
    (ISSUE 12): the int8 keep mask is drawn HERE with `dropout_key` —
    the same bernoulli draw (key, rate, [B, nh, L, L] shape) the dense
    path makes at this RNG-stream point, so same-seed outputs are
    directly comparable. A clear error remains only when no route
    exists: dropout without the key (the RNG point cannot be
    reproduced) or a rate outside [0, 1)."""
    from ...core import flags
    if dropout:
        if not (0.0 < dropout < 1.0):
            raise ValueError(
                f"attention dropout rate must be in [0, 1), got "
                f"{dropout}")
        if dropout_key is None:
            raise ValueError(
                "flash causal_attention with attention-prob dropout "
                "needs dropout_key (the dense path's RNG-stream draw "
                "point); without it no route can reproduce the mask")
        scaffold.record_route('flash_dropout', True)

        def fn_drop(a):
            B, L, _ = a.shape
            x = a.reshape(B, L, num_heads, 3, head_dim)
            q = x[:, :, :, 0].transpose(0, 2, 1, 3).reshape(
                B * num_heads, L, head_dim)
            k = x[:, :, :, 1].transpose(0, 2, 1, 3).reshape(
                B * num_heads, L, head_dim)
            v = x[:, :, :, 2].transpose(0, 2, 1, 3).reshape(
                B * num_heads, L, head_dim)
            keep = jax.random.bernoulli(dropout_key, 1.0 - dropout,
                                        (B, num_heads, L, L))
            mask8 = keep.reshape(B * num_heads, L, L).astype(jnp.int8)
            o = _flash_attn_dropout(float(dropout), q, k, v, mask8)
            o = o.reshape(B, num_heads, L, head_dim).transpose(0, 2, 1, 3)
            return o.reshape(B, L, num_heads * head_dim)
        return run_op('flash_attention', fn_drop, [qkv])
    scaffold.record_route('flash_attention', True)
    packed = bool(flags.flag('FLAGS_flash_packed_causal', True))

    def fn(a):
        B, L, _ = a.shape
        x = a.reshape(B, L, num_heads, 3, head_dim)
        if packed:
            q = x[:, :, :, 0].reshape(B, L, num_heads * head_dim)
            k = x[:, :, :, 1].reshape(B, L, num_heads * head_dim)
            v = x[:, :, :, 2].reshape(B, L, num_heads * head_dim)
            return _flash_attn_packed(True, num_heads, head_dim, q, k, v,
                                      jnp.zeros((B, L), jnp.float32))
        q = x[:, :, :, 0].transpose(0, 2, 1, 3).reshape(B * num_heads, L,
                                                        head_dim)
        k = x[:, :, :, 1].transpose(0, 2, 1, 3).reshape(B * num_heads, L,
                                                        head_dim)
        v = x[:, :, :, 2].transpose(0, 2, 1, 3).reshape(B * num_heads, L,
                                                        head_dim)
        o = flash_attention_bhld(q, k, v)
        o = o.reshape(B, num_heads, L, head_dim).transpose(0, 2, 1, 3)
        return o.reshape(B, L, num_heads * head_dim)
    return run_op('flash_attention', fn, [qkv])


def mha_flash_attention(q, k, v, key_bias=None, causal=False):
    """Tensor-level entry for nn.MultiHeadAttention: q/k/v [B, nh, L, hd];
    key_bias optional Tensor/array [B, L_k] additive. Returns [B, nh, L, hd].
    """
    nh = q.shape[1]
    bias_arr = None
    if key_bias is not None:
        bias_arr = key_bias.data if isinstance(key_bias, Tensor) \
            else jnp.asarray(key_bias)
    scaffold.record_route('flash_attention', True)

    def fn(qa, ka, va):
        B, H, L, D = qa.shape
        o = flash_attention(qa.reshape(B * H, L, D),
                            ka.reshape(B * H, ka.shape[2], D),
                            va.reshape(B * H, va.shape[2], D),
                            bias=bias_arr, num_heads=H, causal=causal)
        return o.reshape(B, H, L, D)
    return run_op('flash_attention', fn, [q, k, v])
