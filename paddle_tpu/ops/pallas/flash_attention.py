"""Flash attention (causal) — Pallas TPU kernel.

Reference parity: operators/fused/fused_attention_op +
fused_softmax_mask_upper_triangle (N27) — the attention fusion the reference
hand-writes in CUDA. TPU-native: a blockwise online-softmax kernel
(Flash-style) so the [L, L] score matrix never materializes in HBM; each
grid step streams K/V blocks through VMEM and keeps fp32 running max /
normalizer / accumulator in VMEM scratch. Q/K/V tiles are MXU-shaped
(block × head_dim with head_dim 64/128).

Backward: fully fused Pallas kernels (no [L, L] materialization): the
forward also emits per-row logsumexp; dq streams K/V blocks per q-block and
dk/dv stream Q/dO blocks per kv-block (the standard two-pass flash backward),
each O(L) memory. 8.6x faster than XLA's materializing backward at L=8192
and exact to fp32 noise (verified vs reference at HIGHEST precision).
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...core.tensor import Tensor
from ...core.autograd import run_op

NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k,
                      seq_len, scale, causal):
    """One (batch*head, q_block) program: stream K/V blocks, online softmax.

    q_ref: [block_q, d]; k_ref/v_ref: [seq_len, d]; o_ref: [block_q, d];
    lse_ref: [block_q, 1] per-row logsumexp (saved for the fused backward).
    """
    block_q = q_ref.shape[0]
    d = q_ref.shape[1]
    qi = pl.program_id(1)
    q_offset = qi * block_q

    q = q_ref[:].astype(jnp.float32) * scale

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        # only blocks overlapping [0, q_offset + block_q) matter
        num_k_blocks = pl.cdiv(q_offset + block_q, block_k)

    def body(ki, carry):
        m, l, acc = carry
        k_start = ki * block_k
        k = k_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 0) + q_offset
            cols = jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 1) + k_start
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k_blocks, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[:] = m + jnp.log(l_safe)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k, seq_len, scale, causal):
    """dq for one (bh, q_block): stream K/V blocks.
    ds = p * (dP - D); dq = scale * ds @ k."""
    block_q = q_ref.shape[0]
    qi = pl.program_id(1)
    q_offset = qi * block_q
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:]      # [block_q, 1]
    delta = delta_ref[:]  # [block_q, 1]

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        num_k_blocks = pl.cdiv(q_offset + block_q, block_k)

    def body(ki, dq):
        k_start = ki * block_k
        k = k_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 0) + q_offset
            cols = jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 1) + k_start
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_k_blocks, body,
                           jnp.zeros_like(q, jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q, seq_len, scale,
                          causal):
    """dk/dv for one (bh, kv_block): stream Q blocks.
    dv = p^T @ do; dk = scale * ds^T @ q."""
    block_k = k_ref.shape[0]
    ki = pl.program_id(1)
    k_start = ki * block_k
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)

    num_q_blocks = pl.cdiv(seq_len, block_q)
    first_q = (k_start // block_q) if causal else 0

    def body(qi, carry):
        dk, dv = carry
        q_offset = qi * block_q
        q = q_ref[pl.ds(q_offset, block_q), :].astype(jnp.float32)
        do = do_ref[pl.ds(q_offset, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(q_offset, block_q), :]
        delta = delta_ref[pl.ds(q_offset, block_q), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 0) + q_offset
            cols = jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 1) + k_start
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)  # [bq, bk]
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_new = dk + scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk0 = jnp.zeros_like(k, jnp.float32)
    dv0 = jnp.zeros_like(v, jnp.float32)
    dk, dv = jax.lax.fori_loop(first_q, num_q_blocks, body, (dk0, dv0))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _flash_forward(q, k, v, causal=True, block_q=256, block_k=256,
                   with_lse=False):
    """q/k/v: [BH, L, D] → [BH, L, D] (+ optional [BH, L] logsumexp)."""
    bh, L, d = q.shape
    block_q = min(block_q, L)
    block_k = min(block_k, L)
    scale = 1.0 / math.sqrt(d)
    grid = (bh, pl.cdiv(L, block_q))
    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               seq_len=L, scale=scale, causal=causal)
    o, lse = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((bh, L, d), q.dtype),
                   jax.ShapeDtypeStruct((bh, L, 1), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, L, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, L, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ),
    )(q, k, v)
    return (o, lse) if with_lse else o


def _flash_backward(q, k, v, o, lse, do, causal=True, block_q=256,
                    block_k=256):
    """Fused flash backward: no [L, L] materialization."""
    bh, L, d = q.shape
    block_q = min(block_q, L)
    block_k = min(block_k, L)
    scale = 1.0 / math.sqrt(d)
    # D_i = rowsum(dO * O) — tiny elementwise pass, leave it to XLA
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [bh, L, 1]

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k, seq_len=L,
                          scale=scale, causal=causal),
        out_shape=jax.ShapeDtypeStruct((bh, L, d), q.dtype),
        grid=(bh, pl.cdiv(L, block_q)),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, L, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, L, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q, seq_len=L,
                          scale=scale, causal=causal),
        out_shape=(jax.ShapeDtypeStruct((bh, L, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, L, d), v.dtype)),
        grid=(bh, pl.cdiv(L, block_k)),
        in_specs=[
            pl.BlockSpec((None, L, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((None, L, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, L, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, L, 1), lambda b, j: (b, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((None, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j: (b, j, 0)),
        ),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _reference_attention(q, k, v, causal=True):
    """jnp reference — the VJP path (recompute pairing)."""
    d = q.shape[-1]
    s = jnp.einsum('bqd,bkd->bqk', q, k,
                   preferred_element_type=jnp.float32) / math.sqrt(d)
    if causal:
        L = q.shape[1]
        mask = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bqk,bkd->bqd', p.astype(q.dtype), v)


@jax.custom_vjp
def flash_attention_bhld(q, k, v):
    return _flash_forward(q, k, v, causal=True)


def _fa_fwd(q, k, v):
    o, lse = _flash_forward(q, k, v, causal=True, with_lse=True)
    return o, (q, k, v, o, lse)


def _fa_bwd(res, g):
    q, k, v, o, lse = res
    return _flash_backward(q, k, v, o, lse, g, causal=True)


flash_attention_bhld.defvjp(_fa_fwd, _fa_bwd)


def causal_attention(qkv, num_heads, head_dim, dropout=0.0):
    """Tensor-level entry used by GPTAttention: qkv [B, L, nh*3*hd]
    ((head, 3, hd) Megatron packing — TP-shardable) → context
    [B, L, nh*hd]."""
    def fn(a):
        B, L, _ = a.shape
        x = a.reshape(B, L, num_heads, 3, head_dim)
        q = x[:, :, :, 0].transpose(0, 2, 1, 3).reshape(B * num_heads, L,
                                                        head_dim)
        k = x[:, :, :, 1].transpose(0, 2, 1, 3).reshape(B * num_heads, L,
                                                        head_dim)
        v = x[:, :, :, 2].transpose(0, 2, 1, 3).reshape(B * num_heads, L,
                                                        head_dim)
        o = flash_attention_bhld(q, k, v)
        o = o.reshape(B, num_heads, L, head_dim).transpose(0, 2, 1, 3)
        return o.reshape(B, L, num_heads * head_dim)
    return run_op('flash_attention', fn, [qkv])
