"""Fused LayerNorm (forward + backward) — Pallas kernels on the shared
scaffolding (TPP, arXiv:2104.05755).

Forward: one pass per row block computing mean/rsqrt(var+eps) in fp32
and the affine epilogue in the input dtype — exactly the op order of
the `ops.nn_ops.layer_norm` reference (normalize in fp32, cast to the
input dtype, THEN scale/shift in the weight dtype), so fp32 outputs
agree to float tolerance and the bf16 cast points match. mean and rstd
are emitted as [rows, 1] residuals for the backward.

Backward (`jax.custom_vjp`): a second one-pass kernel produces dx per
row block from the saved mean/rstd (no recompute of the reductions):

    dx = rstd * (dxhat - mean(dxhat) - xhat * mean(dxhat * xhat))

while dweight/dbias accumulate across the sequential row grid in VMEM
scratch ([1, N] each) and are written once by the last program — the
whole backward is one read of x/dy and one write of dx/dw/db, where the
XLA autodiff of the reference materializes xhat twice and runs three
separate reductions.

Shape contract: normalization over the LAST axis only, with both weight
and bias present (the GPT/BERT LayerNorm shape); `ops.nn_ops.layer_norm`
routes here for that case and keeps the jnp path otherwise. Rows that
don't divide the block size are zero-padded (pad rows see dy = 0, so
they contribute nothing to dw/db and their dx is sliced off).

Routing: `FLAGS_fused_layer_norm` (None = auto: TPU kernel, CPU
reference), recorded as primitive 'layer_norm'.
"""
import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import scaffold

PRIMITIVE = 'layer_norm'
FLAG = 'FLAGS_fused_layer_norm'
# row block: LN rows are [*, hidden] slabs, keep blocks modest so the
# dw/db scratch + x/dy/dx blocks fit VMEM at hidden ~8k
ROW_BLOCK = 128


def use_fused(supported=True):
    return scaffold.use_kernel(PRIMITIVE, FLAG, supported=supported)


def _fwd_kernel(x_ref, w_ref, b_ref, o_ref, mean_ref, rstd_ref, *, eps):
    xf = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mean) ** 2, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    out = ((xf - mean) * rstd).astype(x_ref.dtype)
    o_ref[...] = out * w_ref[...] + b_ref[...]
    mean_ref[...] = mean
    rstd_ref[...] = rstd


def _bwd_kernel(x_ref, w_ref, dy_ref, mean_ref, rstd_ref,
                dx_ref, dw_ref, db_ref, dw_s, db_s):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw_s[...] = jnp.zeros_like(dw_s)
        db_s[...] = jnp.zeros_like(db_s)
    xf = x_ref[...].astype(jnp.float32)
    mean = mean_ref[...]
    rstd = rstd_ref[...]
    xhat = (xf - mean) * rstd
    dyf = dy_ref[...].astype(jnp.float32)
    dxhat = dyf * w_ref[...].astype(jnp.float32)
    m1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (dxhat - m1 - xhat * m2)).astype(dx_ref.dtype)
    # the forward multiplies w by xhat CAST to the input dtype; route
    # dw through the same cast point so bf16 grads match the reference
    xhat_c = xhat.astype(x_ref.dtype).astype(jnp.float32)
    dw_s[...] += jnp.sum(dyf * xhat_c, axis=0, keepdims=True)
    db_s[...] += jnp.sum(dyf, axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        dw_ref[...] = dw_s[...]
        db_ref[...] = db_s[...]


def _fwd_pallas(x2, w, b, eps):
    R, N = x2.shape
    br = scaffold.pick_block_rows(N, ROW_BLOCK)
    xp = scaffold.pad_rows(x2, br)
    rows = xp.shape[0]
    o, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(rows // br,),
        in_specs=[scaffold.row_spec(br, N), scaffold.bcast_spec(1, N),
                  scaffold.bcast_spec(1, N)],
        out_specs=(scaffold.row_spec(br, N), scaffold.row_spec(br, 1),
                   scaffold.row_spec(br, 1)),
        out_shape=(jax.ShapeDtypeStruct((rows, N), x2.dtype),
                   jax.ShapeDtypeStruct((rows, 1), jnp.float32),
                   jax.ShapeDtypeStruct((rows, 1), jnp.float32)),
        interpret=scaffold.interpret_mode(),
    )(xp, w.reshape(1, N), b.reshape(1, N))
    return o[:R], mean, rstd


def _bwd_pallas(x2, w, dy2, mean, rstd):
    R, N = x2.shape
    # same block choice as the forward: mean/rstd were saved at the
    # forward's padded length
    br = scaffold.pick_block_rows(N, ROW_BLOCK)
    xp = scaffold.pad_rows(x2, br)
    dyp = scaffold.pad_rows(dy2, br)
    rows = xp.shape[0]
    dx, dw, db = pl.pallas_call(
        _bwd_kernel,
        grid=(rows // br,),
        in_specs=[scaffold.row_spec(br, N), scaffold.bcast_spec(1, N),
                  scaffold.row_spec(br, N), scaffold.row_spec(br, 1),
                  scaffold.row_spec(br, 1)],
        out_specs=(scaffold.row_spec(br, N), scaffold.bcast_spec(1, N),
                   scaffold.bcast_spec(1, N)),
        out_shape=(jax.ShapeDtypeStruct((rows, N), x2.dtype),
                   jax.ShapeDtypeStruct((1, N), jnp.float32),
                   jax.ShapeDtypeStruct((1, N), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((1, N), jnp.float32),
                        pltpu.VMEM((1, N), jnp.float32)],
        interpret=scaffold.interpret_mode(),
    )(xp, w.reshape(1, N), dyp, mean, rstd)
    return dx[:R], dw.reshape(N), db.reshape(N)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x, weight, bias, eps):
    """Array-level entry: x [..., N], weight/bias [N]; normalization
    over the last axis. Differentiable in x, weight, bias."""
    o, _, _ = _ln_fwd_impl(x, weight, bias, eps)
    return o


def _ln_fwd_impl(x, weight, bias, eps):
    shape = x.shape
    N = shape[-1]
    x2 = x.reshape(-1, N)
    o, mean, rstd = _fwd_pallas(x2, weight, bias, eps)
    return o.reshape(shape), mean, rstd


def _ln_fwd(x, weight, bias, eps):
    o, mean, rstd = _ln_fwd_impl(x, weight, bias, eps)
    return o, (x, weight, bias, mean, rstd)


def _ln_bwd(eps, res, g):
    x, weight, bias, mean, rstd = res
    shape = x.shape
    N = shape[-1]
    dx2, dw, db = _bwd_pallas(x.reshape(-1, N), weight,
                              g.reshape(-1, N), mean, rstd)
    return (dx2.reshape(shape), dw.astype(weight.dtype),
            db.astype(bias.dtype))


fused_layer_norm.defvjp(_ln_fwd, _ln_bwd)
