"""Pallas TPU kernels — custom kernels where XLA fusion isn't enough.

Reference parity: the role of operators/fused/ (fused_attention,
fused_softmax_mask, multihead_matmul — N27) — on TPU most fusions are XLA's
job; Pallas covers the blockwise-algorithm cases (flash attention's online
softmax) that XLA cannot derive.
"""
from . import flash_attention
from . import paged_attention
