"""Pallas TPU primitives library (TPP, arXiv:2104.05755).

Reference parity: the role of operators/fused/ (fused_attention,
fused_softmax_mask, multihead_matmul — N27) — on TPU most fusions are
XLA's job; Pallas covers the blockwise-algorithm cases XLA cannot derive
(flash attention's online softmax) and the bandwidth-bound chains worth
one-pass treatment (the flat-bucket optimizer step, LayerNorm fwd+bwd,
bias+GELU, dropout+residual).

Every primitive sits on the shared scaffolding in `scaffold.py`:
auto-route (Pallas on TPU, reference jnp on CPU, FLAGS_* force),
interpret-mode CI coverage, block/grid helpers, and routing counters
(`ptpu_pallas_{kernel,fallback}_invocations_total`). See
docs/performance.md#fused-primitives for how to add a kernel.
"""
from . import scaffold
from . import flash_attention
from . import paged_attention
from . import fused_optimizer
from . import fused_norm
from . import fused_elementwise
