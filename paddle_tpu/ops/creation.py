"""Tensor creation + random ops.

Reference parity: operators/ fill_constant, gaussian_random, uniform_random,
randint, randperm, bernoulli, multinomial, linspace, arange, eye, tril/triu
(SURVEY.md Appendix B); RNG semantics per core/rng.py (generator.h parity).
"""
import jax
import jax.numpy as jnp
import numpy as np

from .common import as_tensor, register
from ..core import dtypes, rng
from ..core.tensor import Tensor


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def _dt(dtype, default=jnp.float32):
    return dtypes.convert_dtype(dtype) if dtype is not None else default


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    x = as_tensor(x)
    return Tensor(jnp.zeros_like(x.data, dtype=_dt(dtype, x.dtype)))


def ones_like(x, dtype=None, name=None):
    x = as_tensor(x)
    return Tensor(jnp.ones_like(x.data, dtype=_dt(dtype, x.dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    x = as_tensor(x)
    return Tensor(jnp.full_like(x.data, fill_value, dtype=_dt(dtype, x.dtype)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = val(start), val(end), val(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = jnp.int64 if all(isinstance(v, int) for v in (start, end, step)) \
            else jnp.float32
    return Tensor(jnp.arange(start, end, step, dtype=_dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.linspace(val(start), val(stop), int(val(num)), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None):
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def assign(x, output=None):
    x = as_tensor(x)
    out = Tensor(x.data + 0 if dtypes.is_floating(x.dtype) else x.data)
    if output is not None:
        output.set_value(out)
        return output
    return out


def clone(x):
    return assign(x)


def tril_(*a, **k):
    from . import manip
    return manip.tril(*a, **k)


def diagflat(x, offset=0):
    x = as_tensor(x)
    return Tensor(jnp.diagflat(x.data, k=offset))


def complex(real, imag):
    real, imag = as_tensor(real), as_tensor(imag)
    return Tensor(jax.lax.complex(real.data, imag.data))


# ---- random ----------------------------------------------------------------
def uniform(shape, dtype='float32', min=-1.0, max=1.0, seed=0, name=None):
    """Parity: operators/uniform_random_op."""
    key = rng.next_key() if seed == 0 else jax.random.key(seed)
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype),
                                     minval=min, maxval=max))


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype or 'float32', min=0.0, max=1.0)


def randn(shape, dtype=None, name=None):
    key = rng.next_key()
    return Tensor(jax.random.normal(key, _shape(shape), _dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = as_tensor(mean).data if isinstance(mean, Tensor) else mean
        s = as_tensor(std).data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(
            m.shape if hasattr(m, 'shape') else (),
            s.shape if hasattr(s, 'shape') else ())
        key = rng.next_key()
        return Tensor(jax.random.normal(key, shp) * s + m)
    key = rng.next_key()
    return Tensor(jax.random.normal(key, _shape(shape)) * std + mean)


def gaussian(shape, mean=0.0, std=1.0, dtype='float32', name=None):
    key = rng.next_key()
    return Tensor(jax.random.normal(key, _shape(shape), _dt(dtype)) * std + mean)


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def randint(low=0, high=None, shape=(1,), dtype='int64', name=None):
    if high is None:
        low, high = 0, low
    key = rng.next_key()
    return Tensor(jax.random.randint(key, _shape(shape), low, high, dtype=_dt(dtype, jnp.int64)))


def randint_like(x, low=0, high=None, dtype=None):
    x = as_tensor(x)
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype='int64', name=None):
    key = rng.next_key()
    return Tensor(jax.random.permutation(key, n).astype(_dt(dtype, jnp.int64)))


def shuffle(x, axis=0):
    x = as_tensor(x)
    key = rng.next_key()
    return Tensor(jax.random.permutation(key, x.data, axis=axis))


def bernoulli(x, name=None):
    x = as_tensor(x)
    key = rng.next_key()
    return Tensor(jax.random.bernoulli(key, x.data).astype(x.dtype))


def poisson(x):
    x = as_tensor(x)
    key = rng.next_key()
    return Tensor(jax.random.poisson(key, x.data).astype(x.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = as_tensor(x)
    key = rng.next_key()
    probs = x.data / jnp.sum(x.data, axis=-1, keepdims=True)
    if x.ndim == 1:
        out = jax.random.choice(key, x.shape[0], (num_samples,),
                                replace=replacement, p=probs)
    else:
        keys = jax.random.split(key, x.data.shape[0])
        out = jnp.stack([
            jax.random.choice(k, x.shape[-1], (num_samples,), replace=replacement, p=p)
            for k, p in zip(keys, probs)])
    return Tensor(out.astype(jnp.int64))


def truncated_gaussian_random(shape, mean=0.0, std=1.0, dtype='float32'):
    key = rng.next_key()
    out = jax.random.truncated_normal(key, -2.0, 2.0, _shape(shape), _dt(dtype))
    return Tensor(out * std + mean)
