"""Sequence + CRF + beam-search ops (reference op tier 3).

Reference parity: operators/sequence_ops/ (sequence_pad/unpad/expand/
reverse over LoD tensors), linear_chain_crf_op.cc / crf_decoding_op.cc,
and beam_search_op.cc / beam_search_decode_op.cc.

TPU-native design: LoD is dropped (SURVEY N11 disposition) — sequences are
dense padded tensors + a lengths vector, and every recurrence is a
`lax.scan` with length masking (static shapes, compiler-friendly), not a
per-sequence C++ loop. The CRF forward/viterbi recursions and the beam
loop each compile to ONE fused XLA while/scan.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ..core.autograd import run_op
from .common import as_tensor, register


# ---- padded-sequence utilities ---------------------------------------------
def sequence_pad(x, lengths, maxlen=None, pad_value=0.0):
    """[sum_len, ...] packed rows + lengths -> ([B, maxlen, ...], lengths).
    Parity: sequence_pad_op (LoD -> padded)."""
    x = as_tensor(x)
    lengths = as_tensor(lengths)
    lens = np.asarray(lengths.data).reshape(-1).astype(np.int64)
    ml = int(maxlen) if maxlen is not None else int(lens.max())
    offsets = np.concatenate([[0], np.cumsum(lens)[:-1]])

    def fn(arr):
        rows = []
        for off, ln in zip(offsets, lens):
            seq = arr[off:off + ln]
            pad = jnp.full((ml - int(ln),) + arr.shape[1:], pad_value,
                           arr.dtype)
            rows.append(jnp.concatenate([seq, pad], 0))
        return jnp.stack(rows, 0)
    out = run_op('sequence_pad', fn, [x])
    return out, lengths


def sequence_unpad(x, lengths):
    """[B, maxlen, ...] -> [sum_len, ...] packed rows. Parity:
    sequence_unpad_op."""
    x = as_tensor(x)
    lens = np.asarray(as_tensor(lengths).data).reshape(-1).astype(np.int64)

    def fn(arr):
        return jnp.concatenate(
            [arr[b, :int(l)] for b, l in enumerate(lens)], 0)
    return run_op('sequence_unpad', fn, [x])


def sequence_expand(x, repeat_times):
    """Repeat each row i repeat_times[i] times. Parity: sequence_expand's
    row-broadcast role over the ragged batch."""
    x = as_tensor(x)
    reps = np.asarray(as_tensor(repeat_times).data).reshape(-1)

    def fn(arr):
        return jnp.repeat(arr, jnp.asarray(reps), axis=0,
                          total_repeat_length=int(reps.sum()))
    return run_op('sequence_expand', fn, [x])


def sequence_reverse(x, lengths=None):
    """Reverse the time axis, respecting per-row lengths. Parity:
    sequence_reverse_op."""
    x = as_tensor(x)
    if lengths is None:
        return run_op('sequence_reverse', lambda a: jnp.flip(a, 1), [x])
    lengths = as_tensor(lengths)

    def fn(arr, lens):
        T = arr.shape[1]
        idx = jnp.arange(T)[None, :]
        ln = lens.reshape(-1, 1).astype(jnp.int32)
        src = jnp.where(idx < ln, ln - 1 - idx, idx)
        return jnp.take_along_axis(
            arr, src.reshape(src.shape + (1,) * (arr.ndim - 2)), axis=1)
    return run_op('sequence_reverse', fn, [x, lengths], n_nondiff=1)


# ---- linear-chain CRF -------------------------------------------------------
def linear_chain_crf(input, transition, label, length):
    """Negative log-likelihood of a linear-chain CRF (parity:
    linear_chain_crf_op.cc).

    input: [B, T, N] emissions; transition: [N+2, N] with row 0 = start,
    row 1 = stop, rows 2: = square transitions (the reference layout);
    label: int [B, T]; length: int [B]. Returns [B, 1] NLL.
    """
    input = as_tensor(input)
    transition = as_tensor(transition)
    label = as_tensor(label)
    length = as_tensor(length)

    def fn(emit, trans, lab, lens):
        start, stop, sq = trans[0], trans[1], trans[2:]
        B, T, N = emit.shape
        lens = lens.reshape(-1).astype(jnp.int32)
        lab = lab.astype(jnp.int32)

        alpha0 = start[None, :] + emit[:, 0]             # [B, N]

        def fwd(alpha, t):
            nxt = jax.scipy.special.logsumexp(
                alpha[:, :, None] + sq[None], axis=1) + emit[:, t]
            alpha = jnp.where((t < lens)[:, None], nxt, alpha)
            return alpha, None
        alpha, _ = lax.scan(fwd, alpha0, jnp.arange(1, T))
        logz = jax.scipy.special.logsumexp(alpha + stop[None], axis=1)

        # gold path score
        b_idx = jnp.arange(B)
        gold0 = start[lab[:, 0]] + emit[b_idx, 0, lab[:, 0]]

        def gscan(g, t):
            step = sq[lab[:, t - 1], lab[:, t]] + emit[b_idx, t, lab[:, t]]
            return g + jnp.where(t < lens, step, 0.0), None
        gold, _ = lax.scan(gscan, gold0, jnp.arange(1, T))
        last = jnp.clip(lens - 1, 0, T - 1)
        gold = gold + stop[lab[b_idx, last]]
        return (logz - gold).reshape(B, 1)
    return run_op('linear_chain_crf', fn, [input, transition, label,
                                           length], n_nondiff=2)


def crf_decoding(input, transition, length):
    """Viterbi decode (parity: crf_decoding_op.cc). Returns int path
    [B, T] (entries past each row's length are 0)."""
    input = as_tensor(input)
    transition = as_tensor(transition)
    length = as_tensor(length)

    def fn(emit, trans, lens):
        start, stop, sq = trans[0], trans[1], trans[2:]
        B, T, N = emit.shape
        lens = lens.reshape(-1).astype(jnp.int32)
        alpha0 = start[None, :] + emit[:, 0]

        def fwd(alpha, t):
            scores = alpha[:, :, None] + sq[None]         # [B, N, N]
            bp = jnp.argmax(scores, axis=1)               # [B, N]
            nxt = jnp.max(scores, axis=1) + emit[:, t]
            keep = (t < lens)[:, None]
            return jnp.where(keep, nxt, alpha), \
                jnp.where(keep, bp, jnp.arange(N)[None, :])
        alpha, bps = lax.scan(fwd, alpha0, jnp.arange(1, T))  # bps [T-1,B,N]

        last_tag = jnp.argmax(alpha + stop[None], axis=1)     # [B]
        b_idx = jnp.arange(B)

        def back(tag, bp):
            prev = bp[b_idx, tag]
            return prev, prev          # emit the PREDECESSOR tag at t
        _, path_rev = lax.scan(back, last_tag, bps, reverse=True)
        path = jnp.concatenate(
            [path_rev, last_tag[None]], 0).T                  # [B, T]
        # entries at/after each row's length zero out (padded region)
        # and rows shorter than T keep the path aligned from t=0
        tpos = jnp.arange(T)[None, :]
        return jnp.where(tpos < lens[:, None], path, 0).astype(jnp.int64)
    return run_op('crf_decoding', fn, [input, transition, length],
                  n_nondiff=1)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Parity: paddle.text.viterbi_decode — returns (scores, paths).
    transition_params: [N, N]; with include_bos_eos_tag the last two tags
    act as bos/eos like the reference."""
    potentials = as_tensor(potentials)
    transition_params = as_tensor(transition_params)
    lengths = as_tensor(lengths)

    def fn(emit, trans, lens):
        B, T, N = emit.shape
        lens = lens.reshape(-1).astype(jnp.int32)
        if include_bos_eos_tag:
            start = trans[N - 2]         # bos -> tag
            stop = trans[:, N - 1]       # tag -> eos
        else:
            start = jnp.zeros((N,), emit.dtype)
            stop = jnp.zeros((N,), emit.dtype)
        alpha0 = start[None, :] + emit[:, 0]

        def fwd(alpha, t):
            scores = alpha[:, :, None] + trans[None]
            bp = jnp.argmax(scores, axis=1)
            nxt = jnp.max(scores, axis=1) + emit[:, t]
            keep = (t < lens)[:, None]
            return jnp.where(keep, nxt, alpha), \
                jnp.where(keep, bp, jnp.arange(N)[None, :])
        alpha, bps = lax.scan(fwd, alpha0, jnp.arange(1, T))
        final = alpha + stop[None]
        last_tag = jnp.argmax(final, axis=1)
        score = jnp.max(final, axis=1)
        b_idx = jnp.arange(B)

        def back(tag, bp):
            prev = bp[b_idx, tag]
            return prev, prev          # emit the PREDECESSOR tag at t
        _, path_rev = lax.scan(back, last_tag, bps, reverse=True)
        path = jnp.concatenate([path_rev, last_tag[None]], 0).T
        tpos = jnp.arange(T)[None, :]
        path = jnp.where(tpos < lens[:, None], path, 0).astype(jnp.int64)
        return score, path
    score, path = run_op('viterbi_decode', fn,
                         [potentials, transition_params, lengths],
                         n_nondiff=1)
    return score, path


class ViterbiDecoder:
    """Parity: paddle.text.ViterbiDecoder."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = as_tensor(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


# ---- beam search ------------------------------------------------------------
def beam_search(step_fn, init_state, bos_id, eos_id, beam_size, max_len,
                batch_size=1, length_penalty=0.0):
    """Batched beam-search decode (parity: the beam_search +
    beam_search_decode op pair driving RNN/Transformer decoding).

    step_fn(ids [B*K], state) -> (log_probs [B*K, V], new_state): one
    decoder step. State leaves must carry the beam dim at axis 0
    (size B*K). The whole loop is one `lax.scan` — beams advance with
    `lax.top_k` over the joint (beam, vocab) scores; finished beams
    (emitted eos) freeze their score and pad with eos.

    Returns (sequences [B, K, max_len] int64, scores [B, K]), best first.
    """
    B, K = batch_size, beam_size
    neg_inf = -1e9

    def gather_beams(tree, idx):
        # idx [B, K] of source beam within each batch row
        flat = idx + jnp.arange(B)[:, None] * K

        def one(x):
            return x.reshape((B * K,) + x.shape[1:])[flat.reshape(-1)]
        return jax.tree_util.tree_map(one, tree)

    ids0 = jnp.full((B * K,), bos_id, jnp.int32)
    # only beam 0 live initially so the first expansion is unbiased
    scores0 = jnp.tile(jnp.array([0.0] + [neg_inf] * (K - 1),
                                 jnp.float32), (B,)).reshape(B, K)
    fin0 = jnp.zeros((B, K), bool)

    def step(carry, t):
        ids, state, scores, finished, seqs = carry
        logp, new_state = step_fn(ids, state)
        logp = _raw(logp)
        V = logp.shape[-1]
        logp = logp.reshape(B, K, V)
        # finished beams only extend with eos at no cost
        eos_only = jnp.full((V,), neg_inf).at[eos_id].set(0.0)
        logp = jnp.where(finished[:, :, None], eos_only[None, None], logp)
        joint = scores[:, :, None] + logp                  # [B, K, V]
        top_val, top_idx = lax.top_k(joint.reshape(B, K * V), K)
        beam_src = top_idx // V                            # [B, K]
        tok = (top_idx % V).astype(jnp.int32)
        new_state = gather_beams(new_state, beam_src)
        seqs = gather_beams(seqs, beam_src)
        finished = jnp.take_along_axis(finished, beam_src, 1)
        seqs = seqs.at[:, t].set(tok.reshape(B * K))
        finished = finished | (tok == eos_id)
        return (tok.reshape(B * K), new_state, top_val, finished,
                seqs), None

    seqs0 = jnp.zeros((B * K, max_len), jnp.int32)
    (ids, state, scores, finished, seqs), _ = lax.scan(
        step, (ids0, init_state, scores0, fin0, seqs0),
        jnp.arange(max_len))
    seqs = seqs.reshape(B, K, max_len)
    if length_penalty:
        lens = jnp.argmax(seqs == eos_id, axis=-1)
        lens = jnp.where(lens == 0, max_len, lens)
        scores = scores / (lens.astype(jnp.float32) ** length_penalty)
    order = jnp.argsort(-scores, axis=1)
    seqs = jnp.take_along_axis(seqs, order[:, :, None], 1)
    scores = jnp.take_along_axis(scores, order, 1)
    return Tensor(seqs.astype(jnp.int64)), Tensor(scores)


def _raw(x):
    return x.data if isinstance(x, Tensor) else x


for _name, _fn in [('sequence_pad', sequence_pad),
                   ('sequence_unpad', sequence_unpad),
                   ('sequence_expand', sequence_expand),
                   ('sequence_reverse', sequence_reverse),
                   ('linear_chain_crf', linear_chain_crf),
                   ('crf_decoding', crf_decoding),
                   ('viterbi_decode', viterbi_decode)]:
    register(_name, _fn)


# ---- dense-form sequence_* remainder (fluid/layers/sequence_lod.py) --------
def _mask_of(x, lengths):
    L = x.data.shape[1]
    return (jnp.arange(L)[None, :]
            < as_tensor(lengths).data.reshape(-1, 1)).astype(x.data.dtype)


def sequence_pool(input, pool_type='sum', lengths=None, pad_value=0.0):
    """sequence_pool_op over padded [B, L, ...] + lengths: sum/average/
    sqrt/max/min/first/last over each sequence's valid steps."""
    x = as_tensor(input)
    if lengths is None:
        lens = jnp.full((x.data.shape[0],), x.data.shape[1], jnp.int32)
    else:
        lens = as_tensor(lengths).data.reshape(-1)
    L = x.data.shape[1]
    m = (jnp.arange(L)[None, :] < lens[:, None])
    me = m.reshape(m.shape + (1,) * (x.data.ndim - 2))
    pt = pool_type.lower()
    empty = (lens <= 0).reshape(-1, *([1] * (x.data.ndim - 2)))
    if pt in ('sum', 'average', 'sqrt'):
        s = jnp.where(me, x.data, 0).sum(axis=1)
        if pt == 'average':
            s = s / jnp.maximum(lens, 1).reshape(-1, *([1] * (s.ndim - 1)))
        elif pt == 'sqrt':
            s = s / jnp.sqrt(jnp.maximum(lens, 1)).reshape(
                -1, *([1] * (s.ndim - 1)))
        return Tensor(jnp.where(empty, pad_value, s))
    if pt == 'max':
        s = jnp.where(me, x.data, -jnp.inf).max(axis=1)
        return Tensor(jnp.where(empty, pad_value, s))  # no -inf leak
    if pt == 'min':
        s = jnp.where(me, x.data, jnp.inf).min(axis=1)
        return Tensor(jnp.where(empty, pad_value, s))
    if pt == 'first':
        return Tensor(x.data[:, 0])
    if pt == 'last':
        idx = jnp.maximum(lens - 1, 0)
        return Tensor(jnp.take_along_axis(
            x.data, idx.reshape(-1, 1, *([1] * (x.data.ndim - 2))),
            axis=1).squeeze(1))
    raise ValueError(f"unknown pool_type {pool_type!r}")


def sequence_first_step(input, lengths=None):
    return sequence_pool(input, 'first', lengths)


def sequence_last_step(input, lengths=None):
    return sequence_pool(input, 'last', lengths)


def sequence_softmax(input, lengths=None):
    """softmax over each sequence's valid steps (padding gets 0)."""
    x = as_tensor(input)
    if lengths is None:
        return Tensor(jax.nn.softmax(x.data, axis=1))
    m = _mask_of(x, lengths)
    z = jnp.where(m > 0, x.data, -jnp.inf)
    out = jax.nn.softmax(z, axis=1)
    return Tensor(jnp.where(m > 0, out, 0.0))


def sequence_concat(inputs, lengths_list=None):
    """Concatenate along the time axis; with lengths, each output row is
    the packed concat of the inputs' valid prefixes (re-padded)."""
    xs = [as_tensor(t) for t in inputs]
    if lengths_list is None:
        return Tensor(jnp.concatenate([t.data for t in xs], axis=1))
    lens = [np.asarray(as_tensor(l).data).reshape(-1)
            for l in lengths_list]
    B = xs[0].data.shape[0]
    total = [int(sum(l[b] for l in lens)) for b in range(B)]
    ml = max(total) if total else 0
    rows = []
    for b in range(B):
        parts = [np.asarray(t.data[b, :int(l[b])])
                 for t, l in zip(xs, lens)]
        row = np.concatenate(parts, axis=0)
        pad = np.zeros((ml - row.shape[0],) + row.shape[1:],
                       row.dtype)
        rows.append(np.concatenate([row, pad], axis=0))
    return Tensor(jnp.asarray(np.stack(rows))), Tensor(
        jnp.asarray(np.array(total, np.int64)))


def sequence_expand_as(x, y_lengths):
    """Repeat row b of x[B, ...] lengths[b] times (packed output) —
    sequence_expand_as_op."""
    xa = as_tensor(x)
    lens = np.asarray(as_tensor(y_lengths).data).reshape(-1)
    idx = np.repeat(np.arange(len(lens)), lens.astype(np.int64))
    return Tensor(jnp.take(xa.data, jnp.asarray(idx), axis=0))


def sequence_enumerate(input, win_size, pad_value=0, lengths=None):
    """All win_size-grams per step (padded past the end) —
    sequence_enumerate_op on a padded [B, L] batch."""
    x = as_tensor(input)
    B, L = x.data.shape[:2]
    cols = []
    for off in range(win_size):
        sh = jnp.concatenate(
            [x.data[:, off:],
             jnp.full((B, off), pad_value, x.data.dtype)], axis=1)
        cols.append(sh)
    out = jnp.stack(cols, axis=-1)
    if lengths is not None:
        lens = as_tensor(lengths).data.reshape(-1, 1, 1)
        pos = jnp.arange(L).reshape(1, -1, 1) + jnp.arange(win_size)
        out = jnp.where(pos < lens, out, pad_value)
    return Tensor(out)


def sequence_reshape(input, new_dim):
    """[B, L, D] -> [B, L*D/new_dim, new_dim] (sequence_reshape_op)."""
    x = as_tensor(input)
    B = x.data.shape[0]
    return Tensor(x.data.reshape(B, -1, new_dim))


def sequence_slice(input, offset, length):
    """Per-sequence slice [offset[b] : offset[b]+length[b]] re-padded to
    max(length) (sequence_slice_op)."""
    x = as_tensor(input)
    offs = np.asarray(as_tensor(offset).data).reshape(-1)
    lens = np.asarray(as_tensor(length).data).reshape(-1)
    ml = int(lens.max()) if lens.size else 0
    rows = []
    for b in range(x.data.shape[0]):
        seg = np.asarray(
            x.data[b, int(offs[b]):int(offs[b]) + int(lens[b])])
        pad = np.zeros((ml - seg.shape[0],) + seg.shape[1:], seg.dtype)
        rows.append(np.concatenate([seg, pad], axis=0))
    return Tensor(jnp.asarray(np.stack(rows)))


def sequence_scatter(input, index, updates):
    """out[b, index[b, i]] += updates[b, i] (sequence_scatter_op)."""
    x = as_tensor(input)
    idx = as_tensor(index).data.astype(jnp.int32)
    upd = as_tensor(updates).data
    return Tensor(x.data.at[
        jnp.arange(x.data.shape[0])[:, None], idx].add(upd))


def sequence_conv(input, filter_w, context_length=3, context_start=None,
                  lengths=None, bias=None):
    """sequence_conv_op: each step's output = flattened context window
    (zero past sequence bounds) @ filter [ctx*D, O]."""
    x = as_tensor(input)
    w = as_tensor(filter_w)
    B, L, D = x.data.shape
    start = -((context_length - 1) // 2) if context_start is None \
        else context_start
    cols = []
    for c in range(context_length):
        off = start + c
        if off < 0:
            sh = jnp.concatenate(
                [jnp.zeros((B, -off, D), x.data.dtype),
                 x.data[:, :L + off]], axis=1)
        elif off > 0:
            sh = jnp.concatenate(
                [x.data[:, off:],
                 jnp.zeros((B, off, D), x.data.dtype)], axis=1)
        else:
            sh = x.data
        cols.append(sh)
    ctx = jnp.concatenate(cols, axis=-1)          # [B, L, ctx*D]
    if lengths is not None:
        m = _mask_of(as_tensor(ctx), lengths)
        ctx = ctx * m[..., None] if m.ndim < ctx.ndim else ctx * m
    out = jnp.einsum('bld,do->blo', ctx, w.data)
    if bias is not None:
        out = out + as_tensor(bias).data
    return Tensor(out)


# beam-search backtrace + edit distance live with the sequence tier
# (fluid/layers names resolve via static.nn._reexport)
from .contrib import gather_tree, edit_distance  # noqa: E402
