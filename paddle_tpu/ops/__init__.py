"""paddle_tpu.ops — the op library (XLA-traceable, autograd-taped).

Layout mirrors the reference's operator categories (SURVEY.md §1-L4):
math.py (elementwise/reduce/compare), manip.py (shape/layout/index),
creation.py (fill/random), nn_ops.py (activations/norm/conv/loss),
linalg.py. The OP_REGISTRY in common.py is the lookup the static executor
uses (parity: framework/op_registry.h).
"""
from . import common, math, manip, creation, nn_ops, linalg, sequence
from . import recsys
from .common import OP_REGISTRY
