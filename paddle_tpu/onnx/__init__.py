"""paddle.onnx — ONNX export sheet. The onnx package is not part of
this environment (zero egress) and the deployment path here is
StableHLO AOT (static/inference.py), so export() converts when onnx is
importable and otherwise raises with the supported alternative."""


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """paddle.onnx.export (reference: paddle.onnx.export → paddle2onnx).
    """
    try:
        import onnx  # noqa
    except ImportError:
        raise NotImplementedError(
            "paddle.onnx.export needs the `onnx` package, which is not "
            "installed in this environment — export a deployable "
            "artifact with paddle.jit.save / "
            "static.save_inference_model (StableHLO AOT, loadable by "
            "paddle.inference.Predictor) instead")
    raise NotImplementedError(
        "onnx is importable but the paddle2onnx converter is not "
        "bundled; use the StableHLO AOT path")
