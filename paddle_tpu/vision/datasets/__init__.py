"""Vision datasets (parity: python/paddle/vision/datasets — MNIST, CIFAR,
etc.). Zero-egress environment: datasets load from local cache when present;
`FakeData`-style synthetic fallbacks keep the training paths exercisable."""
import gzip
import os
import struct

import numpy as np

from ...io import Dataset
from ...utils.download import DATA_HOME


class MNIST(Dataset):
    """Parity: paddle.vision.datasets.MNIST. Falls back to a deterministic
    synthetic digit set when the real files are absent (zero egress)."""

    def __init__(self, image_path=None, label_path=None, mode='train',
                 transform=None, download=True, backend='cv2'):
        self.mode = mode
        self.transform = transform
        images_file = image_path or os.path.join(
            DATA_HOME, 'mnist',
            f"{'train' if mode == 'train' else 't10k'}-images-idx3-ubyte.gz")
        labels_file = label_path or os.path.join(
            DATA_HOME, 'mnist',
            f"{'train' if mode == 'train' else 't10k'}-labels-idx1-ubyte.gz")
        if os.path.exists(images_file) and os.path.exists(labels_file):
            with gzip.open(images_file, 'rb') as f:
                magic, num, rows, cols = struct.unpack('>IIII', f.read(16))
                self.images = np.frombuffer(f.read(), np.uint8).reshape(
                    num, rows, cols)
            with gzip.open(labels_file, 'rb') as f:
                struct.unpack('>II', f.read(8))
                self.labels = np.frombuffer(f.read(), np.uint8)
        else:
            n = 2048 if mode == 'train' else 512
            rng = np.random.RandomState(42 if mode == 'train' else 7)
            self.labels = rng.randint(0, 10, n).astype(np.uint8)
            # class prototypes fixed across splits so train/test share a
            # distribution; per-split rng only adds noise
            base = np.random.RandomState(123).rand(10, 28, 28)
            self.images = np.clip(
                (base[self.labels] * 255 +
                 rng.randn(n, 28, 28) * 16), 0, 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None, :, :] / 255.0
        label = np.asarray([self.labels[idx]], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode='train', transform=None,
                 download=True, backend='cv2'):
        self.transform = transform
        n = 1024 if mode == 'train' else 256
        rng = np.random.RandomState(0 if mode == 'train' else 1)
        self.labels = rng.randint(0, 10, n).astype(np.int64)
        self.images = rng.randint(0, 255, (n, 3, 32, 32)).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], dtype=np.int64)

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    pass


class Flowers(Dataset):
    """Parity: paddle.vision.datasets.Flowers (102 classes); synthetic
    fallback under zero egress."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode='train', transform=None, download=True,
                 backend='cv2'):
        self.transform = transform
        n = 512 if mode == 'train' else 128
        rng = np.random.RandomState(3 if mode == 'train' else 4)
        self.labels = rng.randint(0, 102, n).astype(np.int64)
        self.images = rng.randint(0, 255, (n, 3, 64, 64)).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], dtype=np.int64)

    def __len__(self):
        return len(self.labels)


class VOC2012(Dataset):
    """Parity: paddle.vision.datasets.VOC2012 (segmentation); synthetic
    image/mask pairs under zero egress."""

    def __init__(self, data_file=None, mode='train', transform=None,
                 download=True, backend='cv2'):
        self.transform = transform
        n = 128 if mode == 'train' else 32
        rng = np.random.RandomState(5 if mode == 'train' else 6)
        self.images = rng.randint(0, 255, (n, 3, 64, 64)).astype(np.uint8)
        self.masks = rng.randint(0, 21, (n, 64, 64)).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, self.masks[idx]

    def __len__(self):
        return len(self.images)


def _default_image_loader(path):
    try:
        from PIL import Image
    except ImportError as e:
        raise ImportError(
            "ImageFolder needs PIL to decode images; pass a custom "
            "loader= (e.g. numpy .npy reader) in this environment"
        ) from e
    with Image.open(path) as im:
        return np.asarray(im.convert('RGB'))


IMG_EXTENSIONS = ('.jpg', '.jpeg', '.png', '.ppm', '.bmp', '.npy')


def _scan_files(root, extensions, is_valid_file):
    """Recursive sorted file discovery. `is_valid_file` receives the
    FULL path (paddle/torchvision DatasetFolder contract)."""
    out = []
    for dirpath, _, files in sorted(os.walk(root)):
        for fname in sorted(files):
            path = os.path.join(dirpath, fname)
            ok = (is_valid_file(path) if is_valid_file
                  else fname.lower().endswith(extensions))
            if ok:
                out.append(path)
    return out


def _load_sample(path, loader):
    """A user loader always wins; the default path decodes .npy (any
    case) with numpy and everything else with PIL."""
    if loader is not None:
        return loader(path)
    if path.lower().endswith('.npy'):
        return np.load(path)
    return _default_image_loader(path)


class DatasetFolder(Dataset):
    """Parity: paddle.vision.datasets.DatasetFolder — one class per
    subdirectory, samples discovered recursively."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader
        extensions = tuple(e.lower() for e in
                           (extensions or IMG_EXTENSIONS))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise ValueError(f"no class subdirectories under {root!r}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            for path in _scan_files(os.path.join(root, c), extensions,
                                    is_valid_file):
                self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise ValueError(f"no samples with extensions {extensions} "
                             f"under {root!r}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = _load_sample(path, self.loader)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([target], dtype=np.int64)

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    """Parity: paddle.vision.datasets.ImageFolder — like DatasetFolder
    but unlabeled (flat or nested files, returns images only)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader
        extensions = tuple(e.lower() for e in
                           (extensions or IMG_EXTENSIONS))
        self.samples = _scan_files(root, extensions, is_valid_file)
        if not self.samples:
            raise ValueError(f"no images under {root!r}")

    def __getitem__(self, idx):
        img = _load_sample(self.samples[idx], self.loader)
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)
