"""Vision datasets (parity: python/paddle/vision/datasets — MNIST, CIFAR,
etc.). Zero-egress environment: datasets load from local cache when present;
`FakeData`-style synthetic fallbacks keep the training paths exercisable."""
import gzip
import os
import struct

import numpy as np

from ...io import Dataset
from ...utils.download import DATA_HOME


class MNIST(Dataset):
    """Parity: paddle.vision.datasets.MNIST. Falls back to a deterministic
    synthetic digit set when the real files are absent (zero egress)."""

    def __init__(self, image_path=None, label_path=None, mode='train',
                 transform=None, download=True, backend='cv2'):
        self.mode = mode
        self.transform = transform
        images_file = image_path or os.path.join(
            DATA_HOME, 'mnist',
            f"{'train' if mode == 'train' else 't10k'}-images-idx3-ubyte.gz")
        labels_file = label_path or os.path.join(
            DATA_HOME, 'mnist',
            f"{'train' if mode == 'train' else 't10k'}-labels-idx1-ubyte.gz")
        if os.path.exists(images_file) and os.path.exists(labels_file):
            with gzip.open(images_file, 'rb') as f:
                magic, num, rows, cols = struct.unpack('>IIII', f.read(16))
                self.images = np.frombuffer(f.read(), np.uint8).reshape(
                    num, rows, cols)
            with gzip.open(labels_file, 'rb') as f:
                struct.unpack('>II', f.read(8))
                self.labels = np.frombuffer(f.read(), np.uint8)
        else:
            n = 2048 if mode == 'train' else 512
            rng = np.random.RandomState(42 if mode == 'train' else 7)
            self.labels = rng.randint(0, 10, n).astype(np.uint8)
            # class prototypes fixed across splits so train/test share a
            # distribution; per-split rng only adds noise
            base = np.random.RandomState(123).rand(10, 28, 28)
            self.images = np.clip(
                (base[self.labels] * 255 +
                 rng.randn(n, 28, 28) * 16), 0, 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None, :, :] / 255.0
        label = np.asarray([self.labels[idx]], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode='train', transform=None,
                 download=True, backend='cv2'):
        self.transform = transform
        n = 1024 if mode == 'train' else 256
        rng = np.random.RandomState(0 if mode == 'train' else 1)
        self.labels = rng.randint(0, 10, n).astype(np.int64)
        self.images = rng.randint(0, 255, (n, 3, 32, 32)).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], dtype=np.int64)

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    pass
