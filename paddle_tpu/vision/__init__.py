"""paddle_tpu.vision (parity: python/paddle/vision)."""
from . import models
from . import transforms
from . import datasets
from . import ops
