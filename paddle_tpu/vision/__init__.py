"""paddle_tpu.vision (parity: python/paddle/vision)."""
from . import models
from . import transforms
from . import datasets
from . import ops


_image_backend = 'pil'


def set_image_backend(backend):
    """paddle.vision.set_image_backend ('pil' | 'cv2'; only PIL ships
    in this environment)."""
    global _image_backend
    if backend not in ('pil', 'cv2'):
        raise ValueError(f"unknown image backend {backend!r}")
    if backend == 'cv2':
        try:
            import cv2  # noqa
        except ImportError:
            raise ValueError("cv2 backend requested but OpenCV is not "
                             "installed; 'pil' is available")
    _image_backend = backend


def get_image_backend():
    """paddle.vision.get_image_backend."""
    return _image_backend


def image_load(path, backend=None):
    """paddle.vision.image_load — PIL.Image (or cv2 ndarray)."""
    b = backend or _image_backend
    if b == 'cv2':
        import cv2
        return cv2.imread(path)
    from PIL import Image
    return Image.open(path)
