"""Image transforms over numpy HWC arrays / Tensors.

Reference parity: python/paddle/vision/transforms/transforms.py (functional
subset on numpy backend — PIL is optional in this environment).
"""
import numbers

import numpy as np

from ...core.tensor import Tensor


def _to_np(img):
    if isinstance(img, Tensor):
        return np.asarray(img.data)
    return np.asarray(img)


def resize(img, size, interpolation='bilinear'):
    img = _to_np(img)
    if isinstance(size, int):
        h, w = img.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    oh, ow = size
    h, w = img.shape[:2]
    ys = (np.arange(oh) * (h / oh)).astype(np.int64).clip(0, h - 1)
    xs = (np.arange(ow) * (w / ow)).astype(np.int64).clip(0, w - 1)
    return img[ys][:, xs]


def hflip(img):
    return _to_np(img)[:, ::-1]


def normalize(img, mean, std, data_format='CHW', to_rgb=False):
    img = _to_np(img).astype(np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == 'CHW':
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    return (img - mean) / std


def to_tensor(img, data_format='CHW'):
    img = _to_np(img).astype(np.float32)
    if img.ndim == 2:
        img = img[:, :, None]
    if data_format == 'CHW':
        img = img.transpose(2, 0, 1)
    if img.max() > 1.5:
        img = img / 255.0
    return Tensor(img)


class BaseTransform:
    def __call__(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class Resize(BaseTransform):
    def __init__(self, size, interpolation='bilinear', keys=None):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.padding = padding

    def __call__(self, img):
        img = _to_np(img)
        if self.padding:
            p = self.padding
            img = np.pad(img, [(p, p), (p, p)] + [(0, 0)] * (img.ndim - 2))
        h, w = img.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[i:i + th, j:j + tw]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, numbers.Number) else size

    def __call__(self, img):
        img = _to_np(img)
        h, w = img.shape[:2]
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return img[i:i + th, j:j + tw]


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation='bilinear', keys=None):
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        img = _to_np(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target_area * ar)))
            th = int(round(np.sqrt(target_area / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                return resize(img[i:i + th, j:j + tw], self.size)
        return resize(CenterCrop(min(h, w))(img), self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return hflip(img)
        return _to_np(img)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format='CHW', to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean, self.std, self.data_format = mean, std, data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class ToTensor(BaseTransform):
    def __init__(self, data_format='CHW', keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        return _to_np(img).transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        img = _to_np(img).astype(np.float32)
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(img * alpha, 0, 255)
