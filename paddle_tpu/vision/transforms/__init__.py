"""Image transforms (parity: python/paddle/vision/transforms)."""
from .transforms import (Compose, Resize, RandomCrop, CenterCrop,
                         RandomHorizontalFlip, Normalize, ToTensor,
                         Transpose, RandomResizedCrop, BrightnessTransform,
                         normalize, to_tensor, resize, hflip)
