"""Vision ops (parity subset: python/paddle/vision/ops)."""
import jax.numpy as jnp
from ..core.tensor import Tensor


def nms(boxes, iou_threshold=0.3, scores=None, **kwargs):
    import numpy as np
    b = np.asarray(boxes.data)
    s = np.asarray(scores.data) if scores is not None else np.ones(len(b))
    order = np.argsort(-s)
    keep = []
    while order.size:
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        xx1 = np.maximum(b[i, 0], b[order[1:], 0])
        yy1 = np.maximum(b[i, 1], b[order[1:], 1])
        xx2 = np.minimum(b[i, 2], b[order[1:], 2])
        yy2 = np.minimum(b[i, 3], b[order[1:], 3])
        w = np.maximum(0.0, xx2 - xx1)
        h = np.maximum(0.0, yy2 - yy1)
        inter = w * h
        area_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
        areas = (b[order[1:], 2] - b[order[1:], 0]) * \
            (b[order[1:], 3] - b[order[1:], 1])
        iou = inter / (area_i + areas - inter)
        order = order[1:][iou <= iou_threshold]
    return Tensor(np.asarray(keep, dtype=np.int64))


def roi_align(*a, **k):
    raise NotImplementedError("roi_align lands with the detection tier")
