"""Vision ops (parity subset: python/paddle/vision/ops)."""
import numpy as np
import jax.numpy as jnp
from ..core.tensor import Tensor


def nms(boxes, iou_threshold=0.3, scores=None, **kwargs):
    import numpy as np
    b = np.asarray(boxes.data)
    s = np.asarray(scores.data) if scores is not None else np.ones(len(b))
    order = np.argsort(-s)
    keep = []
    while order.size:
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        xx1 = np.maximum(b[i, 0], b[order[1:], 0])
        yy1 = np.maximum(b[i, 1], b[order[1:], 1])
        xx2 = np.minimum(b[i, 2], b[order[1:], 2])
        yy2 = np.minimum(b[i, 3], b[order[1:], 3])
        w = np.maximum(0.0, xx2 - xx1)
        h = np.maximum(0.0, yy2 - yy1)
        inter = w * h
        area_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
        areas = (b[order[1:], 2] - b[order[1:], 0]) * \
            (b[order[1:], 3] - b[order[1:], 1])
        iou = inter / (area_i + areas - inter)
        order = order[1:][iou <= iou_threshold]
    return Tensor(np.asarray(keep, dtype=np.int64))


def roi_align(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Parity: paddle.vision.ops.roi_align (operators/roi_align_op.cc).

    Bilinear-sampled ROI pooling, fully vectorized (vmap over ROIs — the
    CUDA kernel's thread-per-cell loop becomes one gather/average graph).
    sampling_ratio <= 0 uses 2 samples per cell axis (the adaptive
    ceil(roi/out) rule is data-dependent, which XLA's static shapes
    exclude; 2 matches the common detectron default).
    """
    import jax
    from ..core.autograd import run_op
    from ..ops.common import as_tensor
    x = as_tensor(x)
    boxes = as_tensor(boxes)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else output_size
    if boxes_num is not None:
        bn = np.asarray(as_tensor(boxes_num).data).reshape(-1)
    else:
        bn = np.array([boxes.shape[0]])
    batch_idx = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)
    sr = sampling_ratio if sampling_ratio > 0 else 2

    def fn(feat, bxs):
        offset = 0.5 if aligned else 0.0
        x1 = bxs[:, 0] * spatial_scale - offset
        y1 = bxs[:, 1] * spatial_scale - offset
        x2 = bxs[:, 2] * spatial_scale - offset
        y2 = bxs[:, 3] * spatial_scale - offset
        rw, rh = x2 - x1, y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bw, bh = rw / ow, rh / oh
        H, W = feat.shape[2], feat.shape[3]

        # sample coords per roi: [oh*sr] x [ow*sr]
        gy = (jnp.arange(oh * sr) + 0.5) / sr          # in bin units
        gx = (jnp.arange(ow * sr) + 0.5) / sr

        def one(b, yy1, xx1, bhh, bww):
            ys = yy1 + gy * bhh                        # [oh*sr]
            xs = xx1 + gx * bww
            # reference kernel: samples outside [-1, H]/[-1, W] contribute
            # zero (not edge replication)
            yok = (ys >= -1.0) & (ys <= H)
            xok = (xs >= -1.0) & (xs <= W)
            ys = jnp.clip(ys, 0.0, H - 1)
            xs = jnp.clip(xs, 0.0, W - 1)
            y0 = jnp.floor(ys)
            x0 = jnp.floor(xs)
            y1i = jnp.clip(y0 + 1, 0, H - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, W - 1).astype(jnp.int32)
            ly = jnp.clip(ys - y0, 0.0, 1.0)
            lx = jnp.clip(xs - x0, 0.0, 1.0)
            y0 = y0.astype(jnp.int32)
            x0 = x0.astype(jnp.int32)
            fm = feat[b]                               # [C, H, W]
            v00 = fm[:, y0][:, :, x0]
            v01 = fm[:, y0][:, :, x1i]
            v10 = fm[:, y1i][:, :, x0]
            v11 = fm[:, y1i][:, :, x1i]
            ly = ly[None, :, None]
            lx = lx[None, None, :]
            val = (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
                   + v10 * ly * (1 - lx) + v11 * ly * lx)  # [C,oh*sr,ow*sr]
            val = val * (yok[None, :, None] & xok[None, None, :])
            C = val.shape[0]
            return val.reshape(C, oh, sr, ow, sr).mean((2, 4))
        return jax.vmap(one)(batch_idx, y1, x1, bh, bw)
    return run_op('roi_align', fn, [x, boxes])


def roi_pool(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
             name=None):
    """Parity: paddle.vision.ops.roi_pool (max pooling over ROI bins)."""
    import jax
    from ..core.autograd import run_op
    from ..ops.common import as_tensor
    x = as_tensor(x)
    boxes = as_tensor(boxes)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else output_size
    if boxes_num is not None:
        bn = np.asarray(as_tensor(boxes_num).data).reshape(-1)
    else:
        bn = np.array([boxes.shape[0]])
    batch_idx = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)

    def fn(feat, bxs):
        H, W = feat.shape[2], feat.shape[3]
        x1 = jnp.floor(bxs[:, 0] * spatial_scale)
        y1 = jnp.floor(bxs[:, 1] * spatial_scale)
        x2 = jnp.ceil(bxs[:, 2] * spatial_scale)
        y2 = jnp.ceil(bxs[:, 3] * spatial_scale)

        def one(b, yy1, xx1, yy2, xx2):
            rh = jnp.maximum(yy2 - yy1, 1.0)
            rw = jnp.maximum(xx2 - xx1, 1.0)
            fm = feat[b]
            ys = jnp.arange(H, dtype=jnp.float32)
            xs = jnp.arange(W, dtype=jnp.float32)
            out = []
            for i in range(oh):
                for j in range(ow):
                    ylo = yy1 + rh * i / oh
                    yhi = yy1 + rh * (i + 1) / oh
                    xlo = xx1 + rw * j / ow
                    xhi = xx1 + rw * (j + 1) / ow
                    my = (ys >= jnp.floor(ylo)) & (ys < jnp.ceil(yhi))
                    mx = (xs >= jnp.floor(xlo)) & (xs < jnp.ceil(xhi))
                    m = my[:, None] & mx[None, :]
                    cell = jnp.where(m[None], fm, -jnp.inf).max((1, 2))
                    out.append(jnp.where(jnp.isfinite(cell), cell, 0.0))
            C = fm.shape[0]
            return jnp.stack(out, -1).reshape(C, oh, ow)
        return jax.vmap(one)(batch_idx, y1, x1, y2, x2)
    return run_op('roi_pool', fn, [x, boxes])


# ---- detection tier (paddle.vision.ops parity surface) ---------------------
# Implementations in vision/detection.py (fixed-shape TPU-native programs).
from .detection import (  # noqa: E402,F401
    yolo_box, prior_box, box_coder, anchor_generator, box_clip,
    iou_similarity, bipartite_match, multiclass_nms, matrix_nms,
    generate_proposals, deform_conv2d, distribute_fpn_proposals,
    collect_fpn_proposals, psroi_pool, density_prior_box)


_DEFORM_CONV_CLS = None


def _deform_conv_cls():
    global _DEFORM_CONV_CLS
    if _DEFORM_CONV_CLS is None:
        from ..nn.layer.base import Layer
        from ..nn import initializer as I

        class DeformConv2D(Layer):
            """Parity: paddle.vision.ops.DeformConv2D — layer wrapper over
            deform_conv2d (deformable_conv_op v1/v2)."""

            def __init__(self, in_channels, out_channels, kernel_size,
                         stride=1, padding=0, dilation=1,
                         deformable_groups=1, groups=1, weight_attr=None,
                         bias_attr=None):
                super().__init__()
                ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
                    else (kernel_size, kernel_size)
                self._attrs = dict(stride=stride, padding=padding,
                                   dilation=dilation,
                                   deformable_groups=deformable_groups,
                                   groups=groups)
                self.weight = self.create_parameter(
                    [out_channels, in_channels // groups, ks[0], ks[1]],
                    attr=weight_attr,
                    default_initializer=I.XavierUniform())
                self.bias = self.create_parameter(
                    [out_channels], attr=bias_attr, is_bias=True) \
                    if bias_attr is not False else None

            def forward(self, x, offset, mask=None):
                return deform_conv2d(x, offset, self.weight, self.bias,
                                     mask=mask, **self._attrs)
        _DEFORM_CONV_CLS = DeformConv2D
    return _DEFORM_CONV_CLS


def __getattr__(name):
    # single lazily-defined class (isinstance-stable across constructions);
    # lazy only to keep vision importable without pulling the whole nn tree
    if name == 'DeformConv2D':
        return _deform_conv_cls()
    raise AttributeError(name)


def read_file(filename, name=None):
    """paddle.vision.ops.read_file (operators/read_file_op.cc): raw file
    bytes as a 1-D uint8 tensor (host IO — input-pipeline op)."""
    with open(filename, 'rb') as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode='unchanged', name=None):
    """paddle.vision.ops.decode_jpeg (operators/decode_jpeg_op.cu uses
    nvJPEG; here PIL on host — same contract): 1-D uint8 encoded bytes →
    uint8 [C, H, W]. mode: 'unchanged' | 'gray' | 'rgb'."""
    import io
    from PIL import Image
    arr = np.asarray(x.data if isinstance(x, Tensor) else x,
                     dtype=np.uint8)
    img = Image.open(io.BytesIO(arr.tobytes()))
    if mode == 'gray':
        img = img.convert('L')
    elif mode in ('rgb', 'RGB'):
        img = img.convert('RGB')
    out = np.asarray(img)
    if out.ndim == 2:
        out = out[None]                   # [1, H, W]
    else:
        out = out.transpose(2, 0, 1)      # [C, H, W]
    return Tensor(jnp.asarray(out))
