"""Detection operator tier.

Reference parity: paddle/fluid/operators/detection/ (18.2k LoC) — the
SSD/YOLO/RCNN op family: iou_similarity_op.cc, box_coder_op.h
(encode/decode_center_size), prior_box_op.h, yolo_box_op.h,
bipartite_match_op.cc, multiclass_nms_op.cc, generate_proposals_v2_op.cc,
box_clip_op.h, anchor_generator_op.h, and deformable_conv_op (v1/v2).

TPU-native design: everything is expressed as fixed-shape jnp array math so
it traces under jit —
  * pure decode/geometry ops (iou, box_coder, prior_box, yolo_box,
    anchor_generator, box_clip, deform_conv2d) are differentiable tensor
    programs that XLA fuses;
  * selection ops (NMS family, bipartite match, proposal generation) replace
    the reference's LoD/dynamic-size outputs with padded fixed-size outputs
    plus a valid-count tensor (the TPU idiom for data-dependent shapes; the
    reference's own GPU kernels do the same internally before compacting).
Sequential decisions (greedy NMS / greedy matching) run as lax.fori_loop
over a precomputed IoU/distance matrix instead of the reference's nested
host loops.
"""
import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ..core.autograd import run_op
from ..ops.common import as_tensor


# ---------------------------------------------------------------------------
# geometry helpers
# ---------------------------------------------------------------------------

def _box_wh(boxes, normalized):
    off = 0.0 if normalized else 1.0
    w = boxes[..., 2] - boxes[..., 0] + off
    h = boxes[..., 3] - boxes[..., 1] + off
    return w, h


def _iou_matrix(a, b, normalized=True):
    """a [N, 4], b [M, 4] → IoU [N, M] (parity: iou_similarity_op.h)."""
    off = 0.0 if normalized else 1.0
    ax1, ay1, ax2, ay2 = a[:, 0], a[:, 1], a[:, 2], a[:, 3]
    bx1, by1, bx2, by2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    ix1 = jnp.maximum(ax1[:, None], bx1[None, :])
    iy1 = jnp.maximum(ay1[:, None], by1[None, :])
    ix2 = jnp.minimum(ax2[:, None], bx2[None, :])
    iy2 = jnp.minimum(ay2[:, None], by2[None, :])
    iw = jnp.clip(ix2 - ix1 + off, 0.0, None)
    ih = jnp.clip(iy2 - iy1 + off, 0.0, None)
    inter = iw * ih
    area_a = (ax2 - ax1 + off) * (ay2 - ay1 + off)
    area_b = (bx2 - bx1 + off) * (by2 - by1 + off)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


def iou_similarity(x, y, box_normalized=True, name=None):
    """Parity: detection/iou_similarity_op.cc — X [N, 4], Y [M, 4] →
    [N, M] IoU."""
    x, y = as_tensor(x), as_tensor(y)

    def fn(a, b):
        return _iou_matrix(a, b, box_normalized)
    return run_op('iou_similarity', fn, [x, y])


def box_clip(input, im_info, name=None):
    """Parity: detection/box_clip_op.h — clip boxes [..., 4] into the image.
    im_info: [N, 3] (h, w, scale) — boxes clipped to (h/scale - 1,
    w/scale - 1)."""
    input, im_info = as_tensor(input), as_tensor(im_info)

    def fn(boxes, info):
        h = info[:, 0] / info[:, 2] - 1.0
        w = info[:, 1] / info[:, 2] - 1.0
        shape = [info.shape[0]] + [1] * (boxes.ndim - 2)
        h = h.reshape(shape)
        w = w.reshape(shape)
        x1 = jnp.clip(boxes[..., 0], 0.0, None)
        y1 = jnp.clip(boxes[..., 1], 0.0, None)
        x2 = jnp.clip(boxes[..., 2], 0.0, None)
        y2 = jnp.clip(boxes[..., 3], 0.0, None)
        return jnp.stack([jnp.minimum(x1, w), jnp.minimum(y1, h),
                          jnp.minimum(x2, w), jnp.minimum(y2, h)], axis=-1)
    return run_op('box_clip', fn, [input, im_info])


# ---------------------------------------------------------------------------
# box_coder
# ---------------------------------------------------------------------------

def box_coder(prior_box, prior_box_var, target_box,
              code_type='encode_center_size', box_normalized=True, axis=0,
              variance=None, name=None):
    """Parity: detection/box_coder_op.h.

    encode: target [M, 4], prior [N, 4] → [M, N, 4]
    decode: target [M, N, 4] (or broadcast), prior [N, 4] → [M, N, 4]
    prior_box_var: None | [N, 4] tensor | 4-list (attr `variance`).
    """
    prior_box = as_tensor(prior_box)
    target_box = as_tensor(target_box)
    var_tensor = None
    if isinstance(prior_box_var, (list, tuple)):
        variance = list(prior_box_var)
    elif prior_box_var is not None:
        var_tensor = as_tensor(prior_box_var)
    off = 0.0 if box_normalized else 1.0

    def _prior_cxcywh(p):
        pw = p[:, 2] - p[:, 0] + off
        ph = p[:, 3] - p[:, 1] + off
        return p[:, 0] + pw / 2, p[:, 1] + ph / 2, pw, ph

    if code_type == 'encode_center_size':
        def fn(*args):
            t, p = args[0], args[1]
            v = args[2] if var_tensor is not None else None
            pcx, pcy, pw, ph = _prior_cxcywh(p)
            tw = t[:, 2] - t[:, 0] + off
            th = t[:, 3] - t[:, 1] + off
            tcx = (t[:, 0] + t[:, 2]) / 2
            tcy = (t[:, 1] + t[:, 3]) / 2
            out = jnp.stack([
                (tcx[:, None] - pcx[None, :]) / pw[None, :],
                (tcy[:, None] - pcy[None, :]) / ph[None, :],
                jnp.log(jnp.abs(tw[:, None] / pw[None, :])),
                jnp.log(jnp.abs(th[:, None] / ph[None, :])),
            ], axis=-1)  # [M, N, 4]
            if v is not None:
                out = out / v[None, :, :]
            elif variance:
                out = out / jnp.asarray(variance, out.dtype)
            return out
        tensors = [target_box, prior_box] + (
            [var_tensor] if var_tensor is not None else [])
        return run_op('box_coder', fn, tensors)

    assert code_type == 'decode_center_size', code_type

    def fn(*args):
        t, p = args[0], args[1]
        v = args[2] if var_tensor is not None else None
        pcx, pcy, pw, ph = _prior_cxcywh(p)
        # broadcast prior along the axis the op decodes over
        if axis == 0:
            shape = (1, -1)
        else:
            shape = (-1, 1)
        pcx, pcy = pcx.reshape(shape), pcy.reshape(shape)
        pw, ph = pw.reshape(shape), ph.reshape(shape)
        if v is not None:
            vv = v[None, :, :] if axis == 0 else v[:, None, :]
            v0, v1, v2, v3 = vv[..., 0], vv[..., 1], vv[..., 2], vv[..., 3]
        elif variance:
            v0, v1, v2, v3 = variance
        else:
            v0 = v1 = v2 = v3 = 1.0
        tcx = v0 * t[..., 0] * pw + pcx
        tcy = v1 * t[..., 1] * ph + pcy
        tw = jnp.exp(v2 * t[..., 2]) * pw
        th = jnp.exp(v3 * t[..., 3]) * ph
        return jnp.stack([tcx - tw / 2, tcy - th / 2,
                          tcx + tw / 2 - off, tcy + th / 2 - off], axis=-1)
    tensors = [target_box, prior_box] + (
        [var_tensor] if var_tensor is not None else [])
    return run_op('box_coder', fn, tensors)


# ---------------------------------------------------------------------------
# prior_box / anchor_generator
# ---------------------------------------------------------------------------

def _prior_wh(min_sizes, max_sizes, aspect_ratios, flip,
              min_max_aspect_ratios_order):
    """The per-cell (w, h) ladder — parity: prior_box_op.h ExpandAspectRatios
    + the kernel's emission order."""
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    whs = []
    for k, ms in enumerate(min_sizes):
        ms = float(ms)
        if not min_max_aspect_ratios_order:
            for ar in ars:
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
            if max_sizes:
                Ms = float(max_sizes[k])
                whs.append((math.sqrt(ms * Ms), math.sqrt(ms * Ms)))
        else:
            whs.append((ms, ms))
            if max_sizes:
                Ms = float(max_sizes[k])
                whs.append((math.sqrt(ms * Ms), math.sqrt(ms * Ms)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
    return whs


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """Parity: detection/prior_box_op.h — SSD priors.
    input [N, C, H, W] feature map, image [N, C, Him, Wim] →
    (boxes [H, W, P, 4] normalized, variances [H, W, P, 4])."""
    input, image = as_tensor(input), as_tensor(image)
    H, W = input.shape[2], input.shape[3]
    Him, Wim = image.shape[2], image.shape[3]
    step_w = steps[0] if steps and steps[0] > 0 else Wim / W
    step_h = steps[1] if steps and steps[1] > 0 else Him / H
    whs = _prior_wh(list(min_sizes), list(max_sizes or []),
                    list(aspect_ratios), flip, min_max_aspect_ratios_order)
    P = len(whs)

    def fn(_x, _im):
        cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
        cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
        cx = jnp.broadcast_to(cx[None, :, None], (H, W, P))
        cy = jnp.broadcast_to(cy[:, None, None], (H, W, P))
        bw = jnp.asarray([w for w, _ in whs], jnp.float32) / 2
        bh = jnp.asarray([h for _, h in whs], jnp.float32) / 2
        out = jnp.stack([(cx - bw) / Wim, (cy - bh) / Him,
                         (cx + bw) / Wim, (cy + bh) / Him], axis=-1)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               (H, W, P, 4))
        return out, var
    return run_op('prior_box', fn, [input, image])


def anchor_generator(input, anchor_sizes, aspect_ratios, variances,
                     stride, offset=0.5, name=None):
    """Parity: detection/anchor_generator_op.h — RPN anchors.
    input [N, C, H, W] → (anchors [H, W, A, 4] in input-image pixels,
    variances [H, W, A, 4])."""
    input = as_tensor(input)
    H, W = input.shape[2], input.shape[3]
    whs = []
    for ar in aspect_ratios:
        for s in anchor_sizes:
            area = float(stride[0] * stride[1])
            base_w = round(math.sqrt(area / float(ar)))
            base_h = round(base_w * float(ar))
            scale_w = float(s) / stride[0]
            scale_h = float(s) / stride[1]
            whs.append((scale_w * base_w, scale_h * base_h))
    A = len(whs)

    def fn(_x):
        # centers at stride*i + offset*(stride-1); corners at
        # center ± (size-1)/2 — anchor_generator_op.h:68-95
        cx = jnp.arange(W, dtype=jnp.float32) * stride[0] \
            + offset * (stride[0] - 1)
        cy = jnp.arange(H, dtype=jnp.float32) * stride[1] \
            + offset * (stride[1] - 1)
        cx = jnp.broadcast_to(cx[None, :, None], (H, W, A))
        cy = jnp.broadcast_to(cy[:, None, None], (H, W, A))
        hw = (jnp.asarray([w for w, _ in whs], jnp.float32) - 1) / 2
        hh = (jnp.asarray([h for _, h in whs], jnp.float32) - 1) / 2
        anchors = jnp.stack([cx - hw, cy - hh, cx + hw, cy + hh], axis=-1)
        var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                               (H, W, A, 4))
        return anchors, var
    return run_op('anchor_generator', fn, [input])


# ---------------------------------------------------------------------------
# yolo_box
# ---------------------------------------------------------------------------

def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Parity: detection/yolo_box_op.h — decode YOLOv3 head output.
    x [N, A*(5+cls), H, W] (A*(6+cls) when iou_aware), img_size [N, 2]
    (h, w) → boxes [N, A*H*W, 4], scores [N, A*H*W, cls]."""
    x, img_size = as_tensor(x), as_tensor(img_size)
    an = len(anchors) // 2
    scale = float(scale_x_y)
    bias = -0.5 * (scale - 1.0)

    def fn(a, imgs):
        N, C, H, W = a.shape
        if iou_aware:
            ious = a[:, :an].reshape(N, an, 1, H, W)
            a = a[:, an:]
        a = a.reshape(N, an, 5 + class_num, H, W)
        grid_x = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
        grid_y = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
        img_h = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        img_w = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        in_h = float(downsample_ratio * H)
        in_w = float(downsample_ratio * W)
        aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
        ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]

        af = a.astype(jnp.float32)
        cx = (grid_x + jax.nn.sigmoid(af[:, :, 0]) * scale + bias) \
            * img_w / W
        cy = (grid_y + jax.nn.sigmoid(af[:, :, 1]) * scale + bias) \
            * img_h / H
        bw = jnp.exp(af[:, :, 2]) * aw * img_w / in_w
        bh = jnp.exp(af[:, :, 3]) * ah * img_h / in_h
        conf = jax.nn.sigmoid(af[:, :, 4])
        if iou_aware:
            iou = jax.nn.sigmoid(ious[:, :, 0].astype(jnp.float32))
            conf = conf ** (1.0 - iou_aware_factor) \
                * iou ** iou_aware_factor
        keep = conf >= conf_thresh

        x1, y1 = cx - bw / 2, cy - bh / 2
        x2, y2 = cx + bw / 2, cy + bh / 2
        if clip_bbox:
            x1 = jnp.clip(x1, 0.0, None)
            y1 = jnp.clip(y1, 0.0, None)
            x2 = jnp.minimum(x2, img_w - 1)
            y2 = jnp.minimum(y2, img_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)       # [N,an,H,W,4]
        boxes = jnp.where(keep[..., None], boxes, 0.0)
        scores = conf[..., None] \
            * jax.nn.sigmoid(af[:, :, 5:].transpose(0, 1, 3, 4, 2))
        scores = jnp.where(keep[..., None], scores, 0.0)
        return (boxes.reshape(N, an * H * W, 4),
                scores.reshape(N, an * H * W, class_num))
    return run_op('yolo_box', fn, [x, img_size],
                  n_nondiff=1)


# ---------------------------------------------------------------------------
# bipartite match
# ---------------------------------------------------------------------------

def _bipartite_match_single(dist):
    """Greedy global-max matching on dist [R, C] → (col→row indices [C],
    col match dist [C]); unmatched = -1 (parity:
    bipartite_match_op.cc BipartiteMatch)."""
    R, C = dist.shape
    init = (jnp.full((C,), -1, jnp.int32), jnp.zeros((C,), dist.dtype),
            jnp.zeros((R,), bool), jnp.zeros((C,), bool))

    def body(_, state):
        midx, mdist, row_used, col_used = state
        masked = jnp.where(row_used[:, None] | col_used[None, :],
                           -jnp.inf, dist)
        flat = jnp.argmax(masked)
        r, c = flat // C, flat % C
        best = masked[r, c]
        ok = best > 1e-6
        midx = jnp.where(ok, midx.at[c].set(r.astype(jnp.int32)), midx)
        mdist = jnp.where(ok, mdist.at[c].set(best.astype(dist.dtype)),
                          mdist)
        row_used = jnp.where(ok, row_used.at[r].set(True), row_used)
        col_used = jnp.where(ok, col_used.at[c].set(True), col_used)
        return midx, mdist, row_used, col_used

    midx, mdist, _, _ = lax.fori_loop(0, min(R, C), body, init)
    return midx, mdist


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """Parity: detection/bipartite_match_op.cc. dist [B, R, C] (or [R, C])
    → (ColToRowMatchIndices [B, C], ColToRowMatchDist [B, C]).
    match_type='per_prediction' additionally argmax-matches unmatched
    columns whose best distance >= dist_threshold * max_col_dist... (the
    reference compares against `dist_threshold` directly)."""
    dist_matrix = as_tensor(dist_matrix)
    batched = dist_matrix.ndim == 3

    def fn(d):
        d3 = d if batched else d[None]

        def one(dd):
            midx, mdist = _bipartite_match_single(dd)
            if match_type == 'per_prediction':
                thr = 0.5 if dist_threshold is None else dist_threshold
                best_row = jnp.argmax(dd, axis=0).astype(jnp.int32)
                best = jnp.max(dd, axis=0)
                fill = (midx == -1) & (best >= thr)
                midx = jnp.where(fill, best_row, midx)
                mdist = jnp.where(fill, best.astype(mdist.dtype), mdist)
            return midx, mdist
        midx, mdist = jax.vmap(one)(d3)
        if not batched:
            midx, mdist = midx[0], mdist[0]
        return midx, mdist
    return run_op('bipartite_match', fn, [dist_matrix],
                  n_nondiff=1)


# ---------------------------------------------------------------------------
# NMS family
# ---------------------------------------------------------------------------

def _greedy_nms_mask(boxes, scores, iou_threshold, normalized=True,
                     score_threshold=None, eta=1.0):
    """Greedy NMS over all boxes (descending score) → keep mask [M].
    eta < 1 tightens the threshold after each kept box once it exceeds 0.5
    (adaptive NMS — multiclass_nms_op.cc NMSFast)."""
    M = boxes.shape[0]
    iou = _iou_matrix(boxes, boxes, normalized)
    order = jnp.argsort(-scores)
    valid0 = jnp.ones((M,), bool) if score_threshold is None else \
        (scores > score_threshold)

    def body(i, state):
        keep, supp, thr = state
        idx = order[i]
        ok = (~supp[idx]) & valid0[idx]
        keep = keep.at[idx].set(ok)
        supp = jnp.where(ok, supp | (iou[idx] > thr), supp)
        if eta < 1.0:
            thr = jnp.where(ok & (thr > 0.5), thr * eta, thr)
        return keep, supp, thr

    keep, _, _ = lax.fori_loop(
        0, M, body, (jnp.zeros((M,), bool), jnp.zeros((M,), bool),
                     jnp.asarray(iou_threshold, jnp.float32)))
    return keep


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=-1,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=-1, name=None):
    """Parity: detection/multiclass_nms_op.cc (multiclass_nms2 outputs).
    bboxes [N, M, 4], scores [N, C, M] →
      out   [N, keep_top_k, 6]  rows (label, score, x1, y1, x2, y2),
      index [N, keep_top_k]     input box index (−1 past valid count),
      count [N]                 kept per image.
    Fixed-shape/padded in place of the reference's LoD output."""
    bboxes, scores = as_tensor(bboxes), as_tensor(scores)
    K = int(keep_top_k)

    def fn(bb, sc):
        N, M, _ = bb.shape
        C = sc.shape[1]

        def one(boxes, s):
            # per-class greedy NMS (background skipped via score=-inf)
            def per_class(c_scores):
                cs = c_scores
                if 0 < nms_top_k < M:
                    # pre-NMS candidate truncation
                    # (multiclass_nms_op.cc GetMaxScoreIndex top_k)
                    kth = -jnp.sort(-cs)[nms_top_k - 1]
                    cs = jnp.where(cs >= kth, cs, -jnp.inf)
                keep = _greedy_nms_mask(boxes, cs, nms_threshold,
                                        normalized, score_threshold,
                                        eta=nms_eta)
                return jnp.where(keep, c_scores, -jnp.inf)
            kept_scores = jax.vmap(per_class)(s)        # [C, M]
            if background_label >= 0:
                kept_scores = kept_scores.at[background_label].set(-jnp.inf)
            flat = kept_scores.reshape(-1)               # [C*M]
            k_eff = min(K, flat.shape[0])    # fewer candidates than K:
            top, arg = lax.top_k(flat, k_eff)  # pad the tail below
            label = (arg // M).astype(jnp.float32)
            box_id = arg % M
            chosen = boxes[box_id]
            valid = top > -jnp.inf
            row = jnp.concatenate([
                jnp.where(valid, label, -1.0)[:, None],
                jnp.where(valid, top, 0.0)[:, None],
                jnp.where(valid[:, None], chosen, 0.0)], axis=1)
            idx_out = jnp.where(valid, box_id, -1).astype(jnp.int32)
            if k_eff < K:
                pad = K - k_eff
                row = jnp.concatenate([
                    row, jnp.tile(jnp.asarray(
                        [[-1.0, 0, 0, 0, 0, 0]], row.dtype), (pad, 1))])
                idx_out = jnp.concatenate(
                    [idx_out, jnp.full((pad,), -1, jnp.int32)])
            return row, idx_out, jnp.sum(valid).astype(jnp.int32)
        return jax.vmap(one)(bb, sc)
    return run_op('multiclass_nms', fn, [bboxes, scores],
                  n_nondiff=1)


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=-1, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               name=None):
    """Parity: detection/matrix_nms_op.cc — parallel soft-NMS: each box's
    score is decayed by its worst higher-scored same-class overlap; no
    sequential suppression, so it is one dense matrix program (the op the
    reference added precisely because greedy NMS serializes on
    accelerators). Fixed-shape outputs like multiclass_nms."""
    bboxes, scores = as_tensor(bboxes), as_tensor(scores)
    K = int(keep_top_k)

    def fn(bb, sc):
        N, M, _ = bb.shape
        C = sc.shape[1]

        def one(boxes, s):
            iou = _iou_matrix(boxes, boxes, normalized)

            def per_class(c_scores):
                valid = c_scores > score_threshold
                if 0 < nms_top_k < M:
                    # pre-decay candidate truncation
                    # (matrix_nms_op.cc:125-126)
                    kth = -jnp.sort(-jnp.where(valid, c_scores,
                                               -jnp.inf))[nms_top_k - 1]
                    valid = valid & (c_scores >= kth)
                cs = jnp.where(valid, c_scores, -jnp.inf)
                order = jnp.argsort(-cs)
                rank = jnp.argsort(order)        # rank[i]: position of box i
                higher = rank[None, :] < rank[:, None]   # j ranked above i
                iou_h = jnp.where(higher, iou, 0.0)
                max_iou = jnp.max(iou_h, axis=1)          # worst overlap
                # decay per reference: min over j of decay(iou_ij)/decay(max_iou_j)
                comp = jnp.where(higher, iou, 0.0)
                max_iou_j = max_iou[None, :]
                if use_gaussian:
                    decay = jnp.exp((max_iou_j ** 2 - comp ** 2)
                                    * gaussian_sigma)
                else:
                    decay = (1.0 - comp) / (1.0 - max_iou_j)
                decay = jnp.where(higher, decay, jnp.inf)
                decay = jnp.clip(jnp.min(decay, axis=1), None, 1.0)
                out = jnp.where(valid, c_scores * decay, -jnp.inf)
                if post_threshold > 0.0:
                    out = jnp.where(out >= post_threshold, out, -jnp.inf)
                return out
            kept = jax.vmap(per_class)(s)
            if background_label >= 0:
                kept = kept.at[background_label].set(-jnp.inf)
            flat = kept.reshape(-1)
            top, arg = lax.top_k(flat, K)
            label = (arg // M).astype(jnp.float32)
            box_id = arg % M
            valid = top > -jnp.inf
            row = jnp.concatenate([
                jnp.where(valid, label, -1.0)[:, None],
                jnp.where(valid, top, 0.0)[:, None],
                jnp.where(valid[:, None], boxes[box_id], 0.0)], axis=1)
            idx_out = jnp.where(valid, box_id, -1).astype(jnp.int32)
            return row, idx_out, jnp.sum(valid).astype(jnp.int32)
        return jax.vmap(one)(bb, sc)
    return run_op('matrix_nms', fn, [bboxes, scores],
                  n_nondiff=1)


# ---------------------------------------------------------------------------
# generate_proposals (RPN)
# ---------------------------------------------------------------------------

def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=True, name=None):
    """Parity: detection/generate_proposals_v2_op.cc.
    scores [N, A, H, W], bbox_deltas [N, 4A, H, W], img_size [N, 2] (h, w),
    anchors [H, W, A, 4], variances [H, W, A, 4] →
      rois [N, post_nms_top_n, 4], roi_scores [N, post_nms_top_n],
      roi_nums [N] (fixed-shape padded in place of LoD)."""
    scores, bbox_deltas = as_tensor(scores), as_tensor(bbox_deltas)
    img_size = as_tensor(img_size)
    anchors, variances = as_tensor(anchors), as_tensor(variances)
    off = 1.0 if pixel_offset else 0.0
    clip_ratio = math.log(1000.0 / 16.0)

    def fn(sc, deltas, imgs, anc, var):
        N, A, H, W = sc.shape
        M = A * H * W
        anc_f = anc.reshape(-1, 4)
        var_f = var.reshape(-1, 4)
        pre_n = min(pre_nms_top_n, M)

        def one(s, d, img):
            s_f = s.transpose(1, 2, 0).reshape(-1)           # [H*W*A]
            d_f = d.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
            # NB: anchors arrive [H, W, A, 4] so flatten order matches
            top, arg = lax.top_k(s_f, pre_n)
            d_t = d_f[arg]
            a_t = anc_f[arg]
            v_t = var_f[arg]
            # decode (bbox_util.h BoxCoder: variance-scaled, ratio-clipped)
            aw = a_t[:, 2] - a_t[:, 0] + off
            ah = a_t[:, 3] - a_t[:, 1] + off
            acx = a_t[:, 0] + aw * 0.5
            acy = a_t[:, 1] + ah * 0.5
            cx = v_t[:, 0] * d_t[:, 0] * aw + acx
            cy = v_t[:, 1] * d_t[:, 1] * ah + acy
            w = jnp.exp(jnp.minimum(v_t[:, 2] * d_t[:, 2], clip_ratio)) * aw
            h = jnp.exp(jnp.minimum(v_t[:, 3] * d_t[:, 3], clip_ratio)) * ah
            x1 = cx - w * 0.5
            y1 = cy - h * 0.5
            x2 = cx + w * 0.5 - off
            y2 = cy + h * 0.5 - off
            # clip to image
            ih, iw = img[0], img[1]
            x1 = jnp.clip(x1, 0.0, iw - off)
            y1 = jnp.clip(y1, 0.0, ih - off)
            x2 = jnp.clip(x2, 0.0, iw - off)
            y2 = jnp.clip(y2, 0.0, ih - off)
            boxes = jnp.stack([x1, y1, x2, y2], axis=1)
            # filter small
            bw = x2 - x1 + off
            bh = y2 - y1 + off
            ms = jnp.maximum(min_size, 1.0)
            big = (bw >= ms) & (bh >= ms)
            s_kept = jnp.where(big, top, -jnp.inf)
            keep = _greedy_nms_mask(boxes, s_kept, nms_thresh,
                                    normalized=not pixel_offset)
            keep = keep & big
            final = jnp.where(keep, s_kept, -jnp.inf)
            k = min(post_nms_top_n, pre_n)
            top2, arg2 = lax.top_k(final, k)
            valid = top2 > -jnp.inf
            rois = jnp.where(valid[:, None], boxes[arg2], 0.0)
            rscores = jnp.where(valid, top2, 0.0)
            pad = post_nms_top_n - k
            if pad:
                rois = jnp.pad(rois, ((0, pad), (0, 0)))
                rscores = jnp.pad(rscores, ((0, pad),))
                valid = jnp.pad(valid, ((0, pad),))
            return rois, rscores, jnp.sum(valid).astype(jnp.int32)
        return jax.vmap(one)(sc, deltas, imgs.astype(sc.dtype))
    return run_op('generate_proposals', fn,
                  [scores, bbox_deltas, img_size, anchors, variances],
                  n_nondiff=3)


# ---------------------------------------------------------------------------
# deformable convolution
# ---------------------------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Parity: operators/deformable_conv_op.cc (v2 with mask; v1 when
    mask=None). x [N, Cin, H, W]; offset [N, 2*dg*kh*kw, Ho, Wo] (y, x
    interleaved per kernel point); mask [N, dg*kh*kw, Ho, Wo];
    weight [Cout, Cin/groups, kh, kw].

    TPU-native: bilinear sampling as four gathers + an einsum contraction
    (the im2col the reference builds per-image in modulated_deformable_im2col
    becomes one batched tensor program, fully differentiable through
    jax.vjp)."""
    x, offset, weight = as_tensor(x), as_tensor(offset), as_tensor(weight)
    tensors = [x, offset, weight]
    if mask is not None:
        tensors.append(as_tensor(mask))
    if bias is not None:
        tensors.append(as_tensor(bias))
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    has_mask = mask is not None
    has_bias = bias is not None

    def fn(*args):
        xa, off, wgt = args[0], args[1], args[2]
        msk = args[3] if has_mask else None
        b = args[3 + has_mask] if has_bias else None
        N, Cin, H, W = xa.shape
        Cout, _, kh, kw = wgt.shape
        Ho = (H + 2 * p[0] - (d[0] * (kh - 1) + 1)) // s[0] + 1
        Wo = (W + 2 * p[1] - (d[1] * (kw - 1) + 1)) // s[1] + 1
        dg = deformable_groups
        K = kh * kw

        off = off.reshape(N, dg, K, 2, Ho, Wo)
        base_y = (jnp.arange(Ho) * s[0] - p[0])[:, None] \
            + (jnp.arange(kh) * d[0])[None, :]                # [Ho, kh]
        base_x = (jnp.arange(Wo) * s[1] - p[1])[:, None] \
            + (jnp.arange(kw) * d[1])[None, :]                # [Wo, kw]
        ky = jnp.broadcast_to(base_y[:, None, :, None], (Ho, Wo, kh, kw))
        kx = jnp.broadcast_to(base_x[None, :, None, :], (Ho, Wo, kh, kw))
        ky = ky.reshape(Ho, Wo, K).transpose(2, 0, 1)[None, None]
        kx = kx.reshape(Ho, Wo, K).transpose(2, 0, 1)[None, None]
        py = ky + off[:, :, :, 0].astype(jnp.float32)     # [N, dg, K, Ho, Wo]
        px = kx + off[:, :, :, 1].astype(jnp.float32)

        y0 = jnp.floor(py)
        x0 = jnp.floor(px)
        wy = py - y0
        wx = px - x0

        def gather(yy, xx):
            yi = yy.astype(jnp.int32)
            xi = xx.astype(jnp.int32)
            inside = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            yc = jnp.clip(yi, 0, H - 1)
            xc = jnp.clip(xi, 0, W - 1)
            # x grouped by deformable group: [N, dg, Cin/dg, H, W]
            xg = xa.reshape(N, dg, Cin // dg, H, W)
            flat = xg.reshape(N, dg, Cin // dg, H * W)
            idx = yc * W + xc                          # [N, dg, K, Ho, Wo]
            idx_f = idx.reshape(N, dg, -1)
            out = jnp.take_along_axis(
                flat, idx_f[:, :, None, :].repeat(Cin // dg, 2), axis=3)
            out = out.reshape(N, dg, Cin // dg, K, Ho, Wo)
            return jnp.where(inside[:, :, None], out, 0.0)

        v00 = gather(y0, x0)
        v01 = gather(y0, x0 + 1)
        v10 = gather(y0 + 1, x0)
        v11 = gather(y0 + 1, x0 + 1)
        wy_ = wy[:, :, None]
        wx_ = wx[:, :, None]
        sampled = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_
                   + v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)
        if msk is not None:
            sampled = sampled * msk.reshape(N, dg, 1, K, Ho, Wo)
        # [N, Cin, K, Ho, Wo] → group conv contraction
        cols = sampled.reshape(N, Cin, K, Ho, Wo)
        cols = cols.reshape(N, groups, Cin // groups, K, Ho, Wo)
        wg = wgt.reshape(groups, Cout // groups, Cin // groups, K)
        out = jnp.einsum('ngckhw,gock->ngohw', cols, wg)
        out = out.reshape(N, Cout, Ho, Wo)
        if b is not None:
            out = out + b.reshape(1, Cout, 1, 1)
        return out.astype(xa.dtype)
    return run_op('deformable_conv', fn, tensors)


# ---------------------------------------------------------------------------
# FPN / RCNN remainder
# ---------------------------------------------------------------------------

def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, name=None):
    """Parity: detection/distribute_fpn_proposals_op.cc — route each RoI
    to its FPN level by scale: level = floor(refer_level +
    log2(sqrt(area) / refer_scale)), clamped to [min_level, max_level].

    fpn_rois [R, 4] → (multi_rois: per-level [R, 4] padded arrays,
    level_counts [L], restore_ind [R]) — fixed-shape (each level array
    keeps R slots; rows beyond its count are zeros), restore_ind maps the
    concatenated per-level order back to the input order (the reference's
    RestoreIndex output)."""
    fpn_rois = as_tensor(fpn_rois)
    n_levels = max_level - min_level + 1

    def fn(rois):
        R = rois.shape[0]
        off = 1.0 if pixel_offset else 0.0
        w = rois[:, 2] - rois[:, 0] + off
        h = rois[:, 3] - rois[:, 1] + off
        scale = jnp.sqrt(jnp.maximum(w * h, 1e-12))
        lvl = jnp.floor(refer_level + jnp.log2(scale / refer_scale + 1e-12))
        lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
        lvl_idx = lvl - min_level                       # [R] in [0, L)

        # stable order: sort by (level, original index)
        order = jnp.argsort(lvl_idx * R + jnp.arange(R))
        sorted_lvl = lvl_idx[order]
        counts = jnp.bincount(lvl_idx, length=n_levels)
        starts = jnp.cumsum(counts) - counts
        # position of each sorted roi within its level
        pos_in_level = jnp.arange(R) - starts[sorted_lvl]
        multi = jnp.zeros((n_levels, R, 4), rois.dtype)
        multi = multi.at[sorted_lvl, pos_in_level].set(rois[order])
        # restore index: for each input roi, its rank in the level-major
        # concatenation (reference RestoreIndex semantics)
        rank_of_sorted = starts[sorted_lvl] + pos_in_level
        restore = jnp.zeros((R,), jnp.int32).at[order].set(
            rank_of_sorted.astype(jnp.int32))
        return multi, counts.astype(jnp.int32), restore
    return run_op('distribute_fpn_proposals', fn, [fpn_rois],
                  n_nondiff=1)


def collect_fpn_proposals(multi_rois, multi_scores, post_nms_top_n,
                          name=None):
    """Parity: detection/collect_fpn_proposals_op.cc — concat per-level
    RoIs, keep the global top post_nms_top_n by score.
    multi_rois: [L, R, 4] (or list), multi_scores: [L, R] with -inf/0 at
    padded slots → (rois [K, 4], scores [K], count)."""
    if isinstance(multi_rois, (list, tuple)):
        from ..ops import manip as _m
        multi_rois = _m.concat([_m.unsqueeze(as_tensor(r), [0])
                                for r in multi_rois], 0)
        multi_scores = _m.concat([_m.unsqueeze(as_tensor(s), [0])
                                  for s in multi_scores], 0)
    multi_rois = as_tensor(multi_rois)
    multi_scores = as_tensor(multi_scores)
    K = int(post_nms_top_n)

    def fn(rois, scores):
        flat_r = rois.reshape(-1, 4)
        flat_s = scores.reshape(-1).astype(jnp.float32)
        k = min(K, flat_s.shape[0])
        top, arg = lax.top_k(flat_s, k)
        valid = top > -jnp.inf
        out_r = jnp.where(valid[:, None], flat_r[arg], 0.0)
        out_s = jnp.where(valid, top, 0.0)
        if k < K:
            out_r = jnp.pad(out_r, ((0, K - k), (0, 0)))
            out_s = jnp.pad(out_s, ((0, K - k),))
            valid = jnp.pad(valid, ((0, K - k),))
        return out_r, out_s, jnp.sum(valid).astype(jnp.int32)
    return run_op('collect_fpn_proposals', fn, [multi_rois, multi_scores],
                  n_nondiff=1)


def psroi_pool(x, boxes, output_channels, spatial_scale, pooled_height,
               pooled_width, boxes_num=None, name=None):
    """Parity: operators/psroi_pool_op.cc — position-sensitive RoI
    pooling: x [N, C=out_c*ph*pw, H, W], boxes [R, 4] (batch 0; extend
    via boxes_num offsets), each output channel/bin pair (c, i, j)
    average-pools input channel c*ph*pw + i*pw + j over its bin →
    [R, out_c, ph, pw]."""
    x, boxes = as_tensor(x), as_tensor(boxes)
    ph, pw = int(pooled_height), int(pooled_width)
    oc = int(output_channels)

    def fn(a, bx):
        N, C, H, W = a.shape
        R = bx.shape[0]

        def one(box):
            x1 = box[0] * spatial_scale
            y1 = box[1] * spatial_scale
            x2 = box[2] * spatial_scale
            y2 = box[3] * spatial_scale
            rw = jnp.maximum(x2 - x1, 0.1)
            rh = jnp.maximum(y2 - y1, 0.1)
            bin_w = rw / pw
            bin_h = rh / ph
            # integer bin extents (reference: floor/ceil per bin)
            ys = jnp.arange(H, dtype=jnp.float32)
            xs = jnp.arange(W, dtype=jnp.float32)

            out = []
            for i in range(ph):
                for j in range(pw):
                    hs = y1 + i * bin_h
                    he = y1 + (i + 1) * bin_h
                    ws = x1 + j * bin_w
                    we = x1 + (j + 1) * bin_w
                    mask = ((ys[:, None] >= jnp.floor(hs))
                            & (ys[:, None] < jnp.ceil(he))
                            & (xs[None, :] >= jnp.floor(ws))
                            & (xs[None, :] < jnp.ceil(we)))
                    area = jnp.maximum(mask.sum(), 1)
                    ch = jnp.arange(oc) * ph * pw + i * pw + j
                    vals = (a[0, ch] * mask[None]).sum((1, 2)) / area
                    out.append(vals)                    # [oc]
            return jnp.stack(out, 1).reshape(oc, ph, pw)
        return jax.vmap(one)(bx.astype(jnp.float32))
    return run_op('psroi_pool', fn, [x, boxes], n_nondiff=1)


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, name=None):
    """Parity: detection/density_prior_box_op.cc — per cell, for each
    (density, fixed_size) pair and fixed ratio, a density×density grid of
    shifted boxes of size fixed_size*sqrt(ratio) (the face-detection
    prior ladder)."""
    input, image = as_tensor(input), as_tensor(image)
    H, W = input.shape[2], input.shape[3]
    Him, Wim = image.shape[2], image.shape[3]
    step_w = steps[0] if steps and steps[0] > 0 else Wim / W
    step_h = steps[1] if steps and steps[1] > 0 else Him / H
    # per-cell (dx, dy, w, h) ladder (densities[k] pairs fixed_sizes[k])
    ladder = []
    for fs, dens in zip(fixed_sizes, densities):
        for ar in fixed_ratios:
            bw = float(fs) * math.sqrt(ar)
            bh = float(fs) / math.sqrt(ar)
            shift = step_w / dens
            for di in range(dens):
                for dj in range(dens):
                    cx_off = (dj + 0.5) * shift - step_w / 2
                    cy_off = (di + 0.5) * shift - step_h / 2
                    ladder.append((cx_off, cy_off, bw, bh))
    P = len(ladder)

    def fn(_x, _im):
        cx0 = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
        cy0 = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
        offs = jnp.asarray(ladder, jnp.float32)         # [P, 4]
        cx = jnp.broadcast_to(cx0[None, :, None]
                              + offs[None, None, :, 0], (H, W, P))
        cy = jnp.broadcast_to(cy0[:, None, None]
                              + offs[None, None, :, 1], (H, W, P))
        bw = offs[:, 2] / 2
        bh = offs[:, 3] / 2
        out = jnp.stack([(cx - bw) / Wim, (cy - bh) / Him,
                         (cx + bw) / Wim, (cy + bh) / Him], -1)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               (H, W, P, 4))
        return out, var
    return run_op('density_prior_box', fn, [input, image])


class DetectionMAP:
    """Parity: operators/detection_map_op.cc / fluid.metrics.DetectionMAP
    — mean average precision over accumulated detections, '11point' or
    'integral' interpolation, difficult-gt exclusion. Host-side metric
    (the reference kernel is CPU-only)."""

    def __init__(self, class_num, overlap_threshold=0.5,
                 evaluate_difficult=False, ap_version='integral'):
        if ap_version not in ('integral', '11point'):
            raise ValueError("ap_version must be 'integral' or '11point'")
        self.class_num = class_num
        self.iou = overlap_threshold
        self.eval_difficult = evaluate_difficult
        self.ap_version = ap_version
        self.reset()

    def reset(self):
        self._dets = []     # (img, cls, score, box)
        self._gts = []      # (img, cls, box, difficult)
        self._img = 0

    @staticmethod
    def _iou(a, b):
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    def update(self, pred_boxes, pred_scores, pred_labels, gt_boxes,
               gt_labels, difficult=None):
        """One image: preds [N,4]/[N]/[N], gts [M,4]/[M], difficult [M]."""
        pb = np.asarray(pred_boxes, np.float64).reshape(-1, 4)
        ps = np.asarray(pred_scores, np.float64).reshape(-1)
        pl = np.asarray(pred_labels).reshape(-1)
        gb = np.asarray(gt_boxes, np.float64).reshape(-1, 4)
        gl = np.asarray(gt_labels).reshape(-1)
        df = (np.zeros(len(gl), bool) if difficult is None
              else np.asarray(difficult).reshape(-1).astype(bool))
        i = self._img
        for b, s, c in zip(pb, ps, pl):
            self._dets.append((i, int(c), float(s), tuple(b)))
        for b, c, d in zip(gb, gl, df):
            self._gts.append((i, int(c), tuple(b), bool(d)))
        self._img += 1

    def accumulate(self):
        """→ mAP in [0, 1]."""
        aps = []
        for c in range(self.class_num):
            gts = [(g[0], g[2], g[3]) for g in self._gts if g[1] == c]
            if self.eval_difficult:
                npos = len(gts)
            else:
                npos = sum(1 for g in gts if not g[2])
            dets = sorted((d for d in self._dets if d[1] == c),
                          key=lambda d: -d[2])
            if npos == 0:
                continue
            matched = set()
            tp = np.zeros(len(dets))
            fp = np.zeros(len(dets))
            by_img = {}
            for gi, (img, box, dif) in enumerate(gts):
                by_img.setdefault(img, []).append((gi, box, dif))
            for di, (img, _, _, box) in enumerate(dets):
                best, best_gi = 0.0, -1
                for gi, gbox, dif in by_img.get(img, []):
                    ov = self._iou(box, gbox)
                    if ov > best:
                        best, best_gi = ov, gi
                if best_gi >= 0 and best >= self.iou:
                    gi = best_gi
                    dif = gts[gi][2]
                    if dif and not self.eval_difficult:
                        continue            # neither tp nor fp
                    if gi not in matched:
                        matched.add(gi)
                        tp[di] = 1
                    else:
                        fp[di] = 1
                else:
                    fp[di] = 1
            ctp = np.cumsum(tp)
            cfp = np.cumsum(fp)
            rec = ctp / npos
            prec = ctp / np.maximum(ctp + cfp, 1e-12)
            if self.ap_version == '11point':
                ap = 0.0
                for t in np.linspace(0, 1, 11):
                    p = prec[rec >= t].max() if (rec >= t).any() else 0.0
                    ap += p / 11.0
            else:
                mrec = np.concatenate([[0.0], rec, [1.0]])
                mpre = np.concatenate([[0.0], prec, [0.0]])
                for k in range(len(mpre) - 2, -1, -1):
                    mpre[k] = max(mpre[k], mpre[k + 1])
                idx = np.where(mrec[1:] != mrec[:-1])[0]
                ap = float(np.sum((mrec[idx + 1] - mrec[idx])
                                  * mpre[idx + 1]))
            aps.append(ap)
        return float(min(np.mean(aps), 1.0)) if aps else 0.0


# ---------------------------------------------------------------------------
# detection tail (VERDICT r3 op remainder, wave 2a — device ops)
# ---------------------------------------------------------------------------

def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    """fluid.layers.sigmoid_focal_loss (fluid/layers/detection.py:475,
    operators/detection/sigmoid_focal_loss_op.cc): x [N, C] logits over C
    REAL classes, label [N, 1] in [0, C] with 0 = background, fg_num [1]
    the positive count; per-element focal loss scaled by 1/fg_num.
    Class j corresponds to label value j+1."""
    x = as_tensor(x)
    label = as_tensor(label, ref=x)
    fg_num = as_tensor(fg_num, ref=x)

    def fn(xv, fg, lab):
        C = xv.shape[1]
        pos = lab.reshape(-1, 1) == jnp.arange(1, C + 1)[None, :]
        # stable log-sigmoid pieces
        log_sig = jax.nn.log_sigmoid(xv)
        log_one_minus = jax.nn.log_sigmoid(-xv)
        sig = jax.nn.sigmoid(xv)
        fgc = jnp.maximum(fg.reshape(()).astype(xv.dtype), 1.0)
        loss_pos = -alpha * jnp.power(1.0 - sig, gamma) * log_sig / fgc
        loss_neg = -(1.0 - alpha) * jnp.power(sig, gamma) \
            * log_one_minus / fgc
        return jnp.where(pos, loss_pos, loss_neg)
    return run_op('sigmoid_focal_loss', fn, [x, fg_num, label],
                  n_nondiff=1)


def target_assign(input, matched_indices, negative_indices=None,
                  neg_lod=None, input_lod=None, mismatch_value=0,
                  name=None):
    """target_assign_op.cc (oracle: test_target_assign_op.py):
    input [R, P, K] packed per-gt rows (R = sum of per-image gt counts;
    `input_lod` = per-image gt counts, default R/B uniform),
    matched_indices [B, P] (LOCAL gt index per prior, -1 unmatched) →
      out [B, P, K]         gathered rows (mismatch_value at unmatched),
      out_weight [B, P, 1]  1.0 at matched priors (and at priors listed
                            in negative_indices, segmented by neg_lod).
    LoD-free dense contract: lengths vectors replace the reference's LoD.
    """
    input = as_tensor(input)
    mi = as_tensor(matched_indices, ref=input)
    neg = None if negative_indices is None \
        else as_tensor(negative_indices, ref=input)
    B = int(mi.shape[0])
    R = int(input.shape[0])
    if input_lod is not None:
        counts = np.asarray(input_lod, np.int64).reshape(-1)
        assert counts.sum() == R and len(counts) == B
        offsets_np = np.concatenate([[0], np.cumsum(counts)[:-1]])
    else:
        assert R % B == 0, "packed rows must divide batch; pass input_lod"
        offsets_np = np.arange(B) * (R // B)
    if neg is not None:
        if neg_lod is None and B > 1:
            raise ValueError(
                "target_assign: negative_indices with batch > 1 needs "
                "neg_lod (per-image counts) — without it every index "
                "would silently land in image 0")
        nl = (np.asarray(neg_lod, np.int64).reshape(-1)
              if neg_lod is not None
              else np.asarray([int(neg.shape[0])]))
        seg_np = np.repeat(np.arange(len(nl)), nl).astype(np.int32)

    def fn(inp, m, *rest):
        P = m.shape[1]
        K = inp.shape[-1]
        offsets = jnp.asarray(offsets_np, jnp.int32)
        matched = m >= 0
        rows = jnp.clip(m, 0, None).astype(jnp.int32) + offsets[:, None]
        gathered = inp[rows.reshape(-1),
                       jnp.tile(jnp.arange(P), B), :].reshape(B, P, K)
        out = jnp.where(matched[..., None], gathered,
                        jnp.asarray(mismatch_value, inp.dtype))
        w = matched.astype(jnp.float32)
        if rest:
            nidx = rest[0].reshape(-1).astype(jnp.int32)
            w = w.at[jnp.asarray(seg_np), nidx].set(1.0)
        return out, w[..., None]

    tens = [input, mi] + ([neg] if neg is not None else [])
    return run_op('target_assign', fn, tens, n_nondiff=len(tens) - 1)


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip=4.135, name=None):
    """box_decoder_and_assign_op.cc (oracle:
    test_box_decoder_and_assign_op.py): decode per-class deltas
    [R, C*4] against priors [R, 4] (+1-width convention), then per row
    pick the highest-scoring NON-background class's box.
    Returns (decoded_box [R, C*4], output_assign_box [R, 4])."""
    prior_box = as_tensor(prior_box)
    target_box = as_tensor(target_box, ref=prior_box)
    var = as_tensor(prior_box_var, ref=prior_box)
    score = as_tensor(box_score, ref=prior_box)

    def fn(p, v, t, s):
        w = p[:, 2] - p[:, 0] + 1.0
        h = p[:, 3] - p[:, 1] + 1.0
        cx = p[:, 0] + 0.5 * w
        cy = p[:, 1] + 0.5 * h
        R, C4 = t.shape
        C = C4 // 4
        d = t.reshape(R, C, 4) * v.reshape(-1)[None, None, :]
        dx, dy = d[..., 0], d[..., 1]
        dw = jnp.minimum(d[..., 2], box_clip)
        dh = jnp.minimum(d[..., 3], box_clip)
        pcx = dx * w[:, None] + cx[:, None]
        pcy = dy * h[:, None] + cy[:, None]
        pw = jnp.exp(dw) * w[:, None]
        ph = jnp.exp(dh) * h[:, None]
        boxes = jnp.stack([pcx - 0.5 * pw, pcy - 0.5 * ph,
                           pcx + 0.5 * pw - 1, pcy + 0.5 * ph - 1],
                          axis=-1)                       # [R, C, 4]
        # argmax score, never class 0 (background)
        order = jnp.argsort(-s, axis=1)
        best = jnp.where(order[:, 0] == 0, order[:, 1], order[:, 0])
        assign = jnp.take_along_axis(
            boxes, best[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        return boxes.reshape(R, C4), assign
    return run_op('box_decoder_and_assign', fn,
                  [prior_box, var, target_box, score], n_nondiff=3)


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, rois_num=None, name=None):
    """prroi_pool_op.cc — Precise RoI pooling (oracle:
    test_prroi_pool_op.py PyPrRoIPool): the EXACT integral of the
    bilinearly-interpolated feature over each continuous bin, divided by
    bin area (no sampling-point approximation).

    TPU-native closed form: bilinear interp is separable —
    f(u, v) = Σ_ij F[j, i] hat(u-i) hat(v-j) — so the bin integral is
    Wy @ F @ Wx^T with 1-D hat-integral weight vectors per bin:
    W[b, i] = G(hi - i) - G(lo - i), G the triangular-kernel CDF. One
    einsum per roi, fully differentiable through `input`.

    rois: [R, 4] (x1, y1, x2, y2) + rois_num [B] per-image counts
    (paddle-2.x dense contract; the reference takes LoD)."""
    input = as_tensor(input)
    rois = as_tensor(rois, ref=input)
    if rois_num is None:
        batch_idx = np.zeros((int(rois.shape[0]),), np.int32)
    else:
        rn = np.asarray(as_tensor(rois_num).data).reshape(-1)
        batch_idx = np.repeat(np.arange(len(rn)), rn).astype(np.int32)

    ph, pw = int(pooled_height), int(pooled_width)

    def fn(x, r):
        N, C, H, W = x.shape

        def hat_cdf(t):
            t = jnp.clip(t, -1.0, 1.0)
            neg = 0.5 * (t + 1.0) ** 2
            pos = 0.5 + t - 0.5 * t * t
            return jnp.where(t <= 0, neg, pos)

        def weights(lo, hi, n, bins):
            # [bins, n] hat-integral of pixel i over each bin
            edges = lo + (hi - lo) * jnp.arange(bins + 1) / bins
            i = jnp.arange(n, dtype=x.dtype)
            cdf = hat_cdf(edges[:, None] - i[None, :])   # [bins+1, n]
            return cdf[1:] - cdf[:-1]

        def one(roi, b):
            x1, y1, x2, y2 = (roi * spatial_scale)
            wx = weights(x1, x2, W, pw)                  # [pw, W]
            wy = weights(y1, y2, H, ph)                  # [ph, H]
            area = jnp.maximum((x2 - x1) / pw, 1e-9) * \
                jnp.maximum((y2 - y1) / ph, 1e-9)
            feat = x[b]                                  # [C, H, W]
            out = jnp.einsum('hH,cHW,wW->chw', wy, feat, wx)
            return out / area
        return jax.vmap(one)(r, jnp.asarray(batch_idx))
    return run_op('prroi_pool', fn, [input, rois], n_nondiff=1)


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """retinanet_detection_output_op.cc (oracle:
    test_retinanet_detection_output.py): per-FPN-level top-k + anchor
    decode (+1-width RetinaNet convention, clipped to the rescaled
    image), then class-wise NMS and global keep_top_k.

    Fixed-shape TPU form: each level keeps its nms_top_k candidates by
    score-masking instead of dynamic filtering; the cross-level merge is
    one concatenated padded NMS. Output rows (label, score, x1..y2),
    label 1-based, -1 past the valid count (+ count tensor), matching
    multiclass_nms's padded contract in place of LoD."""
    bboxes = [as_tensor(b) for b in bboxes]
    scores = [as_tensor(s) for s in scores]
    anchors = [as_tensor(a) for a in anchors]
    im_info = as_tensor(im_info)
    L = len(bboxes)
    K = int(keep_top_k)

    def fn(im, *flat):
        bl, sl, al = flat[:L], flat[L:2 * L], flat[2 * L:]
        C = sl[0].shape[-1]
        cand_b, cand_s, cand_c = [], [], []
        im_h, im_w, im_scale = im[0], im[1], im[2]
        for lvl in range(L):
            sc = sl[lvl].reshape(-1)                     # [A*C]
            bb = bl[lvl].reshape(-1, 4)                  # [A, 4]
            an = al[lvl].reshape(-1, 4)
            thresh = score_threshold if lvl < L - 1 else 0.0
            sc = jnp.where(sc > thresh, sc, -jnp.inf)
            k = min(int(nms_top_k), sc.shape[0]) if nms_top_k > -1 \
                else sc.shape[0]
            top, arg = lax.top_k(sc, k)
            a_id = arg // C
            cls = arg % C
            aw = an[a_id, 2] - an[a_id, 0] + 1
            ah = an[a_id, 3] - an[a_id, 1] + 1
            acx = an[a_id, 0] + aw / 2
            acy = an[a_id, 1] + ah / 2
            d = bb[a_id]
            cx = d[:, 0] * aw + acx
            cy = d[:, 1] * ah + acy
            w = jnp.exp(d[:, 2]) * aw
            h = jnp.exp(d[:, 3]) * ah
            box = jnp.stack([cx - w / 2, cy - h / 2,
                             cx + w / 2 - 1, cy + h / 2 - 1], -1)
            box = box / im_scale
            lim_x = jnp.round(im_w / im_scale) - 1
            lim_y = jnp.round(im_h / im_scale) - 1
            box = jnp.stack([
                jnp.clip(box[:, 0], 0, lim_x),
                jnp.clip(box[:, 1], 0, lim_y),
                jnp.clip(box[:, 2], 0, lim_x),
                jnp.clip(box[:, 3], 0, lim_y)], -1)
            cand_b.append(box)
            cand_s.append(top)
            cand_c.append(cls)
        boxes = jnp.concatenate(cand_b)                  # [M, 4]
        scs = jnp.concatenate(cand_s)
        cls = jnp.concatenate(cand_c)
        C_num = C

        # class-wise NMS over the merged candidates
        def per_class(c):
            s_c = jnp.where((cls == c) & (scs > -jnp.inf), scs, -jnp.inf)
            keep = _greedy_nms_mask(boxes, s_c, nms_threshold,
                                    normalized=False, eta=nms_eta)
            return jnp.where(keep & (s_c > -jnp.inf), s_c, -jnp.inf)
        kept = jax.vmap(per_class)(jnp.arange(C_num))    # [C, M]
        flat = kept.reshape(-1)
        top, arg = lax.top_k(flat, min(K, flat.shape[0]))
        c_id = (arg // boxes.shape[0]).astype(jnp.float32)
        b_id = arg % boxes.shape[0]
        valid = top > -jnp.inf
        rows = jnp.concatenate([
            jnp.where(valid, c_id + 1.0, -1.0)[:, None],
            jnp.where(valid, top, 0.0)[:, None],
            jnp.where(valid[:, None], boxes[b_id], 0.0)], axis=1)
        return rows, jnp.sum(valid).astype(jnp.int32)

    tens = [im_info] + bboxes + scores + anchors
    return run_op('retinanet_detection_output', fn, tens,
                  n_nondiff=len(tens))


def locality_aware_nms(bboxes, scores, score_threshold, nms_threshold,
                       keep_top_k, nms_eta=1.0, name=None):
    """locality_aware_nms_op.cc (EAST text detection): first a
    locality-aware pass — consecutive boxes whose IOU exceeds the
    threshold merge by score-weighted average (scores add) — then
    standard class-0 greedy NMS + keep_top_k.

    The merge pass is inherently sequential (each box merges into the
    running candidate); it compiles to one `lax.scan` over the M boxes.
    bboxes [N, M, 4], scores [N, 1, M] → padded (label, score, x1..y2)
    rows + count, like multiclass_nms."""
    bboxes = as_tensor(bboxes)
    scores = as_tensor(scores, ref=bboxes)
    K = int(keep_top_k)

    def fn(bb, sc):
        def one(boxes, s):
            s = s.reshape(-1)
            M = boxes.shape[0]

            def iou_pair(a, b):
                # +1 pixel convention, matching the normalized=False
                # greedy pass below — one convention for both passes
                lt = jnp.maximum(a[:2], b[:2])
                rb = jnp.minimum(a[2:], b[2:])
                wh = jnp.maximum(rb - lt + 1.0, 0.0)
                inter = wh[0] * wh[1]
                ar_a = jnp.maximum(a[2] - a[0] + 1.0, 0) * \
                    jnp.maximum(a[3] - a[1] + 1.0, 0)
                ar_b = jnp.maximum(b[2] - b[0] + 1.0, 0) * \
                    jnp.maximum(b[3] - b[1] + 1.0, 0)
                return inter / jnp.maximum(ar_a + ar_b - inter, 1e-9)

            # locality-aware merge scan: carry = (current box, score,
            # out boxes, out scores, write cursor)
            out_b0 = jnp.zeros((M, 4), boxes.dtype)
            out_s0 = jnp.full((M,), -jnp.inf, s.dtype)

            def body(carry, i):
                cur_b, cur_s, ob, os_, ptr = carry
                b, sv = boxes[i], s[i]
                first = cur_s == -jnp.inf
                mergeable = (~first) & (iou_pair(cur_b, b)
                                        > nms_threshold)
                tot = cur_s + sv
                merged = (cur_b * cur_s + b * sv) / jnp.maximum(tot,
                                                                1e-9)
                # flush current candidate when not merging
                ob = jnp.where(mergeable | first, ob,
                               ob.at[ptr].set(cur_b))
                os_ = jnp.where(mergeable | first, os_,
                                os_.at[ptr].set(cur_s))
                ptr = jnp.where(mergeable | first, ptr, ptr + 1)
                cur_b = jnp.where(mergeable, merged, b)
                cur_s = jnp.where(mergeable, tot, sv)
                return (cur_b, cur_s, ob, os_, ptr), None

            (cur_b, cur_s, ob, os_, ptr), _ = lax.scan(
                body, (jnp.zeros((4,), boxes.dtype),
                       jnp.asarray(-jnp.inf, s.dtype),
                       out_b0, out_s0, jnp.asarray(0, jnp.int32)),
                jnp.arange(M))
            ob = ob.at[ptr].set(cur_b)                  # flush the tail
            os_ = os_.at[ptr].set(jnp.where(cur_s == -jnp.inf,
                                            -jnp.inf, cur_s))
            keep = _greedy_nms_mask(ob, os_, nms_threshold,
                                    normalized=False,
                                    score_threshold=score_threshold,
                                    eta=nms_eta)
            final = jnp.where(keep & (os_ > -jnp.inf), os_, -jnp.inf)
            top, arg = lax.top_k(final, min(K, M))
            valid = top > -jnp.inf
            rows = jnp.concatenate([
                jnp.where(valid, 0.0, -1.0)[:, None],
                jnp.where(valid, top, 0.0)[:, None],
                jnp.where(valid[:, None], ob[arg], 0.0)], axis=1)
            return rows, jnp.sum(valid).astype(jnp.int32)
        return jax.vmap(one)(bb, sc)
    return run_op('locality_aware_nms', fn, [bboxes, scores],
                  n_nondiff=2)


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """fluid.layers.detection_output (SSD post-process): box_coder
    decode_center_size against the priors, then multiclass_nms.
    loc [N, P, 4], scores [N, P, C] (post-softmax), prior_box [P, 4].
    Returns the multiclass_nms padded triple."""
    loc = as_tensor(loc)
    scores = as_tensor(scores, ref=loc)
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type='decode_center_size', axis=0)
    # the reference layer softmaxes the raw conf logits itself
    # (fluid/layers/detection.py detection_output: nn.softmax(scores))
    from ..ops.nn_ops import softmax as _softmax
    from ..ops.manip import transpose
    sc = transpose(_softmax(scores, axis=-1), [0, 2, 1])
    return multiclass_nms(decoded, sc,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold, normalized=False,
                          nms_eta=nms_eta,
                          background_label=background_label)


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None, scale_x_y=1.0):
    """yolov3_loss_op.cc (oracle: test_yolov3_loss_op.py YOLOv3Loss).

    x [N, A*(5+C), H, W] raw head output, gt_box [N, B, 4] normalized
    xywh, gt_label [N, B], optional gt_score [N, B] (mixup weights).

    TPU-native: the per-gt python loops become a `lax.scan` over the B
    gt slots (sequential to preserve the reference's last-writer-wins
    objectness assignment for duplicate cells) with everything inside
    vectorized over the batch; the coordinate/class/objectness terms use
    stable logits-space BCE. Returns (loss [N], objectness_mask
    [N, A, H, W], gt_match_mask [N, B])."""
    x = as_tensor(x)
    gt_box = as_tensor(gt_box, ref=x)
    gt_label = as_tensor(gt_label, ref=x)
    gt_score_t = None if gt_score is None else as_tensor(gt_score, ref=x)
    anchors_l = [float(a) for a in anchors]
    mask = [int(m) for m in anchor_mask]
    C = int(class_num)
    an_num = len(anchors_l) // 2
    mask_num = len(mask)

    def bce(logit, label):
        # -label*log(sig) - (1-label)*log(1-sig), stable
        return jnp.maximum(logit, 0) - logit * label + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    def xywh_iou(a, b):
        # a [.., 4], b [.., 4] center-size, broadcastable
        al, ar = a[..., 0] - a[..., 2] / 2, a[..., 0] + a[..., 2] / 2
        at, ab = a[..., 1] - a[..., 3] / 2, a[..., 1] + a[..., 3] / 2
        bl, br = b[..., 0] - b[..., 2] / 2, b[..., 0] + b[..., 2] / 2
        bt, bb = b[..., 1] - b[..., 3] / 2, b[..., 1] + b[..., 3] / 2
        iw = jnp.clip(jnp.minimum(ar, br) - jnp.maximum(al, bl), 0., 1.)
        ih = jnp.clip(jnp.minimum(ab, bb) - jnp.maximum(at, bt), 0., 1.)
        inter = iw * ih
        union = (ar - al) * (ab - at) + (br - bl) * (bb - bt) - inter
        return inter / jnp.maximum(union, 1e-10)

    def fn(xv, gb, gl, *rest):
        N, _, H, W = xv.shape
        Bc = gb.shape[1]
        gs = rest[0] if rest else jnp.ones((N, Bc), xv.dtype)
        input_size = downsample_ratio * H
        xr = xv.reshape(N, mask_num, 5 + C, H, W) \
            .transpose(0, 1, 3, 4, 2)                # [N, A, H, W, 5+C]
        bias_xy = -0.5 * (scale_x_y - 1.0)

        smooth_w = min(1.0 / C, 1.0 / 40)
        pos_l = 1.0 - smooth_w if use_label_smooth else 1.0
        neg_l = smooth_w if use_label_smooth else 0.0

        # decoded pred boxes for the ignore mask
        grid_x = jnp.broadcast_to(jnp.arange(W), (H, W))
        grid_y = jnp.broadcast_to(jnp.arange(H)[:, None], (H, W))
        m_anch = jnp.asarray(
            [[anchors_l[2 * m] / input_size,
              anchors_l[2 * m + 1] / input_size] for m in mask], xv.dtype)
        px = (grid_x + jax.nn.sigmoid(xr[..., 0]) * scale_x_y
              + bias_xy) / W
        py = (grid_y + jax.nn.sigmoid(xr[..., 1]) * scale_x_y
              + bias_xy) / H
        pw = jnp.exp(xr[..., 2]) * m_anch[:, 0][None, :, None, None]
        phh = jnp.exp(xr[..., 3]) * m_anch[:, 1][None, :, None, None]
        pred_box = jnp.stack([px, py, pw, phh], -1).reshape(N, -1, 4)
        pred_obj = xr[..., 4].reshape(N, -1)         # [N, A*H*W]

        ious = xywh_iou(pred_box[:, :, None, :], gb[:, None, :, :])
        ious_max = ious.max(-1)                      # [N, A*H*W]
        objness0 = jnp.where(ious_max > ignore_thresh, -1.0, 0.0)

        # gt -> anchor shape matching over ALL an_num anchors
        all_anch = jnp.asarray(
            [[0., 0., anchors_l[2 * i] / input_size,
              anchors_l[2 * i + 1] / input_size]
             for i in range(an_num)], xv.dtype)      # [an_num, 4]
        g_shift = gb.at[..., 0].set(0.).at[..., 1].set(0.)
        sh_iou = xywh_iou(g_shift[:, :, None, :],
                          all_anch[None, None, :, :])  # [N, B, an_num]
        best = jnp.argmax(sh_iou, -1)                # [N, B]
        in_mask = jnp.zeros((an_num,), bool)
        an_idx_of = jnp.zeros((an_num,), jnp.int32)
        for k, m in enumerate(mask):
            in_mask = in_mask.at[m].set(True)
            an_idx_of = an_idx_of.at[m].set(k)
        has_box = gb[..., 2] * gb[..., 3] > 0        # w*h > 0
        valid = has_box & in_mask[best]
        an_idx = an_idx_of[best]                     # [N, B]
        gmatch = jnp.where(valid, an_idx, -1).astype(jnp.int32)

        gi = jnp.clip((gb[..., 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gb[..., 1] * H).astype(jnp.int32), 0, H - 1)
        tx = gb[..., 0] * W - gi
        ty = gb[..., 1] * H - gj
        aw = m_anch[:, 0][an_idx]                    # matched anchor w/h
        ah = m_anch[:, 1][an_idx]
        tw = jnp.log(jnp.maximum(gb[..., 2], 1e-10) / aw)
        th = jnp.log(jnp.maximum(gb[..., 3], 1e-10) / ah)
        box_scale = (2.0 - gb[..., 2] * gb[..., 3]) * gs

        bidx = jnp.arange(N)
        cell = lambda f, a_i, j_i, i_i: f[bidx, a_i, j_i, i_i]

        # per-gt coordinate + class loss, scan preserves write order of
        # the objectness assignment (last writer wins, like the oracle)
        def gt_step(carry, t):
            loss, obj = carry
            a_i, j_i, i_i = an_idx[:, t], gj[:, t], gi[:, t]
            v = valid[:, t]
            sc = box_scale[:, t]
            lx = bce(cell(xr[..., 0], a_i, j_i, i_i), tx[:, t]) * sc
            ly = bce(cell(xr[..., 1], a_i, j_i, i_i), ty[:, t]) * sc
            lw = jnp.abs(cell(xr[..., 2], a_i, j_i, i_i) - tw[:, t]) * sc
            lh = jnp.abs(cell(xr[..., 3], a_i, j_i, i_i) - th[:, t]) * sc
            cls_logits = xr[bidx, a_i, j_i, i_i, 5:]  # [N, C]
            tgt = jnp.where(
                jnp.arange(C)[None, :] == gl[:, t][:, None].astype(
                    jnp.int32), pos_l, neg_l)
            lc = (bce(cls_logits, tgt).sum(-1)) * gs[:, t]
            loss = loss + jnp.where(v, lx + ly + lw + lh + lc, 0.0)
            flat = (a_i * H + j_i) * W + i_i
            obj = jnp.where(
                jnp.zeros_like(obj, bool).at[bidx, flat].set(True)
                & v[:, None], gs[:, t][:, None], obj)
            return (loss, obj), None

        (loss, objness), _ = lax.scan(
            gt_step, (jnp.zeros((N,), xv.dtype), objness0),
            jnp.arange(Bc))

        obj_pos = jnp.where(objness > 0,
                            bce(pred_obj, 1.0) * objness, 0.0)
        obj_neg = jnp.where(objness == 0, bce(pred_obj, 0.0), 0.0)
        loss = loss + (obj_pos + obj_neg).sum(-1)
        return loss, objness.reshape(N, mask_num, H, W), gmatch

    tens = [x, gt_box, gt_label] + \
        ([gt_score_t] if gt_score_t is not None else [])
    return run_op('yolov3_loss', fn, tens, n_nondiff=len(tens) - 1)


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1,
                           position_sensitive=False, rois_num=None,
                           name=None):
    """deformable_psroi_pooling_op.cc (oracle:
    test_deformable_psroi_pooling.py): each output bin averages
    `sample_per_part`^2 bilinear samples, shifted by the learned
    per-part (trans_y, trans_x) offsets; position_sensitive maps output
    channel + group cell to an input channel (R-FCN style).

    TPU-native: the reference's per-(roi, channel, bin, sample) scalar
    loop is one vectorized gather — samples out of bounds contribute 0
    and are excluded from the average via a mask count. Differentiable
    through `input` and `trans`.

    rois [R, 4] + rois_num [B] (dense batch mapping; reference uses
    LoD); the +1/round box snapping matches the kernel."""
    input = as_tensor(input)
    rois = as_tensor(rois, ref=input)
    trans = as_tensor(trans, ref=input)
    if rois_num is None:
        batch_idx_np = np.zeros((int(rois.shape[0]),), np.int32)
    else:
        rn = np.asarray(as_tensor(rois_num).data).reshape(-1)
        batch_idx_np = np.repeat(np.arange(len(rn)), rn).astype(np.int32)
    ph, pw = int(pooled_height), int(pooled_width)
    gh, gw = (int(group_size[0]), int(group_size[1]))
    if part_size is None:
        part_size = (ph, pw)
    part_h, part_w = int(part_size[0]), int(part_size[1])
    sp = int(sample_per_part)

    def fn(x, r, tr):
        N, C, H, W = x.shape
        out_C = C // (gh * gw) if position_sensitive else C

        def bilinear(img, yy, xx):
            # img [H, W]; sample at clamped (yy, xx) with corner masking
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            ly, lx = yy - y0, xx - x0
            y0i = y0.astype(jnp.int32)
            x0i = x0.astype(jnp.int32)

            def at(yi, xi):
                ok = (yi >= 0) & (yi <= H - 1) & (xi >= 0) & (xi <= W - 1)
                v = img[jnp.clip(yi, 0, H - 1), jnp.clip(xi, 0, W - 1)]
                return jnp.where(ok, v, 0.0)
            return ((1 - ly) * (1 - lx) * at(y0i, x0i)
                    + (1 - ly) * lx * at(y0i, x0i + 1)
                    + ly * (1 - lx) * at(y0i + 1, x0i)
                    + ly * lx * at(y0i + 1, x0i + 1))

        def one(roi, b, tr_r):
            x1 = jnp.round(roi[0]) * spatial_scale - 0.5
            y1 = jnp.round(roi[1]) * spatial_scale - 0.5
            x2 = jnp.round(roi[2] + 1) * spatial_scale - 0.5
            y2 = jnp.round(roi[3] + 1) * spatial_scale - 0.5
            rw = jnp.maximum(x2 - x1, 0.1)
            rh = jnp.maximum(y2 - y1, 0.1)
            bin_w, bin_h = rw / pw, rh / ph
            sub_w, sub_h = bin_w / sp, bin_h / sp

            p_h = jnp.arange(ph)
            p_w = jnp.arange(pw)
            # part cell + learned offset per bin
            prt_h = (p_h * part_h // ph)[:, None]        # [ph, 1]
            prt_w = (p_w * part_w // pw)[None, :]        # [1, pw]
            if no_trans:
                tx = jnp.zeros((ph, pw), x.dtype)
                ty = jnp.zeros((ph, pw), x.dtype)
            else:
                tx = tr_r[0][prt_h, prt_w] * trans_std   # [ph, pw]
                ty = tr_r[1][prt_h, prt_w] * trans_std
            wstart = p_w[None, :] * bin_w + x1 + tx * rw
            hstart = p_h[:, None] * bin_h + y1 + ty * rh

            s = jnp.arange(sp)
            xs = jnp.broadcast_to(
                wstart[..., None, None] + s[None, None, None, :] * sub_w,
                (ph, pw, sp, sp))
            ys = jnp.broadcast_to(
                hstart[..., None, None] + s[None, None, :, None] * sub_h,
                (ph, pw, sp, sp))
            inb = (xs >= -0.5) & (xs <= W - 0.5) & \
                (ys >= -0.5) & (ys <= H - 0.5)           # [ph, pw, sp, sp]
            xs_c = jnp.clip(xs, 0.0, W - 1.0)
            ys_c = jnp.clip(ys, 0.0, H - 1.0)

            # channel per (out_c, bin): position-sensitive group mapping
            g_w = jnp.clip(p_w * gh // ph, 0, gh - 1)    # oracle's floor
            g_h = jnp.clip(p_h * gw // pw, 0, gw - 1)
            if position_sensitive:
                c_in = ((jnp.arange(out_C)[:, None, None] * gh
                         + g_h[None, :, None]) * gw
                        + g_w[None, None, :])            # [oC, ph, pw]
            else:
                c_in = jnp.broadcast_to(
                    jnp.arange(out_C)[:, None, None], (out_C, ph, pw))

            def per_chan(c_map):
                def per_bin(i, j):
                    img = x[b, c_map[i, j]]
                    vals = jax.vmap(jax.vmap(
                        lambda yy, xx: bilinear(img, yy, xx)))(
                            ys_c[i, j], xs_c[i, j])
                    m = inb[i, j]
                    cnt = m.sum()
                    return jnp.where(
                        cnt > 0, (vals * m).sum() / jnp.maximum(cnt, 1),
                        0.0)
                return jax.vmap(lambda i: jax.vmap(
                    lambda j: per_bin(i, j))(jnp.arange(pw)))(
                        jnp.arange(ph))
            return jax.vmap(per_chan)(c_in)              # [oC, ph, pw]
        return jax.vmap(one)(r, jnp.asarray(batch_idx_np), tr)
    return run_op('deformable_roi_pooling', fn, [input, rois, trans],
                  n_nondiff=0 if not no_trans else 1)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0,
             overlap_threshold=0.5, neg_pos_ratio=3.0, neg_overlap=0.5,
             loc_loss_weight=1.0, conf_loss_weight=1.0,
             match_type='per_prediction', mining_type='max_negative',
             normalize=True, sample_size=None, gt_valid=None, name=None):
    """fluid.layers.ssd_loss (fluid/layers/detection.py:1070 pipeline):
    bipartite/per-prediction matching, conf softmax loss with
    max-negative hard mining at neg_pos_ratio, smooth-L1 on
    center-size-encoded localization deltas, normalized by matched
    count.

    Dense LoD-free contract: gt_box [N, G, 4] / gt_label [N, G] padded;
    `gt_valid` [N, G] bool (default: nonzero-area boxes). Returns the
    [N, P, 1] weighted per-prior loss like the reference (so callers
    reduce it themselves)."""
    location = as_tensor(location)
    confidence = as_tensor(confidence, ref=location)
    gt_box = as_tensor(gt_box, ref=location)
    gt_label = as_tensor(gt_label, ref=location)
    prior_box = as_tensor(prior_box, ref=location)
    var = prior_box_var
    variance = [0.1, 0.1, 0.2, 0.2] if var is None else None
    if var is not None:
        var = as_tensor(var, ref=location)

    def fn(loc, conf, gb, gl, pb, *rest):
        N, P, _ = loc.shape
        G = gb.shape[1]
        C = conf.shape[-1]
        pv = rest[0] if rest else None
        valid = (gb[..., 2] - gb[..., 0]) * (gb[..., 3] - gb[..., 1]) > 0 \
            if gt_valid is None else jnp.asarray(gt_valid)

        # [N, G, P] IOU (shared normalized-coordinate helper),
        # invalid gt rows zeroed
        iou = jax.vmap(lambda g: _iou_matrix(g, pb, normalized=True))(gb)
        iou = jnp.where(valid[..., None], iou, 0.0)

        midx, mdist = jax.vmap(_bipartite_match_single)(iou)
        if match_type == 'per_prediction':
            best_row = jnp.argmax(iou, axis=1).astype(jnp.int32)
            best = jnp.max(iou, axis=1)
            fill = (midx == -1) & (best >= overlap_threshold)
            midx = jnp.where(fill, best_row, midx)
            mdist = jnp.where(fill, best, mdist)
        matched = midx >= 0                                # [N, P]
        mclip = jnp.clip(midx, 0, G - 1)

        # conf loss per prior vs target label (background at unmatched)
        tgt_label = jnp.where(
            matched,
            jnp.take_along_axis(gl.astype(jnp.int32), mclip, axis=1),
            background_label)
        logp = jax.nn.log_softmax(conf, axis=-1)
        conf_l = -jnp.take_along_axis(
            logp, tgt_label[..., None], axis=-1)[..., 0]   # [N, P]

        # hard negative mining (max_negative): per image take
        # neg_pos_ratio * num_pos negatives with highest conf loss among
        # priors whose match overlap < neg_overlap
        num_pos = matched.sum(-1)                          # [N]
        neg_cand = (~matched) & (mdist < neg_overlap)
        neg_scores = jnp.where(neg_cand, conf_l, -jnp.inf)
        order = jnp.argsort(-neg_scores, axis=-1)
        rank = jnp.argsort(order, axis=-1)                 # rank per prior
        n_neg = jnp.minimum(
            (neg_pos_ratio * num_pos).astype(jnp.int32)
            if sample_size is None
            else jnp.full_like(num_pos, int(sample_size)),
            neg_cand.sum(-1))
        neg_sel = neg_cand & (rank < n_neg[:, None])
        conf_w = matched.astype(loc.dtype) + neg_sel.astype(loc.dtype)

        # localization smooth-L1 against encoded deltas at matched priors
        gmat = jnp.take_along_axis(
            gb, mclip[..., None].astype(jnp.int32), axis=1)  # [N, P, 4]
        pw_ = pb[:, 2] - pb[:, 0]
        ph_ = pb[:, 3] - pb[:, 1]
        pcx = (pb[:, 0] + pb[:, 2]) / 2
        pcy = (pb[:, 1] + pb[:, 3]) / 2
        gw = gmat[..., 2] - gmat[..., 0]
        gh = gmat[..., 3] - gmat[..., 1]
        gcx = (gmat[..., 0] + gmat[..., 2]) / 2
        gcy = (gmat[..., 1] + gmat[..., 3]) / 2
        if pv is not None:
            v0, v1, v2, v3 = (pv[:, 0], pv[:, 1], pv[:, 2], pv[:, 3])
        else:
            v0, v1, v2, v3 = variance
        enc = jnp.stack([
            (gcx - pcx[None, :]) / pw_[None, :] / v0,
            (gcy - pcy[None, :]) / ph_[None, :] / v1,
            jnp.log(jnp.maximum(gw / pw_[None, :], 1e-10)) / v2,
            jnp.log(jnp.maximum(gh / ph_[None, :], 1e-10)) / v3], -1)
        diff = loc - enc
        ad = jnp.abs(diff)
        sl1 = jnp.where(ad < 1.0, 0.5 * ad * ad, ad - 0.5).sum(-1)
        loc_l = sl1 * matched.astype(loc.dtype)            # [N, P]

        total = conf_loss_weight * conf_l * conf_w \
            + loc_loss_weight * loc_l
        if normalize:
            denom = jnp.maximum(num_pos.sum().astype(loc.dtype), 1.0)
            total = total / denom
        return total[..., None]

    tens = [location, confidence, gt_box, gt_label, prior_box] + \
        ([var] if var is not None else [])
    return run_op('ssd_loss', fn, tens, n_nondiff=len(tens) - 2)


# ---------------------------------------------------------------------------
# label-generation ops (host-side data prep, wave 2b)
# ---------------------------------------------------------------------------

def _np_overlaps(a, b):
    """+1-convention IOU matrix (oracle _bbox_overlaps)."""
    w1 = np.maximum(a[:, 2] - a[:, 0] + 1, 0)
    h1 = np.maximum(a[:, 3] - a[:, 1] + 1, 0)
    w2 = np.maximum(b[:, 2] - b[:, 0] + 1, 0)
    h2 = np.maximum(b[:, 3] - b[:, 1] + 1, 0)
    area1 = w1 * h1
    area2 = w2 * h2
    ix = np.maximum(
        np.minimum(a[:, None, 2], b[None, :, 2])
        - np.maximum(a[:, None, 0], b[None, :, 0]) + 1, 0)
    iy = np.maximum(
        np.minimum(a[:, None, 3], b[None, :, 3])
        - np.maximum(a[:, None, 1], b[None, :, 1]) + 1, 0)
    inter = ix * iy
    return inter / np.maximum(area1[:, None] + area2[None, :] - inter,
                              1e-10)


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True,
                      gt_num=None):
    """rpn_target_assign_op.cc (oracle: test_rpn_target_assign_op.py
    rpn_target_assign): sample an RPN minibatch per image — anchors with
    max-overlap-per-gt or IOU >= positive_overlap become foreground
    (capped at fg_fraction * batch_size, random subsample), anchors with
    IOU < negative_overlap fill the background quota.

    Host-side data-prep op (sampling + data-dependent sizes — same
    disposition as the recsys tier): returns
    (predicted_scores [S, 1], predicted_location [L, 4],
     target_label [S, 1], target_bbox [L, 4],
     bbox_inside_weight [L, 4]) gathered over the batch, with anchor
    indices offset per image. gt_boxes [N, G, 4] dense (+ optional
    gt_num lengths); straddle filtering needs im_info [N, 3]."""
    from ..ops.recsys import _host_only
    _host_only('rpn_target_assign')
    bp = np.asarray(as_tensor(bbox_pred).data)     # [N, A, 4]
    cl = np.asarray(as_tensor(cls_logits).data)    # [N, A, 1]
    an = np.asarray(as_tensor(anchor_box).data)    # [A, 4]
    gbs = np.asarray(as_tensor(gt_boxes).data)     # [N, G, 4]
    N, A = bp.shape[0], an.shape[0]
    gn = (np.asarray(as_tensor(gt_num).data).reshape(-1).astype(int)
          if gt_num is not None else None)
    im = (np.asarray(as_tensor(im_info).data)
          if im_info is not None else None)
    crowd_all = (np.asarray(as_tensor(is_crowd).data)
                 if is_crowd is not None else None)

    scores, locs, labels, tboxes, inw = [], [], [], [], []
    for b in range(N):
        g = gbs[b][:gn[b]] if gn is not None else gbs[b]
        keep = (g[:, 2] - g[:, 0]) * (g[:, 3] - g[:, 1]) > 0
        if crowd_all is not None:
            # crowd regions are excluded from fg/bg assignment entirely
            cr = crowd_all[b].reshape(-1)[:len(g)].astype(bool)
            keep = keep & ~cr
        g = g[keep]
        if rpn_straddle_thresh >= 0 and im is not None:
            h, w = im[b, 0], im[b, 1]
            inside = np.where(
                (an[:, 0] >= -rpn_straddle_thresh)
                & (an[:, 1] >= -rpn_straddle_thresh)
                & (an[:, 2] < w + rpn_straddle_thresh)
                & (an[:, 3] < h + rpn_straddle_thresh))[0]
        else:
            inside = np.arange(A)
        iou = _np_overlaps(an[inside], g) if len(g) else \
            np.zeros((len(inside), 1))
        a2g = iou.argmax(1)
        a2g_max = iou.max(1) if len(g) else np.zeros(len(inside))
        g_max = iou.max(0) if len(g) else np.zeros(0)
        lab = -np.ones(len(inside), np.int32)
        if len(g):
            lab[np.where(iou == g_max)[0]] = 1
        lab[a2g_max >= rpn_positive_overlap] = 1
        num_fg = int(rpn_fg_fraction * rpn_batch_size_per_im)
        fg = np.where(lab == 1)[0]
        if len(fg) > num_fg:
            off = (np.random.choice(fg, len(fg) - num_fg, replace=False)
                   if use_random else fg[num_fg:])
            lab[off] = -1
        fg = np.where(lab == 1)[0]
        num_bg = rpn_batch_size_per_im - len(fg)
        bg = np.where(a2g_max < rpn_negative_overlap)[0]
        if len(bg) > num_bg:
            # with-replacement draw IS the reference behavior
            # (test_rpn_target_assign_op.py:63 uses np.random.randint)
            bg = (bg[np.random.randint(len(bg), size=num_bg)]
                  if use_random else bg[:num_bg])
        lab[bg] = np.where(lab[bg] == 1, lab[bg], 0)
        fg = np.where(lab == 1)[0]
        bgs = np.where(lab == 0)[0]
        loc_i = inside[fg]
        sc_i = inside[np.concatenate([fg, bgs])]
        scores.append(cl[b].reshape(A, -1)[sc_i])
        locs.append(bp[b][loc_i])
        labels.append(lab[np.concatenate([fg, bgs])][:, None])
        t = g[a2g[fg]] if len(g) else np.zeros((0, 4), an.dtype)
        tboxes.append(t)
        inw.append(np.ones((len(fg), 4), np.float32))

    import jax.numpy as _jnp
    return tuple(Tensor(_jnp.asarray(np.concatenate(x)))
                 for x in (scores, locs, labels, tboxes, inw))


def _box_to_delta(ex, gt, weights):
    """oracle _box_to_delta (+1 convention, weighted)."""
    ex_w = ex[:, 2] - ex[:, 0] + 1
    ex_h = ex[:, 3] - ex[:, 1] + 1
    ex_cx = ex[:, 0] + 0.5 * ex_w
    ex_cy = ex[:, 1] + 0.5 * ex_h
    gt_w = gt[:, 2] - gt[:, 0] + 1
    gt_h = gt[:, 3] - gt[:, 1] + 1
    gt_cx = gt[:, 0] + 0.5 * gt_w
    gt_cy = gt[:, 1] + 0.5 * gt_h
    dx = (gt_cx - ex_cx) / ex_w / weights[0]
    dy = (gt_cy - ex_cy) / ex_h / weights[1]
    dw = np.log(gt_w / ex_w) / weights[2]
    dh = np.log(gt_h / ex_h) / weights[3]
    return np.stack([dx, dy, dw, dh], 1)


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, rois_num=None,
                             gt_num=None):
    """generate_proposal_labels_op.cc (oracle:
    test_generate_proposal_labels_op.py _sample_rois): sample a Fast
    R-CNN head minibatch from proposals + gt — fg above fg_thresh (at
    most fg_fraction * batch), bg in [bg_thresh_lo, bg_thresh_hi),
    per-class expanded smooth-L1 targets.

    Host-side data-prep op (random subsampling, per-image variable
    counts). Dense contract: rpn_rois [R, 4] + rois_num [N], gt arrays
    [N, G, .] + gt_num. Returns (rois [S, 4], labels_int32 [S, 1],
    bbox_targets [S, 4C], bbox_inside_weights, bbox_outside_weights,
    lengths [N])."""
    from ..ops.recsys import _host_only
    _host_only('generate_proposal_labels')
    rois_all = np.asarray(as_tensor(rpn_rois).data)
    gcls = np.asarray(as_tensor(gt_classes).data)
    crowd = np.asarray(as_tensor(is_crowd).data)
    gbs = np.asarray(as_tensor(gt_boxes).data)
    im = np.asarray(as_tensor(im_info).data)
    N = gbs.shape[0]
    C = int(class_nums)
    rn = (np.asarray(as_tensor(rois_num).data).reshape(-1).astype(int)
          if rois_num is not None
          else np.full(N, len(rois_all) // N, int))
    gn = (np.asarray(as_tensor(gt_num).data).reshape(-1).astype(int)
          if gt_num is not None else np.full(N, gbs.shape[1], int))
    r_off = np.concatenate([[0], np.cumsum(rn)[:-1]])

    out_rois, out_lab, out_tgt, out_inw, out_onw, lens = \
        [], [], [], [], [], []
    for b in range(N):
        rois = rois_all[r_off[b]:r_off[b] + rn[b]]
        g = gbs[b][:gn[b]]
        gc = gcls[b].reshape(-1)[:gn[b]]
        cr = crowd[b].reshape(-1)[:gn[b]].astype(bool)
        im_scale = im[b, 2]
        boxes = np.vstack([g, rois / im_scale])
        gt_ov = np.zeros((len(boxes), C))
        b2g = np.zeros(len(boxes), np.int32)
        if len(g):
            ov = _np_overlaps(boxes, g)
            amax, omax = ov.argmax(1), ov.max(1)
            nz = np.where(omax > 0)[0]
            gt_ov[nz, gc[amax[nz]].astype(int)] = omax[nz]
            b2g[nz] = amax[nz]
            gt_ov[np.where(cr)[0]] = -1.0
        mo = gt_ov.max(1)
        mc = gt_ov.argmax(1)
        fg_per = int(np.round(fg_fraction * batch_size_per_im))
        fg = np.where(mo >= fg_thresh)[0]
        n_fg = min(fg_per, len(fg))
        if len(fg) > n_fg and use_random:
            fg = np.random.choice(fg, n_fg, replace=False)
        fg = fg[:n_fg]
        bg = np.where((mo < bg_thresh_hi) & (mo >= bg_thresh_lo))[0]
        n_bg = min(batch_size_per_im - n_fg, len(bg))
        if len(bg) > n_bg and use_random:
            bg = np.random.choice(bg, n_bg, replace=False)
        bg = bg[:n_bg]
        keep = np.append(fg, bg)
        lab = mc[keep]
        lab[n_fg:] = 0
        sb = boxes[keep]
        sg = g[b2g[keep]] if len(g) else np.zeros_like(sb)
        if len(g):
            sg[n_fg:] = g[0]
        deltas = _box_to_delta(sb, sg, bbox_reg_weights) \
            if len(g) else np.zeros_like(sb)
        tgt = np.zeros((len(keep), 4 * C), np.float32)
        inw = np.zeros_like(tgt)
        for i, l in enumerate(lab):
            if l > 0:
                c = 1 if is_cls_agnostic else int(l)
                tgt[i, 4 * c:4 * c + 4] = deltas[i]
                inw[i, 4 * c:4 * c + 4] = 1.0
        out_rois.append(sb * im_scale)
        out_lab.append(lab[:, None].astype(np.int32))
        out_tgt.append(tgt)
        out_inw.append(inw)
        out_onw.append((inw > 0).astype(np.float32))
        lens.append(len(keep))

    import jax.numpy as _jnp
    outs = [np.concatenate(x) for x in
            (out_rois, out_lab, out_tgt, out_inw, out_onw)]
    return tuple(Tensor(_jnp.asarray(o)) for o in outs) + \
        (Tensor(_jnp.asarray(np.asarray(lens, np.int32))),)


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution,
                         rois_num=None, gt_num=None):
    """generate_mask_labels_op.cc: build Mask R-CNN head targets — for
    each foreground roi, crop its matched instance mask and resize to
    resolution^2, expanded per class.

    Host-side data-prep op. Deviation from the reference's COCO polygon
    format: `gt_segms` takes dense binary masks [N, G, H, W] (polygon
    rasterization belongs to the dataset layer under this framework's
    zero-egress datasets). Returns (mask_rois [S, 4], roi_has_mask_int32
    [S, 1], mask_int32 [S, num_classes * resolution^2], lengths [N])."""
    from ..ops.recsys import _host_only
    _host_only('generate_mask_labels')
    im = np.asarray(as_tensor(im_info).data)
    gcls = np.asarray(as_tensor(gt_classes).data)
    segms = np.asarray(as_tensor(gt_segms).data)
    rois_all = np.asarray(as_tensor(rois).data)
    labs = np.asarray(as_tensor(labels_int32).data).reshape(-1)
    N = segms.shape[0]
    R = int(resolution)
    rn = (np.asarray(as_tensor(rois_num).data).reshape(-1).astype(int)
          if rois_num is not None
          else np.full(N, len(rois_all) // N, int))
    gn = (np.asarray(as_tensor(gt_num).data).reshape(-1).astype(int)
          if gt_num is not None else np.full(N, segms.shape[1], int))
    r_off = np.concatenate([[0], np.cumsum(rn)[:-1]])

    crowd_all = np.asarray(as_tensor(is_crowd).data)
    out_rois, out_has, out_mask, lens = [], [], [], []
    for b in range(N):
        rois_b = rois_all[r_off[b]:r_off[b] + rn[b]]
        labs_b = labs[r_off[b]:r_off[b] + rn[b]]
        g_masks = segms[b][:gn[b]]
        gc = gcls[b].reshape(-1)[:gn[b]].astype(int)
        cr = crowd_all[b].reshape(-1)[:gn[b]].astype(bool)
        im_scale = im[b, 2]
        fg = np.where(labs_b > 0)[0]
        if len(fg) == 0 or gn[b] == 0:
            lens.append(0)
            continue
        gt_boxes_b = []
        for m in g_masks:
            ys, xs = np.where(m > 0)
            if len(xs) == 0:
                gt_boxes_b.append([0, 0, 0, 0])
            else:
                gt_boxes_b.append([xs.min(), ys.min(), xs.max(),
                                   ys.max()])
        gt_boxes_b = np.asarray(gt_boxes_b, np.float32)
        n_fg_used = 0
        for i in fg:
            roi = rois_b[i] / im_scale
            cls = int(labs_b[i])
            # match only non-crowd gts OF THE ROI'S CLASS (the
            # reference restricts candidates the same way)
            cand = np.where((gc == cls) & ~cr)[0]
            if len(cand) == 0:
                continue
            ov = _np_overlaps(roi[None], gt_boxes_b[cand])[0]
            gi = int(cand[ov.argmax()])
            x1, y1, x2, y2 = roi
            H, W = g_masks.shape[1:]
            x1i = int(np.clip(np.floor(x1), 0, W - 1))
            y1i = int(np.clip(np.floor(y1), 0, H - 1))
            x2i = int(np.clip(np.ceil(x2), x1i + 1, W))
            y2i = int(np.clip(np.ceil(y2), y1i + 1, H))
            crop = g_masks[gi][y1i:y2i, x1i:x2i].astype(np.float32)
            # nearest-neighbor resize to [R, R]
            yy = np.clip((np.arange(R) + 0.5) * crop.shape[0] / R, 0,
                         crop.shape[0] - 1).astype(int)
            xx = np.clip((np.arange(R) + 0.5) * crop.shape[1] / R, 0,
                         crop.shape[1] - 1).astype(int)
            m = (crop[yy][:, xx] > 0.5).astype(np.int32)
            full = -np.ones((num_classes, R * R), np.int32)
            full[cls] = m.reshape(-1)
            out_rois.append(rois_b[i])
            out_has.append([1])
            out_mask.append(full.reshape(-1))
            n_fg_used += 1
        lens.append(n_fg_used)

    import jax.numpy as _jnp
    R2 = int(resolution) ** 2
    rois_np = (np.asarray(out_rois, np.float32) if out_rois
               else np.zeros((0, 4), np.float32))
    has_np = (np.asarray(out_has, np.int32) if out_has
              else np.zeros((0, 1), np.int32))
    mask_np = (np.asarray(out_mask, np.int32) if out_mask
               else np.zeros((0, num_classes * R2), np.int32))
    return (Tensor(_jnp.asarray(rois_np)),
            Tensor(_jnp.asarray(has_np)),
            Tensor(_jnp.asarray(mask_np)),
            Tensor(_jnp.asarray(np.asarray(lens, np.int32))))


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box,
                            anchor_var, gt_boxes, gt_labels, is_crowd,
                            im_info, num_classes=1,
                            positive_overlap=0.5, negative_overlap=0.4,
                            gt_num=None):
    """fluid.layers.retinanet_target_assign
    (operators/detection/retinanet_target_assign_op.cc): focal-loss
    sample selection — positives are max-overlap-per-gt anchors or
    IOU >= positive_overlap; negatives IOU < negative_overlap; anchors
    in between are ignored; NO subsampling (focal loss trains on all).
    Positive labels are the gt class (1..C), negative labels 0.

    Host-side data-prep (same disposition as rpn_target_assign).
    Returns (predict_scores [S, C], predict_location [L, 4],
    target_label [S, 1], target_bbox [L, 4], bbox_inside_weight [L, 4],
    fg_num [1])."""
    from ..ops.recsys import _host_only
    _host_only('retinanet_target_assign')
    bp = np.asarray(as_tensor(bbox_pred).data)
    cl = np.asarray(as_tensor(cls_logits).data)
    an = np.asarray(as_tensor(anchor_box).data)
    gbs = np.asarray(as_tensor(gt_boxes).data)
    gls = np.asarray(as_tensor(gt_labels).data)
    crowd_all = (np.asarray(as_tensor(is_crowd).data)
                 if is_crowd is not None else None)
    N, A = bp.shape[0], an.shape[0]
    gn = (np.asarray(as_tensor(gt_num).data).reshape(-1).astype(int)
          if gt_num is not None else np.full(N, gbs.shape[1], int))

    scores, locs, labels, tboxes, inw = [], [], [], [], []
    fg_total = 0
    for b in range(N):
        g = gbs[b][:gn[b]]
        gl = gls[b].reshape(-1)[:gn[b]]
        keep = (g[:, 2] - g[:, 0]) * (g[:, 3] - g[:, 1]) > 0
        if crowd_all is not None:
            keep &= ~crowd_all[b].reshape(-1)[:gn[b]].astype(bool)
        g, gl = g[keep], gl[keep]
        if len(g):
            iou = _np_overlaps(an, g)
            a2g = iou.argmax(1)
            a2g_max = iou.max(1)
            g_max = iou.max(0)
            lab = -np.ones(A, np.int64)
            lab[a2g_max < negative_overlap] = 0
            lab[np.where(iou == g_max)[0]] = 1
            lab[a2g_max >= positive_overlap] = 1
        else:
            a2g = np.zeros(A, int)
            lab = np.zeros(A, np.int64)
        fg = np.where(lab == 1)[0]
        bg = np.where(lab == 0)[0]
        fg_total += len(fg)
        sel = np.concatenate([fg, bg])
        scores.append(cl[b].reshape(A, -1)[sel])
        # positive target label = gt class; negatives 0
        tl = np.zeros(len(sel), np.int64)
        if len(g):
            tl[:len(fg)] = gl[a2g[fg]]
        labels.append(tl[:, None])
        locs.append(bp[b][fg])
        tboxes.append(g[a2g[fg]] if len(g)
                      else np.zeros((0, 4), an.dtype))
        inw.append(np.ones((len(fg), 4), np.float32))

    import jax.numpy as _jnp
    outs = [np.concatenate(x) if x else np.zeros((0, 1))
            for x in (scores, locs, labels, tboxes, inw)]
    return tuple(Tensor(_jnp.asarray(o)) for o in outs) + \
        (Tensor(_jnp.asarray(np.asarray([max(fg_total, 1)],
                                        np.int32))),)


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              rois_num=None, name=None):
    """roi_perspective_transform_op.cc (EAST): each roi is a QUAD
    [x1 y1 x2 y2 x3 y3 x4 y4] (clockwise from top-left); the op warps
    the quad region to a fixed [H', W'] patch via the homography that
    maps the output rectangle corners onto the quad, with bilinear
    sampling and an in-bounds mask.

    TPU-native: homographies solved per roi as one batched 8x8 linear
    system (jnp.linalg.solve), sampling as one vectorized gather —
    no per-pixel host loop. Returns (out [R, C, H', W'],
    mask [R, 1, H', W'], transform_matrix [R, 9])."""
    import jax
    input = as_tensor(input)
    rois = as_tensor(rois, ref=input)
    if rois_num is None:
        batch_idx_np = np.zeros((int(rois.shape[0]),), np.int32)
    else:
        rn = np.asarray(as_tensor(rois_num).data).reshape(-1)
        batch_idx_np = np.repeat(np.arange(len(rn)), rn).astype(np.int32)
    Ht, Wt = int(transformed_height), int(transformed_width)

    def fn(x, r):
        N, C, H, W = x.shape

        def homography(quad):
            # solve for h mapping (u, v) in the H'xW' rect to the quad
            src = jnp.asarray([[0., 0.], [Wt - 1., 0.],
                               [Wt - 1., Ht - 1.], [0., Ht - 1.]],
                              x.dtype)
            dst = quad.reshape(4, 2) * spatial_scale
            rows = []
            for i in range(4):
                u, v = src[i]
                xx, yy = dst[i]
                rows.append(jnp.asarray(
                    [u, v, 1., 0., 0., 0., -u * xx, -v * xx], x.dtype))
                rows.append(jnp.asarray(
                    [0., 0., 0., u, v, 1., -u * yy, -v * yy], x.dtype))
            Amat = jnp.stack(rows)
            b2 = jnp.stack([dst[0, 0], dst[0, 1], dst[1, 0], dst[1, 1],
                            dst[2, 0], dst[2, 1], dst[3, 0], dst[3, 1]])
            h = jnp.linalg.solve(Amat, b2)
            return jnp.concatenate([h, jnp.ones((1,), x.dtype)])

        def one(quad, b):
            h = homography(quad)
            Hm = h.reshape(3, 3)
            uu = jnp.arange(Wt, dtype=x.dtype)
            vv = jnp.arange(Ht, dtype=x.dtype)
            U, V = jnp.meshgrid(uu, vv)              # [Ht, Wt]
            ones = jnp.ones_like(U)
            pts = jnp.stack([U, V, ones], 0).reshape(3, -1)
            mapped = Hm @ pts
            xs = mapped[0] / jnp.maximum(jnp.abs(mapped[2]), 1e-9) \
                * jnp.sign(mapped[2])
            ys = mapped[1] / jnp.maximum(jnp.abs(mapped[2]), 1e-9) \
                * jnp.sign(mapped[2])
            inb = (xs >= -0.5) & (xs <= W - 0.5) & (ys >= -0.5) \
                & (ys <= H - 0.5)
            xc = jnp.clip(xs, 0, W - 1)
            yc = jnp.clip(ys, 0, H - 1)
            x0 = jnp.floor(xc).astype(jnp.int32)
            y0 = jnp.floor(yc).astype(jnp.int32)
            x1 = jnp.clip(x0 + 1, 0, W - 1)
            y1 = jnp.clip(y0 + 1, 0, H - 1)
            lx = xc - x0
            ly = yc - y0
            img = x[b]                                # [C, H, W]
            val = (img[:, y0, x0] * (1 - ly) * (1 - lx)
                   + img[:, y0, x1] * (1 - ly) * lx
                   + img[:, y1, x0] * ly * (1 - lx)
                   + img[:, y1, x1] * ly * lx)        # [C, Ht*Wt]
            val = jnp.where(inb[None, :], val, 0.0)
            return (val.reshape(C, Ht, Wt),
                    inb.reshape(1, Ht, Wt).astype(jnp.int32), h)
        outs, masks, hs = jax.vmap(one)(r, jnp.asarray(batch_idx_np))
        return outs, masks, hs
    return run_op('roi_perspective_transform', fn, [input, rois],
                  n_nondiff=1)
