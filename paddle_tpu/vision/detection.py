"""Detection operator tier.

Reference parity: paddle/fluid/operators/detection/ (18.2k LoC) — the
SSD/YOLO/RCNN op family: iou_similarity_op.cc, box_coder_op.h
(encode/decode_center_size), prior_box_op.h, yolo_box_op.h,
bipartite_match_op.cc, multiclass_nms_op.cc, generate_proposals_v2_op.cc,
box_clip_op.h, anchor_generator_op.h, and deformable_conv_op (v1/v2).

TPU-native design: everything is expressed as fixed-shape jnp array math so
it traces under jit —
  * pure decode/geometry ops (iou, box_coder, prior_box, yolo_box,
    anchor_generator, box_clip, deform_conv2d) are differentiable tensor
    programs that XLA fuses;
  * selection ops (NMS family, bipartite match, proposal generation) replace
    the reference's LoD/dynamic-size outputs with padded fixed-size outputs
    plus a valid-count tensor (the TPU idiom for data-dependent shapes; the
    reference's own GPU kernels do the same internally before compacting).
Sequential decisions (greedy NMS / greedy matching) run as lax.fori_loop
over a precomputed IoU/distance matrix instead of the reference's nested
host loops.
"""
import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ..core.autograd import run_op
from ..ops.common import as_tensor


# ---------------------------------------------------------------------------
# geometry helpers
# ---------------------------------------------------------------------------

def _box_wh(boxes, normalized):
    off = 0.0 if normalized else 1.0
    w = boxes[..., 2] - boxes[..., 0] + off
    h = boxes[..., 3] - boxes[..., 1] + off
    return w, h


def _iou_matrix(a, b, normalized=True):
    """a [N, 4], b [M, 4] → IoU [N, M] (parity: iou_similarity_op.h)."""
    off = 0.0 if normalized else 1.0
    ax1, ay1, ax2, ay2 = a[:, 0], a[:, 1], a[:, 2], a[:, 3]
    bx1, by1, bx2, by2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    ix1 = jnp.maximum(ax1[:, None], bx1[None, :])
    iy1 = jnp.maximum(ay1[:, None], by1[None, :])
    ix2 = jnp.minimum(ax2[:, None], bx2[None, :])
    iy2 = jnp.minimum(ay2[:, None], by2[None, :])
    iw = jnp.clip(ix2 - ix1 + off, 0.0, None)
    ih = jnp.clip(iy2 - iy1 + off, 0.0, None)
    inter = iw * ih
    area_a = (ax2 - ax1 + off) * (ay2 - ay1 + off)
    area_b = (bx2 - bx1 + off) * (by2 - by1 + off)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


def iou_similarity(x, y, box_normalized=True, name=None):
    """Parity: detection/iou_similarity_op.cc — X [N, 4], Y [M, 4] →
    [N, M] IoU."""
    x, y = as_tensor(x), as_tensor(y)

    def fn(a, b):
        return _iou_matrix(a, b, box_normalized)
    return run_op('iou_similarity', fn, [x, y])


def box_clip(input, im_info, name=None):
    """Parity: detection/box_clip_op.h — clip boxes [..., 4] into the image.
    im_info: [N, 3] (h, w, scale) — boxes clipped to (h/scale - 1,
    w/scale - 1)."""
    input, im_info = as_tensor(input), as_tensor(im_info)

    def fn(boxes, info):
        h = info[:, 0] / info[:, 2] - 1.0
        w = info[:, 1] / info[:, 2] - 1.0
        shape = [info.shape[0]] + [1] * (boxes.ndim - 2)
        h = h.reshape(shape)
        w = w.reshape(shape)
        x1 = jnp.clip(boxes[..., 0], 0.0, None)
        y1 = jnp.clip(boxes[..., 1], 0.0, None)
        x2 = jnp.clip(boxes[..., 2], 0.0, None)
        y2 = jnp.clip(boxes[..., 3], 0.0, None)
        return jnp.stack([jnp.minimum(x1, w), jnp.minimum(y1, h),
                          jnp.minimum(x2, w), jnp.minimum(y2, h)], axis=-1)
    return run_op('box_clip', fn, [input, im_info])


# ---------------------------------------------------------------------------
# box_coder
# ---------------------------------------------------------------------------

def box_coder(prior_box, prior_box_var, target_box,
              code_type='encode_center_size', box_normalized=True, axis=0,
              variance=None, name=None):
    """Parity: detection/box_coder_op.h.

    encode: target [M, 4], prior [N, 4] → [M, N, 4]
    decode: target [M, N, 4] (or broadcast), prior [N, 4] → [M, N, 4]
    prior_box_var: None | [N, 4] tensor | 4-list (attr `variance`).
    """
    prior_box = as_tensor(prior_box)
    target_box = as_tensor(target_box)
    var_tensor = None
    if isinstance(prior_box_var, (list, tuple)):
        variance = list(prior_box_var)
    elif prior_box_var is not None:
        var_tensor = as_tensor(prior_box_var)
    off = 0.0 if box_normalized else 1.0

    def _prior_cxcywh(p):
        pw = p[:, 2] - p[:, 0] + off
        ph = p[:, 3] - p[:, 1] + off
        return p[:, 0] + pw / 2, p[:, 1] + ph / 2, pw, ph

    if code_type == 'encode_center_size':
        def fn(*args):
            t, p = args[0], args[1]
            v = args[2] if var_tensor is not None else None
            pcx, pcy, pw, ph = _prior_cxcywh(p)
            tw = t[:, 2] - t[:, 0] + off
            th = t[:, 3] - t[:, 1] + off
            tcx = (t[:, 0] + t[:, 2]) / 2
            tcy = (t[:, 1] + t[:, 3]) / 2
            out = jnp.stack([
                (tcx[:, None] - pcx[None, :]) / pw[None, :],
                (tcy[:, None] - pcy[None, :]) / ph[None, :],
                jnp.log(jnp.abs(tw[:, None] / pw[None, :])),
                jnp.log(jnp.abs(th[:, None] / ph[None, :])),
            ], axis=-1)  # [M, N, 4]
            if v is not None:
                out = out / v[None, :, :]
            elif variance:
                out = out / jnp.asarray(variance, out.dtype)
            return out
        tensors = [target_box, prior_box] + (
            [var_tensor] if var_tensor is not None else [])
        return run_op('box_coder', fn, tensors)

    assert code_type == 'decode_center_size', code_type

    def fn(*args):
        t, p = args[0], args[1]
        v = args[2] if var_tensor is not None else None
        pcx, pcy, pw, ph = _prior_cxcywh(p)
        # broadcast prior along the axis the op decodes over
        if axis == 0:
            shape = (1, -1)
        else:
            shape = (-1, 1)
        pcx, pcy = pcx.reshape(shape), pcy.reshape(shape)
        pw, ph = pw.reshape(shape), ph.reshape(shape)
        if v is not None:
            vv = v[None, :, :] if axis == 0 else v[:, None, :]
            v0, v1, v2, v3 = vv[..., 0], vv[..., 1], vv[..., 2], vv[..., 3]
        elif variance:
            v0, v1, v2, v3 = variance
        else:
            v0 = v1 = v2 = v3 = 1.0
        tcx = v0 * t[..., 0] * pw + pcx
        tcy = v1 * t[..., 1] * ph + pcy
        tw = jnp.exp(v2 * t[..., 2]) * pw
        th = jnp.exp(v3 * t[..., 3]) * ph
        return jnp.stack([tcx - tw / 2, tcy - th / 2,
                          tcx + tw / 2 - off, tcy + th / 2 - off], axis=-1)
    tensors = [target_box, prior_box] + (
        [var_tensor] if var_tensor is not None else [])
    return run_op('box_coder', fn, tensors)


# ---------------------------------------------------------------------------
# prior_box / anchor_generator
# ---------------------------------------------------------------------------

def _prior_wh(min_sizes, max_sizes, aspect_ratios, flip,
              min_max_aspect_ratios_order):
    """The per-cell (w, h) ladder — parity: prior_box_op.h ExpandAspectRatios
    + the kernel's emission order."""
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    whs = []
    for k, ms in enumerate(min_sizes):
        ms = float(ms)
        if not min_max_aspect_ratios_order:
            for ar in ars:
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
            if max_sizes:
                Ms = float(max_sizes[k])
                whs.append((math.sqrt(ms * Ms), math.sqrt(ms * Ms)))
        else:
            whs.append((ms, ms))
            if max_sizes:
                Ms = float(max_sizes[k])
                whs.append((math.sqrt(ms * Ms), math.sqrt(ms * Ms)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
    return whs


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """Parity: detection/prior_box_op.h — SSD priors.
    input [N, C, H, W] feature map, image [N, C, Him, Wim] →
    (boxes [H, W, P, 4] normalized, variances [H, W, P, 4])."""
    input, image = as_tensor(input), as_tensor(image)
    H, W = input.shape[2], input.shape[3]
    Him, Wim = image.shape[2], image.shape[3]
    step_w = steps[0] if steps and steps[0] > 0 else Wim / W
    step_h = steps[1] if steps and steps[1] > 0 else Him / H
    whs = _prior_wh(list(min_sizes), list(max_sizes or []),
                    list(aspect_ratios), flip, min_max_aspect_ratios_order)
    P = len(whs)

    def fn(_x, _im):
        cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
        cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
        cx = jnp.broadcast_to(cx[None, :, None], (H, W, P))
        cy = jnp.broadcast_to(cy[:, None, None], (H, W, P))
        bw = jnp.asarray([w for w, _ in whs], jnp.float32) / 2
        bh = jnp.asarray([h for _, h in whs], jnp.float32) / 2
        out = jnp.stack([(cx - bw) / Wim, (cy - bh) / Him,
                         (cx + bw) / Wim, (cy + bh) / Him], axis=-1)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               (H, W, P, 4))
        return out, var
    return run_op('prior_box', fn, [input, image])


def anchor_generator(input, anchor_sizes, aspect_ratios, variances,
                     stride, offset=0.5, name=None):
    """Parity: detection/anchor_generator_op.h — RPN anchors.
    input [N, C, H, W] → (anchors [H, W, A, 4] in input-image pixels,
    variances [H, W, A, 4])."""
    input = as_tensor(input)
    H, W = input.shape[2], input.shape[3]
    whs = []
    for ar in aspect_ratios:
        for s in anchor_sizes:
            area = float(stride[0] * stride[1])
            base_w = round(math.sqrt(area / float(ar)))
            base_h = round(base_w * float(ar))
            scale_w = float(s) / stride[0]
            scale_h = float(s) / stride[1]
            whs.append((scale_w * base_w, scale_h * base_h))
    A = len(whs)

    def fn(_x):
        # centers at stride*i + offset*(stride-1); corners at
        # center ± (size-1)/2 — anchor_generator_op.h:68-95
        cx = jnp.arange(W, dtype=jnp.float32) * stride[0] \
            + offset * (stride[0] - 1)
        cy = jnp.arange(H, dtype=jnp.float32) * stride[1] \
            + offset * (stride[1] - 1)
        cx = jnp.broadcast_to(cx[None, :, None], (H, W, A))
        cy = jnp.broadcast_to(cy[:, None, None], (H, W, A))
        hw = (jnp.asarray([w for w, _ in whs], jnp.float32) - 1) / 2
        hh = (jnp.asarray([h for _, h in whs], jnp.float32) - 1) / 2
        anchors = jnp.stack([cx - hw, cy - hh, cx + hw, cy + hh], axis=-1)
        var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                               (H, W, A, 4))
        return anchors, var
    return run_op('anchor_generator', fn, [input])


# ---------------------------------------------------------------------------
# yolo_box
# ---------------------------------------------------------------------------

def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Parity: detection/yolo_box_op.h — decode YOLOv3 head output.
    x [N, A*(5+cls), H, W] (A*(6+cls) when iou_aware), img_size [N, 2]
    (h, w) → boxes [N, A*H*W, 4], scores [N, A*H*W, cls]."""
    x, img_size = as_tensor(x), as_tensor(img_size)
    an = len(anchors) // 2
    scale = float(scale_x_y)
    bias = -0.5 * (scale - 1.0)

    def fn(a, imgs):
        N, C, H, W = a.shape
        if iou_aware:
            ious = a[:, :an].reshape(N, an, 1, H, W)
            a = a[:, an:]
        a = a.reshape(N, an, 5 + class_num, H, W)
        grid_x = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
        grid_y = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
        img_h = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        img_w = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        in_h = float(downsample_ratio * H)
        in_w = float(downsample_ratio * W)
        aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
        ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]

        af = a.astype(jnp.float32)
        cx = (grid_x + jax.nn.sigmoid(af[:, :, 0]) * scale + bias) \
            * img_w / W
        cy = (grid_y + jax.nn.sigmoid(af[:, :, 1]) * scale + bias) \
            * img_h / H
        bw = jnp.exp(af[:, :, 2]) * aw * img_w / in_w
        bh = jnp.exp(af[:, :, 3]) * ah * img_h / in_h
        conf = jax.nn.sigmoid(af[:, :, 4])
        if iou_aware:
            iou = jax.nn.sigmoid(ious[:, :, 0].astype(jnp.float32))
            conf = conf ** (1.0 - iou_aware_factor) \
                * iou ** iou_aware_factor
        keep = conf >= conf_thresh

        x1, y1 = cx - bw / 2, cy - bh / 2
        x2, y2 = cx + bw / 2, cy + bh / 2
        if clip_bbox:
            x1 = jnp.clip(x1, 0.0, None)
            y1 = jnp.clip(y1, 0.0, None)
            x2 = jnp.minimum(x2, img_w - 1)
            y2 = jnp.minimum(y2, img_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)       # [N,an,H,W,4]
        boxes = jnp.where(keep[..., None], boxes, 0.0)
        scores = conf[..., None] \
            * jax.nn.sigmoid(af[:, :, 5:].transpose(0, 1, 3, 4, 2))
        scores = jnp.where(keep[..., None], scores, 0.0)
        return (boxes.reshape(N, an * H * W, 4),
                scores.reshape(N, an * H * W, class_num))
    return run_op('yolo_box', fn, [x, img_size],
                  n_nondiff=1)


# ---------------------------------------------------------------------------
# bipartite match
# ---------------------------------------------------------------------------

def _bipartite_match_single(dist):
    """Greedy global-max matching on dist [R, C] → (col→row indices [C],
    col match dist [C]); unmatched = -1 (parity:
    bipartite_match_op.cc BipartiteMatch)."""
    R, C = dist.shape
    init = (jnp.full((C,), -1, jnp.int32), jnp.zeros((C,), dist.dtype),
            jnp.zeros((R,), bool), jnp.zeros((C,), bool))

    def body(_, state):
        midx, mdist, row_used, col_used = state
        masked = jnp.where(row_used[:, None] | col_used[None, :],
                           -jnp.inf, dist)
        flat = jnp.argmax(masked)
        r, c = flat // C, flat % C
        best = masked[r, c]
        ok = best > 1e-6
        midx = jnp.where(ok, midx.at[c].set(r.astype(jnp.int32)), midx)
        mdist = jnp.where(ok, mdist.at[c].set(best.astype(dist.dtype)),
                          mdist)
        row_used = jnp.where(ok, row_used.at[r].set(True), row_used)
        col_used = jnp.where(ok, col_used.at[c].set(True), col_used)
        return midx, mdist, row_used, col_used

    midx, mdist, _, _ = lax.fori_loop(0, min(R, C), body, init)
    return midx, mdist


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """Parity: detection/bipartite_match_op.cc. dist [B, R, C] (or [R, C])
    → (ColToRowMatchIndices [B, C], ColToRowMatchDist [B, C]).
    match_type='per_prediction' additionally argmax-matches unmatched
    columns whose best distance >= dist_threshold * max_col_dist... (the
    reference compares against `dist_threshold` directly)."""
    dist_matrix = as_tensor(dist_matrix)
    batched = dist_matrix.ndim == 3

    def fn(d):
        d3 = d if batched else d[None]

        def one(dd):
            midx, mdist = _bipartite_match_single(dd)
            if match_type == 'per_prediction':
                thr = 0.5 if dist_threshold is None else dist_threshold
                best_row = jnp.argmax(dd, axis=0).astype(jnp.int32)
                best = jnp.max(dd, axis=0)
                fill = (midx == -1) & (best >= thr)
                midx = jnp.where(fill, best_row, midx)
                mdist = jnp.where(fill, best.astype(mdist.dtype), mdist)
            return midx, mdist
        midx, mdist = jax.vmap(one)(d3)
        if not batched:
            midx, mdist = midx[0], mdist[0]
        return midx, mdist
    return run_op('bipartite_match', fn, [dist_matrix],
                  n_nondiff=1)


# ---------------------------------------------------------------------------
# NMS family
# ---------------------------------------------------------------------------

def _greedy_nms_mask(boxes, scores, iou_threshold, normalized=True,
                     score_threshold=None, eta=1.0):
    """Greedy NMS over all boxes (descending score) → keep mask [M].
    eta < 1 tightens the threshold after each kept box once it exceeds 0.5
    (adaptive NMS — multiclass_nms_op.cc NMSFast)."""
    M = boxes.shape[0]
    iou = _iou_matrix(boxes, boxes, normalized)
    order = jnp.argsort(-scores)
    valid0 = jnp.ones((M,), bool) if score_threshold is None else \
        (scores > score_threshold)

    def body(i, state):
        keep, supp, thr = state
        idx = order[i]
        ok = (~supp[idx]) & valid0[idx]
        keep = keep.at[idx].set(ok)
        supp = jnp.where(ok, supp | (iou[idx] > thr), supp)
        if eta < 1.0:
            thr = jnp.where(ok & (thr > 0.5), thr * eta, thr)
        return keep, supp, thr

    keep, _, _ = lax.fori_loop(
        0, M, body, (jnp.zeros((M,), bool), jnp.zeros((M,), bool),
                     jnp.asarray(iou_threshold, jnp.float32)))
    return keep


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=-1,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=-1, name=None):
    """Parity: detection/multiclass_nms_op.cc (multiclass_nms2 outputs).
    bboxes [N, M, 4], scores [N, C, M] →
      out   [N, keep_top_k, 6]  rows (label, score, x1, y1, x2, y2),
      index [N, keep_top_k]     input box index (−1 past valid count),
      count [N]                 kept per image.
    Fixed-shape/padded in place of the reference's LoD output."""
    bboxes, scores = as_tensor(bboxes), as_tensor(scores)
    K = int(keep_top_k)

    def fn(bb, sc):
        N, M, _ = bb.shape
        C = sc.shape[1]

        def one(boxes, s):
            # per-class greedy NMS (background skipped via score=-inf)
            def per_class(c_scores):
                cs = c_scores
                if 0 < nms_top_k < M:
                    # pre-NMS candidate truncation
                    # (multiclass_nms_op.cc GetMaxScoreIndex top_k)
                    kth = -jnp.sort(-cs)[nms_top_k - 1]
                    cs = jnp.where(cs >= kth, cs, -jnp.inf)
                keep = _greedy_nms_mask(boxes, cs, nms_threshold,
                                        normalized, score_threshold,
                                        eta=nms_eta)
                return jnp.where(keep, c_scores, -jnp.inf)
            kept_scores = jax.vmap(per_class)(s)        # [C, M]
            if background_label >= 0:
                kept_scores = kept_scores.at[background_label].set(-jnp.inf)
            flat = kept_scores.reshape(-1)               # [C*M]
            top, arg = lax.top_k(flat, K)
            label = (arg // M).astype(jnp.float32)
            box_id = arg % M
            chosen = boxes[box_id]
            valid = top > -jnp.inf
            row = jnp.concatenate([
                jnp.where(valid, label, -1.0)[:, None],
                jnp.where(valid, top, 0.0)[:, None],
                jnp.where(valid[:, None], chosen, 0.0)], axis=1)
            idx_out = jnp.where(valid, box_id, -1).astype(jnp.int32)
            return row, idx_out, jnp.sum(valid).astype(jnp.int32)
        return jax.vmap(one)(bb, sc)
    return run_op('multiclass_nms', fn, [bboxes, scores],
                  n_nondiff=1)


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=-1, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               name=None):
    """Parity: detection/matrix_nms_op.cc — parallel soft-NMS: each box's
    score is decayed by its worst higher-scored same-class overlap; no
    sequential suppression, so it is one dense matrix program (the op the
    reference added precisely because greedy NMS serializes on
    accelerators). Fixed-shape outputs like multiclass_nms."""
    bboxes, scores = as_tensor(bboxes), as_tensor(scores)
    K = int(keep_top_k)

    def fn(bb, sc):
        N, M, _ = bb.shape
        C = sc.shape[1]

        def one(boxes, s):
            iou = _iou_matrix(boxes, boxes, normalized)

            def per_class(c_scores):
                valid = c_scores > score_threshold
                if 0 < nms_top_k < M:
                    # pre-decay candidate truncation
                    # (matrix_nms_op.cc:125-126)
                    kth = -jnp.sort(-jnp.where(valid, c_scores,
                                               -jnp.inf))[nms_top_k - 1]
                    valid = valid & (c_scores >= kth)
                cs = jnp.where(valid, c_scores, -jnp.inf)
                order = jnp.argsort(-cs)
                rank = jnp.argsort(order)        # rank[i]: position of box i
                higher = rank[None, :] < rank[:, None]   # j ranked above i
                iou_h = jnp.where(higher, iou, 0.0)
                max_iou = jnp.max(iou_h, axis=1)          # worst overlap
                # decay per reference: min over j of decay(iou_ij)/decay(max_iou_j)
                comp = jnp.where(higher, iou, 0.0)
                max_iou_j = max_iou[None, :]
                if use_gaussian:
                    decay = jnp.exp((max_iou_j ** 2 - comp ** 2)
                                    * gaussian_sigma)
                else:
                    decay = (1.0 - comp) / (1.0 - max_iou_j)
                decay = jnp.where(higher, decay, jnp.inf)
                decay = jnp.clip(jnp.min(decay, axis=1), None, 1.0)
                out = jnp.where(valid, c_scores * decay, -jnp.inf)
                if post_threshold > 0.0:
                    out = jnp.where(out >= post_threshold, out, -jnp.inf)
                return out
            kept = jax.vmap(per_class)(s)
            if background_label >= 0:
                kept = kept.at[background_label].set(-jnp.inf)
            flat = kept.reshape(-1)
            top, arg = lax.top_k(flat, K)
            label = (arg // M).astype(jnp.float32)
            box_id = arg % M
            valid = top > -jnp.inf
            row = jnp.concatenate([
                jnp.where(valid, label, -1.0)[:, None],
                jnp.where(valid, top, 0.0)[:, None],
                jnp.where(valid[:, None], boxes[box_id], 0.0)], axis=1)
            idx_out = jnp.where(valid, box_id, -1).astype(jnp.int32)
            return row, idx_out, jnp.sum(valid).astype(jnp.int32)
        return jax.vmap(one)(bb, sc)
    return run_op('matrix_nms', fn, [bboxes, scores],
                  n_nondiff=1)


# ---------------------------------------------------------------------------
# generate_proposals (RPN)
# ---------------------------------------------------------------------------

def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=True, name=None):
    """Parity: detection/generate_proposals_v2_op.cc.
    scores [N, A, H, W], bbox_deltas [N, 4A, H, W], img_size [N, 2] (h, w),
    anchors [H, W, A, 4], variances [H, W, A, 4] →
      rois [N, post_nms_top_n, 4], roi_scores [N, post_nms_top_n],
      roi_nums [N] (fixed-shape padded in place of LoD)."""
    scores, bbox_deltas = as_tensor(scores), as_tensor(bbox_deltas)
    img_size = as_tensor(img_size)
    anchors, variances = as_tensor(anchors), as_tensor(variances)
    off = 1.0 if pixel_offset else 0.0
    clip_ratio = math.log(1000.0 / 16.0)

    def fn(sc, deltas, imgs, anc, var):
        N, A, H, W = sc.shape
        M = A * H * W
        anc_f = anc.reshape(-1, 4)
        var_f = var.reshape(-1, 4)
        pre_n = min(pre_nms_top_n, M)

        def one(s, d, img):
            s_f = s.transpose(1, 2, 0).reshape(-1)           # [H*W*A]
            d_f = d.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
            # NB: anchors arrive [H, W, A, 4] so flatten order matches
            top, arg = lax.top_k(s_f, pre_n)
            d_t = d_f[arg]
            a_t = anc_f[arg]
            v_t = var_f[arg]
            # decode (bbox_util.h BoxCoder: variance-scaled, ratio-clipped)
            aw = a_t[:, 2] - a_t[:, 0] + off
            ah = a_t[:, 3] - a_t[:, 1] + off
            acx = a_t[:, 0] + aw * 0.5
            acy = a_t[:, 1] + ah * 0.5
            cx = v_t[:, 0] * d_t[:, 0] * aw + acx
            cy = v_t[:, 1] * d_t[:, 1] * ah + acy
            w = jnp.exp(jnp.minimum(v_t[:, 2] * d_t[:, 2], clip_ratio)) * aw
            h = jnp.exp(jnp.minimum(v_t[:, 3] * d_t[:, 3], clip_ratio)) * ah
            x1 = cx - w * 0.5
            y1 = cy - h * 0.5
            x2 = cx + w * 0.5 - off
            y2 = cy + h * 0.5 - off
            # clip to image
            ih, iw = img[0], img[1]
            x1 = jnp.clip(x1, 0.0, iw - off)
            y1 = jnp.clip(y1, 0.0, ih - off)
            x2 = jnp.clip(x2, 0.0, iw - off)
            y2 = jnp.clip(y2, 0.0, ih - off)
            boxes = jnp.stack([x1, y1, x2, y2], axis=1)
            # filter small
            bw = x2 - x1 + off
            bh = y2 - y1 + off
            ms = jnp.maximum(min_size, 1.0)
            big = (bw >= ms) & (bh >= ms)
            s_kept = jnp.where(big, top, -jnp.inf)
            keep = _greedy_nms_mask(boxes, s_kept, nms_thresh,
                                    normalized=not pixel_offset)
            keep = keep & big
            final = jnp.where(keep, s_kept, -jnp.inf)
            k = min(post_nms_top_n, pre_n)
            top2, arg2 = lax.top_k(final, k)
            valid = top2 > -jnp.inf
            rois = jnp.where(valid[:, None], boxes[arg2], 0.0)
            rscores = jnp.where(valid, top2, 0.0)
            pad = post_nms_top_n - k
            if pad:
                rois = jnp.pad(rois, ((0, pad), (0, 0)))
                rscores = jnp.pad(rscores, ((0, pad),))
                valid = jnp.pad(valid, ((0, pad),))
            return rois, rscores, jnp.sum(valid).astype(jnp.int32)
        return jax.vmap(one)(sc, deltas, imgs.astype(sc.dtype))
    return run_op('generate_proposals', fn,
                  [scores, bbox_deltas, img_size, anchors, variances],
                  n_nondiff=3)


# ---------------------------------------------------------------------------
# deformable convolution
# ---------------------------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Parity: operators/deformable_conv_op.cc (v2 with mask; v1 when
    mask=None). x [N, Cin, H, W]; offset [N, 2*dg*kh*kw, Ho, Wo] (y, x
    interleaved per kernel point); mask [N, dg*kh*kw, Ho, Wo];
    weight [Cout, Cin/groups, kh, kw].

    TPU-native: bilinear sampling as four gathers + an einsum contraction
    (the im2col the reference builds per-image in modulated_deformable_im2col
    becomes one batched tensor program, fully differentiable through
    jax.vjp)."""
    x, offset, weight = as_tensor(x), as_tensor(offset), as_tensor(weight)
    tensors = [x, offset, weight]
    if mask is not None:
        tensors.append(as_tensor(mask))
    if bias is not None:
        tensors.append(as_tensor(bias))
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    has_mask = mask is not None
    has_bias = bias is not None

    def fn(*args):
        xa, off, wgt = args[0], args[1], args[2]
        msk = args[3] if has_mask else None
        b = args[3 + has_mask] if has_bias else None
        N, Cin, H, W = xa.shape
        Cout, _, kh, kw = wgt.shape
        Ho = (H + 2 * p[0] - (d[0] * (kh - 1) + 1)) // s[0] + 1
        Wo = (W + 2 * p[1] - (d[1] * (kw - 1) + 1)) // s[1] + 1
        dg = deformable_groups
        K = kh * kw

        off = off.reshape(N, dg, K, 2, Ho, Wo)
        base_y = (jnp.arange(Ho) * s[0] - p[0])[:, None] \
            + (jnp.arange(kh) * d[0])[None, :]                # [Ho, kh]
        base_x = (jnp.arange(Wo) * s[1] - p[1])[:, None] \
            + (jnp.arange(kw) * d[1])[None, :]                # [Wo, kw]
        ky = jnp.broadcast_to(base_y[:, None, :, None], (Ho, Wo, kh, kw))
        kx = jnp.broadcast_to(base_x[None, :, None, :], (Ho, Wo, kh, kw))
        ky = ky.reshape(Ho, Wo, K).transpose(2, 0, 1)[None, None]
        kx = kx.reshape(Ho, Wo, K).transpose(2, 0, 1)[None, None]
        py = ky + off[:, :, :, 0].astype(jnp.float32)     # [N, dg, K, Ho, Wo]
        px = kx + off[:, :, :, 1].astype(jnp.float32)

        y0 = jnp.floor(py)
        x0 = jnp.floor(px)
        wy = py - y0
        wx = px - x0

        def gather(yy, xx):
            yi = yy.astype(jnp.int32)
            xi = xx.astype(jnp.int32)
            inside = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            yc = jnp.clip(yi, 0, H - 1)
            xc = jnp.clip(xi, 0, W - 1)
            # x grouped by deformable group: [N, dg, Cin/dg, H, W]
            xg = xa.reshape(N, dg, Cin // dg, H, W)
            flat = xg.reshape(N, dg, Cin // dg, H * W)
            idx = yc * W + xc                          # [N, dg, K, Ho, Wo]
            idx_f = idx.reshape(N, dg, -1)
            out = jnp.take_along_axis(
                flat, idx_f[:, :, None, :].repeat(Cin // dg, 2), axis=3)
            out = out.reshape(N, dg, Cin // dg, K, Ho, Wo)
            return jnp.where(inside[:, :, None], out, 0.0)

        v00 = gather(y0, x0)
        v01 = gather(y0, x0 + 1)
        v10 = gather(y0 + 1, x0)
        v11 = gather(y0 + 1, x0 + 1)
        wy_ = wy[:, :, None]
        wx_ = wx[:, :, None]
        sampled = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_
                   + v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)
        if msk is not None:
            sampled = sampled * msk.reshape(N, dg, 1, K, Ho, Wo)
        # [N, Cin, K, Ho, Wo] → group conv contraction
        cols = sampled.reshape(N, Cin, K, Ho, Wo)
        cols = cols.reshape(N, groups, Cin // groups, K, Ho, Wo)
        wg = wgt.reshape(groups, Cout // groups, Cin // groups, K)
        out = jnp.einsum('ngckhw,gock->ngohw', cols, wg)
        out = out.reshape(N, Cout, Ho, Wo)
        if b is not None:
            out = out + b.reshape(1, Cout, 1, 1)
        return out.astype(xa.dtype)
    return run_op('deformable_conv', fn, tensors)


# ---------------------------------------------------------------------------
# FPN / RCNN remainder
# ---------------------------------------------------------------------------

def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, name=None):
    """Parity: detection/distribute_fpn_proposals_op.cc — route each RoI
    to its FPN level by scale: level = floor(refer_level +
    log2(sqrt(area) / refer_scale)), clamped to [min_level, max_level].

    fpn_rois [R, 4] → (multi_rois: per-level [R, 4] padded arrays,
    level_counts [L], restore_ind [R]) — fixed-shape (each level array
    keeps R slots; rows beyond its count are zeros), restore_ind maps the
    concatenated per-level order back to the input order (the reference's
    RestoreIndex output)."""
    fpn_rois = as_tensor(fpn_rois)
    n_levels = max_level - min_level + 1

    def fn(rois):
        R = rois.shape[0]
        off = 1.0 if pixel_offset else 0.0
        w = rois[:, 2] - rois[:, 0] + off
        h = rois[:, 3] - rois[:, 1] + off
        scale = jnp.sqrt(jnp.maximum(w * h, 1e-12))
        lvl = jnp.floor(refer_level + jnp.log2(scale / refer_scale + 1e-12))
        lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
        lvl_idx = lvl - min_level                       # [R] in [0, L)

        # stable order: sort by (level, original index)
        order = jnp.argsort(lvl_idx * R + jnp.arange(R))
        sorted_lvl = lvl_idx[order]
        counts = jnp.bincount(lvl_idx, length=n_levels)
        starts = jnp.cumsum(counts) - counts
        # position of each sorted roi within its level
        pos_in_level = jnp.arange(R) - starts[sorted_lvl]
        multi = jnp.zeros((n_levels, R, 4), rois.dtype)
        multi = multi.at[sorted_lvl, pos_in_level].set(rois[order])
        # restore index: for each input roi, its rank in the level-major
        # concatenation (reference RestoreIndex semantics)
        rank_of_sorted = starts[sorted_lvl] + pos_in_level
        restore = jnp.zeros((R,), jnp.int32).at[order].set(
            rank_of_sorted.astype(jnp.int32))
        return multi, counts.astype(jnp.int32), restore
    return run_op('distribute_fpn_proposals', fn, [fpn_rois],
                  n_nondiff=1)


def collect_fpn_proposals(multi_rois, multi_scores, post_nms_top_n,
                          name=None):
    """Parity: detection/collect_fpn_proposals_op.cc — concat per-level
    RoIs, keep the global top post_nms_top_n by score.
    multi_rois: [L, R, 4] (or list), multi_scores: [L, R] with -inf/0 at
    padded slots → (rois [K, 4], scores [K], count)."""
    if isinstance(multi_rois, (list, tuple)):
        from ..ops import manip as _m
        multi_rois = _m.concat([_m.unsqueeze(as_tensor(r), [0])
                                for r in multi_rois], 0)
        multi_scores = _m.concat([_m.unsqueeze(as_tensor(s), [0])
                                  for s in multi_scores], 0)
    multi_rois = as_tensor(multi_rois)
    multi_scores = as_tensor(multi_scores)
    K = int(post_nms_top_n)

    def fn(rois, scores):
        flat_r = rois.reshape(-1, 4)
        flat_s = scores.reshape(-1).astype(jnp.float32)
        k = min(K, flat_s.shape[0])
        top, arg = lax.top_k(flat_s, k)
        valid = top > -jnp.inf
        out_r = jnp.where(valid[:, None], flat_r[arg], 0.0)
        out_s = jnp.where(valid, top, 0.0)
        if k < K:
            out_r = jnp.pad(out_r, ((0, K - k), (0, 0)))
            out_s = jnp.pad(out_s, ((0, K - k),))
            valid = jnp.pad(valid, ((0, K - k),))
        return out_r, out_s, jnp.sum(valid).astype(jnp.int32)
    return run_op('collect_fpn_proposals', fn, [multi_rois, multi_scores],
                  n_nondiff=1)


def psroi_pool(x, boxes, output_channels, spatial_scale, pooled_height,
               pooled_width, boxes_num=None, name=None):
    """Parity: operators/psroi_pool_op.cc — position-sensitive RoI
    pooling: x [N, C=out_c*ph*pw, H, W], boxes [R, 4] (batch 0; extend
    via boxes_num offsets), each output channel/bin pair (c, i, j)
    average-pools input channel c*ph*pw + i*pw + j over its bin →
    [R, out_c, ph, pw]."""
    x, boxes = as_tensor(x), as_tensor(boxes)
    ph, pw = int(pooled_height), int(pooled_width)
    oc = int(output_channels)

    def fn(a, bx):
        N, C, H, W = a.shape
        R = bx.shape[0]

        def one(box):
            x1 = box[0] * spatial_scale
            y1 = box[1] * spatial_scale
            x2 = box[2] * spatial_scale
            y2 = box[3] * spatial_scale
            rw = jnp.maximum(x2 - x1, 0.1)
            rh = jnp.maximum(y2 - y1, 0.1)
            bin_w = rw / pw
            bin_h = rh / ph
            # integer bin extents (reference: floor/ceil per bin)
            ys = jnp.arange(H, dtype=jnp.float32)
            xs = jnp.arange(W, dtype=jnp.float32)

            out = []
            for i in range(ph):
                for j in range(pw):
                    hs = y1 + i * bin_h
                    he = y1 + (i + 1) * bin_h
                    ws = x1 + j * bin_w
                    we = x1 + (j + 1) * bin_w
                    mask = ((ys[:, None] >= jnp.floor(hs))
                            & (ys[:, None] < jnp.ceil(he))
                            & (xs[None, :] >= jnp.floor(ws))
                            & (xs[None, :] < jnp.ceil(we)))
                    area = jnp.maximum(mask.sum(), 1)
                    ch = jnp.arange(oc) * ph * pw + i * pw + j
                    vals = (a[0, ch] * mask[None]).sum((1, 2)) / area
                    out.append(vals)                    # [oc]
            return jnp.stack(out, 1).reshape(oc, ph, pw)
        return jax.vmap(one)(bx.astype(jnp.float32))
    return run_op('psroi_pool', fn, [x, boxes], n_nondiff=1)


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, name=None):
    """Parity: detection/density_prior_box_op.cc — per cell, for each
    (density, fixed_size) pair and fixed ratio, a density×density grid of
    shifted boxes of size fixed_size*sqrt(ratio) (the face-detection
    prior ladder)."""
    input, image = as_tensor(input), as_tensor(image)
    H, W = input.shape[2], input.shape[3]
    Him, Wim = image.shape[2], image.shape[3]
    step_w = steps[0] if steps and steps[0] > 0 else Wim / W
    step_h = steps[1] if steps and steps[1] > 0 else Him / H
    # per-cell (dx, dy, w, h) ladder (densities[k] pairs fixed_sizes[k])
    ladder = []
    for fs, dens in zip(fixed_sizes, densities):
        for ar in fixed_ratios:
            bw = float(fs) * math.sqrt(ar)
            bh = float(fs) / math.sqrt(ar)
            shift = step_w / dens
            for di in range(dens):
                for dj in range(dens):
                    cx_off = (dj + 0.5) * shift - step_w / 2
                    cy_off = (di + 0.5) * shift - step_h / 2
                    ladder.append((cx_off, cy_off, bw, bh))
    P = len(ladder)

    def fn(_x, _im):
        cx0 = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
        cy0 = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
        offs = jnp.asarray(ladder, jnp.float32)         # [P, 4]
        cx = jnp.broadcast_to(cx0[None, :, None]
                              + offs[None, None, :, 0], (H, W, P))
        cy = jnp.broadcast_to(cy0[:, None, None]
                              + offs[None, None, :, 1], (H, W, P))
        bw = offs[:, 2] / 2
        bh = offs[:, 3] / 2
        out = jnp.stack([(cx - bw) / Wim, (cy - bh) / Him,
                         (cx + bw) / Wim, (cy + bh) / Him], -1)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               (H, W, P, 4))
        return out, var
    return run_op('density_prior_box', fn, [input, image])


class DetectionMAP:
    """Parity: operators/detection_map_op.cc / fluid.metrics.DetectionMAP
    — mean average precision over accumulated detections, '11point' or
    'integral' interpolation, difficult-gt exclusion. Host-side metric
    (the reference kernel is CPU-only)."""

    def __init__(self, class_num, overlap_threshold=0.5,
                 evaluate_difficult=False, ap_version='integral'):
        if ap_version not in ('integral', '11point'):
            raise ValueError("ap_version must be 'integral' or '11point'")
        self.class_num = class_num
        self.iou = overlap_threshold
        self.eval_difficult = evaluate_difficult
        self.ap_version = ap_version
        self.reset()

    def reset(self):
        self._dets = []     # (img, cls, score, box)
        self._gts = []      # (img, cls, box, difficult)
        self._img = 0

    @staticmethod
    def _iou(a, b):
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    def update(self, pred_boxes, pred_scores, pred_labels, gt_boxes,
               gt_labels, difficult=None):
        """One image: preds [N,4]/[N]/[N], gts [M,4]/[M], difficult [M]."""
        pb = np.asarray(pred_boxes, np.float64).reshape(-1, 4)
        ps = np.asarray(pred_scores, np.float64).reshape(-1)
        pl = np.asarray(pred_labels).reshape(-1)
        gb = np.asarray(gt_boxes, np.float64).reshape(-1, 4)
        gl = np.asarray(gt_labels).reshape(-1)
        df = (np.zeros(len(gl), bool) if difficult is None
              else np.asarray(difficult).reshape(-1).astype(bool))
        i = self._img
        for b, s, c in zip(pb, ps, pl):
            self._dets.append((i, int(c), float(s), tuple(b)))
        for b, c, d in zip(gb, gl, df):
            self._gts.append((i, int(c), tuple(b), bool(d)))
        self._img += 1

    def accumulate(self):
        """→ mAP in [0, 1]."""
        aps = []
        for c in range(self.class_num):
            gts = [(g[0], g[2], g[3]) for g in self._gts if g[1] == c]
            if self.eval_difficult:
                npos = len(gts)
            else:
                npos = sum(1 for g in gts if not g[2])
            dets = sorted((d for d in self._dets if d[1] == c),
                          key=lambda d: -d[2])
            if npos == 0:
                continue
            matched = set()
            tp = np.zeros(len(dets))
            fp = np.zeros(len(dets))
            by_img = {}
            for gi, (img, box, dif) in enumerate(gts):
                by_img.setdefault(img, []).append((gi, box, dif))
            for di, (img, _, _, box) in enumerate(dets):
                best, best_gi = 0.0, -1
                for gi, gbox, dif in by_img.get(img, []):
                    ov = self._iou(box, gbox)
                    if ov > best:
                        best, best_gi = ov, gi
                if best_gi >= 0 and best >= self.iou:
                    gi = best_gi
                    dif = gts[gi][2]
                    if dif and not self.eval_difficult:
                        continue            # neither tp nor fp
                    if gi not in matched:
                        matched.add(gi)
                        tp[di] = 1
                    else:
                        fp[di] = 1
                else:
                    fp[di] = 1
            ctp = np.cumsum(tp)
            cfp = np.cumsum(fp)
            rec = ctp / npos
            prec = ctp / np.maximum(ctp + cfp, 1e-12)
            if self.ap_version == '11point':
                ap = 0.0
                for t in np.linspace(0, 1, 11):
                    p = prec[rec >= t].max() if (rec >= t).any() else 0.0
                    ap += p / 11.0
            else:
                mrec = np.concatenate([[0.0], rec, [1.0]])
                mpre = np.concatenate([[0.0], prec, [0.0]])
                for k in range(len(mpre) - 2, -1, -1):
                    mpre[k] = max(mpre[k], mpre[k + 1])
                idx = np.where(mrec[1:] != mrec[:-1])[0]
                ap = float(np.sum((mrec[idx + 1] - mrec[idx])
                                  * mpre[idx + 1]))
            aps.append(ap)
        return float(min(np.mean(aps), 1.0)) if aps else 0.0
