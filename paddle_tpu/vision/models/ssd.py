"""SSD-style single-shot detector (detection-tier end-to-end model).

Reference parity: the SSD the reference assembles from fluid.layers
detection ops — multi_box_head + prior_box (layers/detection.py),
ssd_loss (bipartite_match + target assign + smooth_l1 + softmax CE,
layers/detection.py ssd_loss), and detection_output
(box_coder decode + multiclass_nms). The op tier lives in
paddle_tpu/vision/detection.py; this model wires it into a trainable
detector the way the reference's SSD configs do.

TPU-native: everything except the final NMS is one fixed-shape jitted
program; matching runs as the vectorized bipartite/argmax assignment over
the IoU matrix (no LoD, masks instead).
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ... import nn
from ...core.tensor import Tensor
from ...core.autograd import run_op
from ...ops import math as M
from ...ops import manip
from ...ops import nn_ops as F
from .. import detection as D


class SSDHead(nn.Layer):
    """Per-feature-map conv predictors: loc [N, P, 4] + conf [N, P, C]."""

    def __init__(self, in_channels, num_priors, num_classes):
        super().__init__()
        self.num_classes = num_classes
        self.loc = nn.Conv2D(in_channels, num_priors * 4, 3, padding=1)
        self.conf = nn.Conv2D(in_channels, num_priors * num_classes, 3,
                              padding=1)

    def forward(self, feat):
        N = feat.shape[0]
        loc = manip.transpose(self.loc(feat), [0, 2, 3, 1])
        loc = manip.reshape(loc, [N, -1, 4])
        conf = manip.transpose(self.conf(feat), [0, 2, 3, 1])
        conf = manip.reshape(conf, [N, -1, self.num_classes])
        return loc, conf


class TinySSD(nn.Layer):
    """A compact SSD: conv backbone with two prediction scales — the
    reference's mobilenet-ssd topology at toy size (the op wiring, loss
    and decode paths are the full SSD ones)."""

    def __init__(self, num_classes=4, image_size=64):
        super().__init__()
        self.num_classes = num_classes      # incl. background class 0
        self.image_size = image_size
        self.stem = nn.Sequential(
            nn.Conv2D(3, 16, 3, stride=2, padding=1), nn.ReLU(),
            nn.Conv2D(16, 32, 3, stride=2, padding=1), nn.ReLU())
        self.block1 = nn.Sequential(
            nn.Conv2D(32, 64, 3, stride=2, padding=1), nn.ReLU())
        self.block2 = nn.Sequential(
            nn.Conv2D(64, 64, 3, stride=2, padding=1), nn.ReLU())
        self._prior_cfg = [
            # (min_size, max_size, ars)
            (16.0, 32.0, (2.0,)),
            (32.0, 56.0, (2.0,)),
        ]
        np1 = len(D._prior_wh([16.0], [32.0], [2.0], True, False))
        np2 = len(D._prior_wh([32.0], [56.0], [2.0], True, False))
        self.head1 = SSDHead(64, np1, num_classes)
        self.head2 = SSDHead(64, np2, num_classes)

    def priors(self, feats):
        """Normalized [P_total, 4] priors + variances for the two maps —
        shape-static, so computed once per feature geometry and kept OFF
        the autograd tape (re-recording them each step would drag dead
        zero-cotangent VJP work through prior_box)."""
        key = tuple(tuple(f.shape[2:]) for f in feats)
        cached = getattr(self, '_prior_cache', None)
        if cached is not None and cached[0] == key:
            return cached[1], cached[2]
        img = Tensor(jnp.zeros((1, 3, self.image_size, self.image_size),
                               jnp.float32))
        outs, vars_ = [], []
        for feat, (ms, Ms, ars) in zip(feats, self._prior_cfg):
            b, v = D.prior_box(feat, img, min_sizes=[ms], max_sizes=[Ms],
                               aspect_ratios=list(ars), flip=True,
                               clip=True)
            outs.append(manip.reshape(b, [-1, 4]))
            vars_.append(manip.reshape(v, [-1, 4]))
        pri = Tensor(manip.concat(outs, 0).data)       # detached
        pvar = Tensor(manip.concat(vars_, 0).data)
        self._prior_cache = (key, pri, pvar)
        return pri, pvar

    def forward(self, images):
        x = self.stem(images)
        f1 = self.block1(x)
        f2 = self.block2(f1)
        l1, c1 = self.head1(f1)
        l2, c2 = self.head2(f2)
        loc = manip.concat([l1, l2], 1)      # [N, P, 4]
        conf = manip.concat([c1, c2], 1)     # [N, P, C]
        priors, prior_vars = self.priors([f1, f2])
        return loc, conf, priors, prior_vars


def ssd_loss(loc, conf, priors, prior_vars, gt_boxes, gt_labels,
             overlap_threshold=0.5, neg_pos_ratio=3.0):
    """Parity: layers/detection.py ssd_loss — match priors to ground truth
    (best-prior-per-gt forced + IoU threshold), encode regression targets
    (box_coder encode semantics), smooth_l1 on positives, softmax CE with
    hard negative mining at neg:pos = 3:1.

    gt_boxes [N, G, 4] normalized (padded with zeros), gt_labels [N, G]
    (0 = padding/background). Returns scalar loss."""
    n_cls = conf.shape[-1]

    def fn(loc_a, conf_a, pri, pvar, gb, gl):
        Nb, P, _ = loc_a.shape
        G = gb.shape[1]

        def one(loc_i, conf_i, gb_i, gl_i):
            iou = D._iou_matrix(gb_i, pri)                 # [G, P]
            valid_gt = (gl_i > 0)
            iou = jnp.where(valid_gt[:, None], iou, 0.0)
            best_gt = jnp.argmax(iou, 0)                   # per prior
            best_iou = jnp.max(iou, 0)
            # force-match the best prior of each gt (bipartite step);
            # padding GTs scatter to a dropped out-of-range slot so they
            # can never collide with a valid GT at prior 0
            best_prior = jnp.argmax(iou, 1)                # [G]
            safe_prior = jnp.where(valid_gt, best_prior, P)
            forced = jnp.zeros((P,), bool) \
                .at[safe_prior].set(True, mode='drop')
            forced_gt = jnp.zeros((P,), jnp.int32) \
                .at[safe_prior].set(jnp.arange(G, dtype=jnp.int32),
                                    mode='drop')
            match_gt = jnp.where(forced, forced_gt, best_gt)
            pos = forced | (best_iou >= overlap_threshold)
            labels = jnp.where(pos, gl_i[match_gt], 0)     # 0 = bg

            # encode matched gt vs priors (encode_center_size w/ variance)
            mg = gb_i[match_gt]                            # [P, 4]
            pw = pri[:, 2] - pri[:, 0]
            ph = pri[:, 3] - pri[:, 1]
            pcx = pri[:, 0] + pw / 2
            pcy = pri[:, 1] + ph / 2
            gw = jnp.maximum(mg[:, 2] - mg[:, 0], 1e-6)
            gh = jnp.maximum(mg[:, 3] - mg[:, 1], 1e-6)
            gcx = (mg[:, 0] + mg[:, 2]) / 2
            gcy = (mg[:, 1] + mg[:, 3]) / 2
            t = jnp.stack([(gcx - pcx) / pw, (gcy - pcy) / ph,
                           jnp.log(gw / pw), jnp.log(gh / ph)], 1) / pvar

            # smooth_l1 on positives
            d = loc_i - t
            sl1 = jnp.where(jnp.abs(d) < 1.0, 0.5 * d * d,
                            jnp.abs(d) - 0.5).sum(-1)
            n_pos = jnp.maximum(pos.sum(), 1)
            loss_loc = jnp.where(pos, sl1, 0.0).sum() / n_pos

            # softmax CE + hard negative mining
            logp = jax.nn.log_softmax(conf_i.astype(jnp.float32), -1)
            ce = -jnp.take_along_axis(logp, labels[:, None], 1)[:, 0]
            neg_score = jnp.where(pos, -jnp.inf, ce)       # candidates
            k = jnp.minimum((neg_pos_ratio * n_pos).astype(jnp.int32),
                            P - 1)
            thresh = jnp.sort(neg_score)[::-1][jnp.clip(k, 0, P - 1)]
            neg = (~pos) & (neg_score > thresh)
            loss_conf = (jnp.where(pos | neg, ce, 0.0).sum()
                         / n_pos)
            return loss_loc + loss_conf

        return jnp.mean(jax.vmap(one)(loc_a, conf_a, gb, gl))
    return run_op('ssd_loss', fn,
                  [loc, conf, priors, prior_vars,
                   gt_boxes, gt_labels], n_nondiff=2)


def ssd_detection_output(loc, conf, priors, prior_vars,
                         score_threshold=0.05, nms_threshold=0.45,
                         keep_top_k=50, nms_top_k=200):
    """Parity: layers/detection.py detection_output — decode loc deltas
    against the priors (box_coder decode_center_size) then per-class
    multiclass NMS. Returns (out [N, K, 6], index, counts)."""
    decoded = D.box_coder(priors, prior_vars, loc,
                          code_type='decode_center_size', axis=0)
    # axis=0: prior per SECOND target dim (box_coder_op.h axis==0 indexes
    # prior rows by the column) → decoded [N, P, 4]
    scores = F.softmax(conf, axis=-1)                      # [N, P, C]
    scores_t = manip.transpose(scores, [0, 2, 1])          # [N, C, P]
    return D.multiclass_nms(decoded, scores_t,
                            score_threshold=score_threshold,
                            nms_threshold=nms_threshold,
                            keep_top_k=keep_top_k, nms_top_k=nms_top_k,
                            background_label=0)
