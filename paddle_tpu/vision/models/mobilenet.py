"""MobileNetV1/V2 (parity: python/paddle/vision/models/mobilenetv{1,2}.py)."""
from ... import nn


class ConvBNLayer(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, padding=0, groups=1,
                 act='relu6'):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                              groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = nn.ReLU6() if act == 'relu6' else nn.ReLU() if act else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c1, out_c2, stride, scale=1.0):
        super().__init__()
        c1, c2 = int(out_c1 * scale), int(out_c2 * scale)
        self.dw = ConvBNLayer(in_c, c1, 3, stride, 1, groups=in_c, act='relu')
        self.pw = ConvBNLayer(c1, c2, 1, 1, 0, act='relu')

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: int(c * scale)
        self.conv1 = ConvBNLayer(3, s(32), 3, 2, 1, act='relu')
        cfg = [(32, 32, 64, 1), (64, 64, 128, 2), (128, 128, 128, 1),
               (128, 128, 256, 2), (256, 256, 256, 1), (256, 256, 512, 2),
               (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 512, 1),
               (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 1024, 2),
               (1024, 1024, 1024, 1)]
        blocks = []
        for in_c, c1, c2, stride in cfg:
            blocks.append(DepthwiseSeparable(s(in_c), c1, c2, stride, scale))
        self.blocks = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops import manip
            x = manip.flatten(x, 1)
            x = self.fc(x)
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(inp, hidden, 1))
        layers += [ConvBNLayer(hidden, hidden, 3, stride, 1, groups=hidden),
                   nn.Conv2D(hidden, oup, 1, bias_attr=False),
                   nn.BatchNorm2D(oup)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        input_channel = int(32 * scale)
        features = [ConvBNLayer(3, input_channel, 3, 2, 1)]
        for t, c, n, s in cfg:
            out_c = int(c * scale)
            for i in range(n):
                features.append(InvertedResidual(
                    input_channel, out_c, s if i == 0 else 1, t))
                input_channel = out_c
        self.last_channel = int(1280 * max(1.0, scale))
        features.append(ConvBNLayer(input_channel, self.last_channel, 1))
        self.features = nn.Sequential(*features)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(self.last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops import manip
            x = manip.flatten(x, 1)
            x = self.classifier(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
