"""Vision model zoo (parity: python/paddle/vision/models — LeNet,
ResNet18-152, VGG, MobileNetV1/V2)."""
from .lenet import LeNet
from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .mobilenet import MobileNetV1, MobileNetV2, mobilenet_v1, mobilenet_v2
from .ssd import TinySSD, SSDHead, ssd_loss, ssd_detection_output  # noqa: F401,E402
