"""hapi.Model (parity: python/paddle/hapi/model.py Model:878)."""
import numpy as np

from ..core.tensor import Tensor
from ..core.autograd import no_grad
from .. import framework
from ..io import DataLoader, Dataset
from .callbacks import CallbackList, ProgBarLogger


class Model:
    """Keras-like trainer (parity: hapi/model.py Model/fit:1523,
    DynamicGraphAdapter:659)."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit=False):
        self._optimizer = optimizer
        self._loss = loss
        self._jit = jit
        self._train_step = None
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]

    # -- single steps ---------------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        if getattr(self, '_jit', False) and update:
            return self._train_batch_jit(inputs, labels)
        self.network.train()
        inputs = self._to_list(inputs)
        labels = self._to_list(labels)
        outputs = self.network(*[self._to_tensor(x) for x in inputs])
        outs = self._to_list(outputs)
        losses = self._loss(*(outs + [self._to_tensor(l) for l in labels]))
        loss_list = self._to_list(losses)
        total = loss_list[0]
        for extra in loss_list[1:]:
            total = total + extra
        total.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m_out = m.compute(outs[0], *[self._to_tensor(l) for l in labels])
            metrics.append(m.update(m_out))
        out_loss = [[float(np.asarray(l.data))] for l in loss_list]
        return (out_loss, metrics) if metrics else out_loss

    def _train_batch_jit(self, inputs, labels):
        """One fused XLA program per step (paddle_tpu.jit.TrainStep) — the
        TPU-idiomatic fit loop."""
        from ..jit import TrainStep
        inputs = self._to_list(inputs)
        labels = self._to_list(labels)
        if self._train_step is None:
            n_in = len(inputs)
            loss_obj = self._loss

            def loss_fn(model, *batch):
                outs = model(*batch[:n_in])
                outs = outs if isinstance(outs, (list, tuple)) else [outs]
                losses = loss_obj(*(list(outs) + list(batch[n_in:])))
                losses = losses if isinstance(losses, (list, tuple)) \
                    else [losses]
                total = losses[0]
                for extra in losses[1:]:
                    total = total + extra
                return total
            self.network.train()
            self._train_step = TrainStep(self.network, loss_fn,
                                         self._optimizer)
        batch = [self._to_tensor(x) for x in inputs + labels]
        loss = self._train_step(*batch)
        return [[float(np.asarray(loss.data))]]

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        if getattr(self, '_train_step', None) is not None:
            self._train_step.sync_model()  # pull jitted params into the layer
        self.network.eval()
        inputs = self._to_list(inputs)
        labels = self._to_list(labels)
        outputs = self.network(*[self._to_tensor(x) for x in inputs])
        outs = self._to_list(outputs)
        out_loss = []
        if self._loss is not None and labels:
            losses = self._loss(*(outs + [self._to_tensor(l)
                                          for l in labels]))
            out_loss = [[float(np.asarray(l.data))]
                        for l in self._to_list(losses)]
        metrics = []
        for m in self._metrics:
            m_out = m.compute(outs[0], *[self._to_tensor(l) for l in labels])
            metrics.append(m.update(m_out))
        return (out_loss, metrics) if metrics else out_loss

    @no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        inputs = self._to_list(inputs)
        outputs = self.network(*[self._to_tensor(x) for x in inputs])
        return [np.asarray(o.data) for o in self._to_list(outputs)]

    # -- loops ---------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        train_loader = self._to_loader(train_data, batch_size, shuffle,
                                       drop_last, num_workers)
        eval_loader = self._to_loader(eval_data, batch_size, False, False,
                                      num_workers) if eval_data is not None \
            else None
        cbks = CallbackList(callbacks or ([ProgBarLogger(log_freq, verbose)]
                                          if verbose else []))
        cbks.set_model(self)
        cbks.set_params({'epochs': epochs, 'verbose': verbose,
                         'metrics': self._metrics_name(),
                         'steps': self._safe_len(train_loader)})
        cbks.on_begin('train')
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            from .. import profiler as _prof
            telemetry_cbs = [c for c in cbks.callbacks
                             if hasattr(c, 'observe_batch')]
            for step, batch in enumerate(train_loader):
                if num_iters is not None and step >= num_iters:
                    break
                for tc in telemetry_cbs:
                    tc.observe_batch(batch)
                cbks.on_batch_begin('train', step, logs)
                ins, labs = self._split_batch(batch)
                with _prof.RecordEvent('hapi::train_batch',
                                       event_type='train', step=step):
                    result = self.train_batch(ins, labs,
                                              update=(step + 1) %
                                              accumulate_grad_batches == 0)
                logs = self._update_logs(result, logs, step)
                cbks.on_batch_end('train', step, logs)
                if self.stop_training:
                    break
            if isinstance(self._optimizer_lr_scheduler(), object) and \
                    hasattr(self._optimizer_lr_scheduler(), 'step'):
                sched = self._optimizer_lr_scheduler()
                if sched is not None:
                    sched.step()
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/{epoch}")
            if self.stop_training:
                break
        cbks.on_end('train')
        if save_dir:
            self.save(f"{save_dir}/final")

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = self._to_loader(eval_data, batch_size, False, False,
                                 num_workers)
        for m in self._metrics:
            m.reset()
        logs = {}
        for step, batch in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            ins, labs = self._split_batch(batch)
            result = self.eval_batch(ins, labs)
            logs = self._update_logs(result, logs, step)
        out = {}
        if 'loss' in logs:
            out['loss'] = logs['loss']
        for m in self._metrics:
            res = m.accumulate()
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = res if isinstance(res, list) else [res]
            for n, v in zip(names, vals):
                out[n] = v
        return out

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._to_loader(test_data, batch_size, False, False,
                                 num_workers)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch, has_label=False)
            outputs.append(self.predict_batch(ins))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    # -- persistence ----------------------------------------------------------
    def save(self, path, training=True):
        if getattr(self, '_train_step', None) is not None:
            self._train_step.sync_model()
        framework.save(self.network.state_dict(), path + '.pdparams')
        if training and self._optimizer is not None:
            framework.save(self._optimizer.state_dict(), path + '.pdopt')

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        sd = framework.load(path + '.pdparams')
        self.network.set_state_dict(sd)
        import os
        if not reset_optimizer and self._optimizer is not None \
                and os.path.exists(path + '.pdopt'):
            self._optimizer.set_state_dict(framework.load(path + '.pdopt'))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtype)

    # -- helpers ---------------------------------------------------------------
    @staticmethod
    def _safe_len(loader):
        try:
            return len(loader)
        except TypeError:
            return None

    @staticmethod
    def _to_list(x):
        if x is None:
            return []
        if isinstance(x, (list, tuple)):
            return list(x)
        return [x]

    @staticmethod
    def _to_tensor(x):
        return x if isinstance(x, Tensor) else Tensor(np.asarray(x))

    def _to_loader(self, data, batch_size, shuffle, drop_last, num_workers):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)

    def _split_batch(self, batch, has_label=True):
        batch = list(batch) if isinstance(batch, (list, tuple)) else [batch]
        if not has_label or self._loss is None:
            return batch, []
        n_in = len(self._inputs) if self._inputs else max(1, len(batch) - 1)
        return batch[:n_in], batch[n_in:]

    def _metrics_name(self):
        names = ['loss']
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    def _update_logs(self, result, logs, step):
        if isinstance(result, tuple):
            losses, _ = result
        else:
            losses = result
        loss_v = losses[0][0]
        logs = dict(logs)
        prev = logs.get('loss', loss_v)
        logs['loss'] = (prev * step + loss_v) / (step + 1)
        logs['step'] = step
        for m in self._metrics:
            res = m.accumulate()
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = res if isinstance(res, list) else [res]
            for n, v in zip(names, vals):
                logs[n] = v
        return logs

    def _optimizer_lr_scheduler(self):
        if self._optimizer is None:
            return None
        from ..optimizer.lr import LRScheduler
        lr = self._optimizer._learning_rate
        return lr if isinstance(lr, LRScheduler) else None
