"""paddle_tpu.hapi — high-level Model API.

Reference parity: python/paddle/hapi/model.py (Model:878, fit:1523) with the
DynamicGraphAdapter(:659) path; prepare/fit/evaluate/predict/save/load and
callbacks. TPU-native: train/eval steps run through paddle_tpu.jit.TrainStep
(one XLA executable per step) when the model is jit-compatible, falling back
to the eager tape otherwise.
"""
from .model import Model
from .callbacks import (Callback, ProgBarLogger, ModelCheckpoint,
                        LRSchedulerCallback, EarlyStopping,
                        ReduceLROnPlateau, VisualDL, StepTelemetry)
from .summary import summary
