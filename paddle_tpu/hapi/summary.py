"""Model summary (parity: python/paddle/hapi/model_summary.py)."""
import numpy as np


def summary(net, input_size=None, dtypes=None):
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if not p.stop_gradient:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max((len(r[0]) for r in rows), default=20) + 2
    print(f"{'Param':<{width}}{'Shape':<24}{'Count':>12}")
    print("-" * (width + 36))
    for name, shape, n in rows:
        print(f"{name:<{width}}{str(shape):<24}{n:>12,}")
    print("-" * (width + 36))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    return {'total_params': total, 'trainable_params': trainable}
