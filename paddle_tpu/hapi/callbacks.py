"""hapi callbacks (parity: python/paddle/hapi/callbacks.py — ProgBarLogger:297,
ModelCheckpoint:533, LRScheduler:598, EarlyStopping:688)."""
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_begin(self, mode, logs=None):
        pass

    def on_end(self, mode, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, mode, step, logs=None):
        pass

    def on_batch_end(self, mode, step, logs=None):
        pass

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        def call(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)
        return call


class ProgBarLogger(Callback):
    """Parity: hapi/callbacks.py:297."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.t0 = time.time()
        if self.verbose:
            total = self.params.get('epochs')
            print(f"Epoch {epoch + 1}/{total}")

    def on_batch_end(self, mode, step, logs=None):
        if self.verbose and mode == 'train' and \
                (step + 1) % self.log_freq == 0:
            msg = ' - '.join(f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                             if isinstance(v, (int, float)) and k != 'step')
            steps = self.params.get('steps')
            print(f"step {step + 1}/{steps} - {msg}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dur = time.time() - self.t0
            msg = ' - '.join(f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                             if isinstance(v, (int, float)) and k != 'step')
            print(f"epoch {epoch + 1} done ({dur:.1f}s) - {msg}")


class ModelCheckpoint(Callback):
    """Parity: hapi/callbacks.py:533."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")


class LRSchedulerCallback(Callback):
    """Parity: hapi/callbacks.py LRScheduler:598."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, '_optimizer', None)
        if opt is None:
            return None
        from ..optimizer.lr import LRScheduler
        lr = opt._learning_rate
        return lr if isinstance(lr, LRScheduler) else None

    def on_batch_end(self, mode, step, logs=None):
        if mode == 'train' and self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    """Parity: hapi/callbacks.py:688."""

    def __init__(self, monitor='loss', mode='auto', patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.best = None
        if mode == 'max' or (mode == 'auto' and 'acc' in monitor):
            self.compare = lambda a, b: a > b + self.min_delta
        else:
            self.compare = lambda a, b: a < b - self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        current = logs.get(self.monitor) or logs.get('eval_' + self.monitor)
        if current is None:
            return
        if self.best is None or self.compare(current, self.best):
            self.best = current
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class ReduceLROnPlateau(Callback):
    """Parity: hapi/callbacks.py:956 — reduce the optimizer's learning
    rate by `factor` once `monitor` stops improving for `patience`
    epochs, with a cooldown and a floor."""

    def __init__(self, monitor='loss', factor=0.1, patience=10, verbose=1,
                 mode='auto', min_delta=1e-4, cooldown=0, min_lr=0.0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.cooldown_counter = 0
        self.wait = 0
        self.best = None
        if mode == 'max' or (mode == 'auto' and 'acc' in monitor):
            self.compare = lambda a, b: a > b + self.min_delta
        else:
            self.compare = lambda a, b: a < b - self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        current = logs.get(self.monitor)
        if current is None:
            current = logs.get('eval_' + self.monitor)
        if current is None:
            return
        if isinstance(current, (list, tuple)):
            current = current[0]
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self.best is None or self.compare(current, self.best):
            self.best = current
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, '_optimizer', None)
                if opt is not None:
                    old = float(opt.get_lr())
                    new = max(old * self.factor, self.min_lr)
                    if old - new > 1e-12:
                        opt.set_lr(new)
                        if self.verbose:
                            print(f"Epoch {epoch}: ReduceLROnPlateau "
                                  f"reducing learning rate to {new}.")
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """Accepted for API parity; logs to stdout in this environment."""

    def __init__(self, log_dir=None):
        super().__init__()
        self.log_dir = log_dir

    def on_epoch_end(self, epoch, logs=None):
        pass


class StepTelemetry(Callback):
    """Observability-v2 reporter: wraps profiler.StepTelemetry around the
    train loop. Per-batch it measures step latency and examples/sec
    (batch size inferred from the first input's leading dim) and
    publishes the gauges into core.monitor; `snapshot()` (also stamped
    into the epoch logs under 'telemetry') carries compile seconds,
    compile-cache hit/miss and device memory alongside throughput —
    the dict bench.py and the /metrics endpoint consume."""

    def __init__(self, window=20, tokens_per_example=None, log_freq=0):
        super().__init__()
        from ..profiler import StepTelemetry as _Reporter
        self.reporter = _Reporter(window=window)
        self.tokens_per_example = tokens_per_example
        self.log_freq = log_freq
        self._batch_examples = None

    def on_batch_begin(self, mode, step, logs=None):
        if mode == 'train':
            self.reporter.begin_step()

    def on_batch_end(self, mode, step, logs=None):
        if mode != 'train':
            return
        ex = self._batch_examples
        if ex is None:
            ex = (logs or {}).get('batch_size')
        tokens = None
        if ex is not None and self.tokens_per_example:
            tokens = int(ex) * int(self.tokens_per_example)
        self.reporter.end_step(examples=ex, tokens=tokens)
        if self.log_freq and (step + 1) % self.log_freq == 0:
            s = self.reporter.snapshot()
            line = (f"[telemetry] step {step + 1}: "
                    f"{s['examples_per_sec']:.1f} ex/s, "
                    f"{s['avg_step_ms']:.1f} ms/step, "
                    f"compile {s['compile_seconds_total']:.2f}s")
            numerics = s.get('numerics') or {}
            if numerics.get('grad_norm_global') is not None:
                line += f", |g|={numerics['grad_norm_global']:.3g}"
            if numerics.get('nonfinite_steps'):
                line += (f", nonfinite_steps="
                         f"{int(numerics['nonfinite_steps'])}")
            print(line)

    def observe_batch(self, batch):
        """Called by Model.fit with the raw batch to size examples/sec."""
        try:
            first = batch[0] if isinstance(batch, (list, tuple)) else batch
            self._batch_examples = int(first.shape[0])
        except Exception:
            self._batch_examples = None

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs['telemetry'] = self.snapshot()

    def snapshot(self):
        return self.reporter.snapshot()
