"""Global flags registry.

Reference parity: platform/flags.cc (35 gflags DEFINEs) +
pybind/global_value_getter_setter.cc — `paddle.set_flags/get_flags` and
`FLAGS_*` env seeding. Flags that map to XLA/jax knobs apply them on set.
"""
import os

_FLAGS = {
    'FLAGS_check_nan_inf': False,
    # numerics observatory (core/numerics.py): defer the NaN/Inf sync to
    # the step boundary (optimizer.step / numerics.flush) — device-side
    # flag accumulation + ONE host sync per step, with replay-based op
    # localization on a trip. Off = legacy raise-at-the-op semantics.
    'FLAGS_check_nan_inf_deferred': False,
    # ops kept in the eager replay journal per step (memory bound of the
    # deferred mode; the oldest ops drop first)
    'FLAGS_check_nan_inf_max_journal': 4096,
    # always-on tensor stats: compiled train steps thread grad/param
    # stat taps as extra outputs and publish ptpu_num_* gauges; the
    # eager optimizer publishes the same from .grad (one extra host
    # sync per step either way)
    'FLAGS_tensor_stats': False,
    'FLAGS_cudnn_deterministic': True,   # XLA is deterministic by default
    'FLAGS_allocator_strategy': 'pjrt',
    'FLAGS_fraction_of_gpu_memory_to_use': 0.92,
    'FLAGS_eager_delete_tensor_gb': 0.0,
    'FLAGS_use_pinned_memory': True,
    'FLAGS_benchmark': False,
    'FLAGS_selected_gpus': '',
    'FLAGS_selected_tpus': '',
    'FLAGS_sync_nccl_allreduce': True,
    'FLAGS_max_inplace_grad_add': 0,
    'FLAGS_conv_workspace_size_limit': 512,
    'FLAGS_paddle_num_threads': 1,
    'FLAGS_profile_start_step': -1,
    'FLAGS_profile_stop_step': -1,
    # route eligible nn.MultiHeadAttention through the Pallas flash kernel
    # (parity: the reference's fused_attention op swap-in)
    'FLAGS_use_flash_attention': True,
    # min sequence length for the flash route; below it XLA's fused dense
    # attention usually wins on TPU (tunable per model/shape)
    'FLAGS_flash_min_seq': 1024,
    # causal_attention (GPT path) through the packed transpose-free
    # kernel. Off by default: the packed kernel keeps FULL [L, H*D] K/V
    # slabs in VMEM — ~16 MB at GPT-1.3B shapes (L=2048, H*D=2048),
    # over the v5e VMEM budget; enable per-model after measuring (BERT
    # shapes are fine: 0.75 MB slabs)
    'FLAGS_flash_packed_causal': False,
    # MHA encoder flash via the packed transpose-free kernel (True) or
    # the BHLD-transposing kernel (False) — A/B knob for tuning
    'FLAGS_flash_packed_mha': True,
    # serving: ragged paged-attention route. None = auto (Pallas kernel
    # on TPU, dense lax fallback on CPU — transformer.py's flash-routing
    # pattern); True/False force a route (tests force True to run the
    # kernel body under interpret mode on the CPU mesh)
    'FLAGS_paged_attention_kernel': None,
    # fused Pallas primitives (ops/pallas/, TPP arXiv:2104.05755) —
    # same route convention as the paged kernel: None = auto (fused
    # Pallas kernel on TPU, reference jnp path on CPU), True/False
    # force (tests force True: the kernels run under interpret mode on
    # the CPU mesh). Route decisions are counted in
    # ptpu_pallas_{kernel,fallback}_invocations_total.
    # one-pass optimizer step + grad stats over flat buckets
    'FLAGS_fused_optimizer': None,
    # fused LayerNorm fwd+bwd (last-axis, affine)
    'FLAGS_fused_layer_norm': None,
    # fused bias+GELU and dropout+residual-add blocks
    'FLAGS_fused_elementwise': None,
    # wrap op-kernel exceptions with [operator < name > error] context
    # (enforce.h framing; off by default to keep exception types exact)
    'FLAGS_op_error_context': False,
    # XLA scheduling knobs for communication/compute overlap (ISSUE 10,
    # docs/performance.md#comm-overlap). None = leave the compiler
    # default; True/False edit XLA_FLAGS in the environment on set —
    # effective only BEFORE backend initialization, so launchers export
    # PTPU_COMM_OVERLAP=1 (honored at this module's import, below) or
    # set FLAGS_xla_*/the env tokens directly. Engine builds also call
    # bucketing.ensure_overlap_xla_flags(), which records intent and
    # updates the env for child processes; user pins are respected.
    'FLAGS_xla_latency_hiding_scheduler': None,
    'FLAGS_xla_async_collectives': None,
}

# FLAGS_* -> the xla option tokens they drive in XLA_FLAGS
_XLA_FLAG_TOKENS = {
    'FLAGS_xla_latency_hiding_scheduler': (
        'xla_tpu_enable_latency_hiding_scheduler',),
    'FLAGS_xla_async_collectives': (
        'xla_tpu_enable_async_collective_fusion',),
}


def _tpu_plausible():
    """True when this process could plausibly initialize a TPU backend.
    The xla_tpu_* option names only exist in TPU-enabled XLA builds —
    a CPU-only jaxlib ABORTS the process on unknown XLA_FLAGS tokens,
    and the env is inherited by every subprocess, so exporting them
    unconditionally would be a landmine."""
    plat = os.environ.get('JAX_PLATFORMS', '')
    if plat:
        return 'tpu' in plat.lower()
    try:
        import importlib.util
        return importlib.util.find_spec('libtpu') is not None
    except Exception:
        return False


def _apply_xla_flag(name, value):
    """Reflect a True/False XLA flag into the XLA_FLAGS environment
    (replacing any prior token for the same option). The backend reads
    XLA_FLAGS once at initialization; a set after init is recorded in
    the registry but cannot reach the already-built client. On a
    non-TPU platform the registry records the value but the TPU-only
    tokens are NOT exported (see _tpu_plausible)."""
    if value is None or not _tpu_plausible():
        return
    val = 'true' if value else 'false'
    toks = [t for t in os.environ.get('XLA_FLAGS', '').split()
            if not any(t.startswith(f'--{opt}=')
                       for opt in _XLA_FLAG_TOKENS[name])]
    toks += [f'--{opt}={val}' for opt in _XLA_FLAG_TOKENS[name]]
    os.environ['XLA_FLAGS'] = ' '.join(toks)


def _seed_from_env():
    for k in list(_FLAGS):
        if k in os.environ:
            v = os.environ[k]
            cur = _FLAGS[k]
            if isinstance(cur, bool):
                _FLAGS[k] = v.lower() in ('1', 'true', 'yes')
            elif isinstance(cur, int):
                _FLAGS[k] = int(v)
            elif isinstance(cur, float):
                _FLAGS[k] = float(v)
            elif cur is None and v.lower() in ('1', 'true', 'yes',
                                               '0', 'false', 'no'):
                # tri-state flags (None = auto): env seeds a real bool
                _FLAGS[k] = v.lower() in ('1', 'true', 'yes')
            else:
                _FLAGS[k] = v
            if k in _XLA_FLAG_TOKENS:
                _apply_xla_flag(k, _FLAGS[k])


_seed_from_env()

# comm/compute overlap (ISSUE 10): the XLA scheduling flags only reach
# the compiler when exported BEFORE backend initialization, and engine
# builds necessarily run after it — so the launcher contract
# `PTPU_COMM_OVERLAP=1` is honored HERE, at first import of this
# module, flipping any still-unset scheduling flag. Explicit
# FLAGS_xla_* env settings were seeded above and take precedence.
if os.environ.get('PTPU_COMM_OVERLAP', '').lower() in ('1', 'true',
                                                       'yes'):
    for _k in _XLA_FLAG_TOKENS:
        if _FLAGS.get(_k) is None:
            _FLAGS[_k] = True
            _apply_xla_flag(_k, True)


def set_flags(flags):
    """Parity: paddle.set_flags({'FLAGS_x': v})."""
    for k, v in flags.items():
        _FLAGS[k] = v
        if k in _XLA_FLAG_TOKENS:
            _apply_xla_flag(k, v)


def get_flags(keys):
    """Parity: paddle.get_flags — str or list → dict."""
    if isinstance(keys, str):
        keys = [keys]
    return {k: _FLAGS.get(k) for k in keys}


def flag(name, default=None):
    return _FLAGS.get(name, default)
