"""Global flags registry.

Reference parity: platform/flags.cc (35 gflags DEFINEs) +
pybind/global_value_getter_setter.cc — `paddle.set_flags/get_flags` and
`FLAGS_*` env seeding. Flags that map to XLA/jax knobs apply them on set.
"""
import os

_FLAGS = {
    'FLAGS_check_nan_inf': False,
    # numerics observatory (core/numerics.py): defer the NaN/Inf sync to
    # the step boundary (optimizer.step / numerics.flush) — device-side
    # flag accumulation + ONE host sync per step, with replay-based op
    # localization on a trip. Off = legacy raise-at-the-op semantics.
    'FLAGS_check_nan_inf_deferred': False,
    # ops kept in the eager replay journal per step (memory bound of the
    # deferred mode; the oldest ops drop first)
    'FLAGS_check_nan_inf_max_journal': 4096,
    # always-on tensor stats: compiled train steps thread grad/param
    # stat taps as extra outputs and publish ptpu_num_* gauges; the
    # eager optimizer publishes the same from .grad (one extra host
    # sync per step either way)
    'FLAGS_tensor_stats': False,
    'FLAGS_cudnn_deterministic': True,   # XLA is deterministic by default
    'FLAGS_allocator_strategy': 'pjrt',
    'FLAGS_fraction_of_gpu_memory_to_use': 0.92,
    'FLAGS_eager_delete_tensor_gb': 0.0,
    'FLAGS_use_pinned_memory': True,
    'FLAGS_benchmark': False,
    'FLAGS_selected_gpus': '',
    'FLAGS_selected_tpus': '',
    'FLAGS_sync_nccl_allreduce': True,
    'FLAGS_max_inplace_grad_add': 0,
    'FLAGS_conv_workspace_size_limit': 512,
    'FLAGS_paddle_num_threads': 1,
    'FLAGS_profile_start_step': -1,
    'FLAGS_profile_stop_step': -1,
    # route eligible nn.MultiHeadAttention through the Pallas flash kernel
    # (parity: the reference's fused_attention op swap-in)
    'FLAGS_use_flash_attention': True,
    # min sequence length for the flash route; below it XLA's fused dense
    # attention usually wins on TPU (tunable per model/shape)
    'FLAGS_flash_min_seq': 1024,
    # causal_attention (GPT path) through the packed transpose-free
    # kernel. Off by default: the packed kernel keeps FULL [L, H*D] K/V
    # slabs in VMEM — ~16 MB at GPT-1.3B shapes (L=2048, H*D=2048),
    # over the v5e VMEM budget; enable per-model after measuring (BERT
    # shapes are fine: 0.75 MB slabs)
    'FLAGS_flash_packed_causal': False,
    # MHA encoder flash via the packed transpose-free kernel (True) or
    # the BHLD-transposing kernel (False) — A/B knob for tuning
    'FLAGS_flash_packed_mha': True,
    # serving: ragged paged-attention route. None = auto (Pallas kernel
    # on TPU, dense lax fallback on CPU — transformer.py's flash-routing
    # pattern); True/False force a route (tests force True to run the
    # kernel body under interpret mode on the CPU mesh)
    'FLAGS_paged_attention_kernel': None,
    # fused Pallas primitives (ops/pallas/, TPP arXiv:2104.05755) —
    # same route convention as the paged kernel: None = auto (fused
    # Pallas kernel on TPU, reference jnp path on CPU), True/False
    # force (tests force True: the kernels run under interpret mode on
    # the CPU mesh). Route decisions are counted in
    # ptpu_pallas_{kernel,fallback}_invocations_total.
    # one-pass optimizer step + grad stats over flat buckets
    'FLAGS_fused_optimizer': None,
    # fused LayerNorm fwd+bwd (last-axis, affine)
    'FLAGS_fused_layer_norm': None,
    # fused bias+GELU and dropout+residual-add blocks
    'FLAGS_fused_elementwise': None,
    # wrap op-kernel exceptions with [operator < name > error] context
    # (enforce.h framing; off by default to keep exception types exact)
    'FLAGS_op_error_context': False,
}


def _seed_from_env():
    for k in list(_FLAGS):
        if k in os.environ:
            v = os.environ[k]
            cur = _FLAGS[k]
            if isinstance(cur, bool):
                _FLAGS[k] = v.lower() in ('1', 'true', 'yes')
            elif isinstance(cur, int):
                _FLAGS[k] = int(v)
            elif isinstance(cur, float):
                _FLAGS[k] = float(v)
            else:
                _FLAGS[k] = v


_seed_from_env()


def set_flags(flags):
    """Parity: paddle.set_flags({'FLAGS_x': v})."""
    for k, v in flags.items():
        _FLAGS[k] = v


def get_flags(keys):
    """Parity: paddle.get_flags — str or list → dict."""
    if isinstance(keys, str):
        keys = [keys]
    return {k: _FLAGS.get(k) for k in keys}


def flag(name, default=None):
    return _FLAGS.get(name, default)
