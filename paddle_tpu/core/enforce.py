"""Error enforcement machinery.

Reference parity: paddle/fluid/platform/enforce.h (PADDLE_ENFORCE* macros
→ EnforceNotMet carrying an error summary + context) and
platform/errors.h's typed taxonomy (InvalidArgument, NotFound,
OutOfRange, AlreadyExists, PermissionDenied, PreconditionNotMet,
Unimplemented, Unavailable, Fatal, External).

TPU-native shape: plain Python exception classes (jax/XLA surface their
own compiled-program errors; this tier covers the framework's own
argument/state validation) plus `enforce`/`enforce_eq`-style helpers the
op layer uses to attach op context to failures.
"""


class EnforceNotMet(RuntimeError):
    """Parity: enforce.h EnforceNotMet — the base enforcement failure."""

    def __init__(self, message, error_type='Error'):
        super().__init__(message)
        self.error_type = error_type
        self.message = message

    def __str__(self):
        return f"{self.error_type}: {self.message}"


class InvalidArgumentError(EnforceNotMet):
    def __init__(self, message):
        super().__init__(message, 'InvalidArgumentError')


class NotFoundError(EnforceNotMet):
    def __init__(self, message):
        super().__init__(message, 'NotFoundError')


class OutOfRangeError(EnforceNotMet):
    def __init__(self, message):
        super().__init__(message, 'OutOfRangeError')


class AlreadyExistsError(EnforceNotMet):
    def __init__(self, message):
        super().__init__(message, 'AlreadyExistsError')


class PermissionDeniedError(EnforceNotMet):
    def __init__(self, message):
        super().__init__(message, 'PermissionDeniedError')


class PreconditionNotMetError(EnforceNotMet):
    def __init__(self, message):
        super().__init__(message, 'PreconditionNotMetError')


class UnimplementedError(EnforceNotMet):
    def __init__(self, message):
        super().__init__(message, 'UnimplementedError')


class UnavailableError(EnforceNotMet):
    def __init__(self, message):
        super().__init__(message, 'UnavailableError')


class ExecutionTimeoutError(EnforceNotMet):
    def __init__(self, message):
        super().__init__(message, 'ExecutionTimeoutError')


class FatalError(EnforceNotMet):
    def __init__(self, message):
        super().__init__(message, 'FatalError')


class ExternalError(EnforceNotMet):
    def __init__(self, message):
        super().__init__(message, 'ExternalError')


def enforce(condition, message, error_cls=EnforceNotMet):
    """Parity: PADDLE_ENFORCE(cond, msg)."""
    if not condition:
        raise error_cls(message)


def enforce_eq(a, b, message=None, error_cls=InvalidArgumentError):
    """Parity: PADDLE_ENFORCE_EQ."""
    if a != b:
        raise error_cls(message or f"expected {a!r} == {b!r}")


def enforce_gt(a, b, message=None, error_cls=InvalidArgumentError):
    if not a > b:
        raise error_cls(message or f"expected {a!r} > {b!r}")


def enforce_ge(a, b, message=None, error_cls=InvalidArgumentError):
    if not a >= b:
        raise error_cls(message or f"expected {a!r} >= {b!r}")


def enforce_not_none(v, message=None, error_cls=NotFoundError):
    if v is None:
        raise error_cls(message or "value is None")
    return v


def op_error_context(op_name, exc):
    """Wrap an exception raised inside an op kernel with the op's name —
    the [operator < name > error] framing of enforce.h's
    GetCurrentTraceBackString reports."""
    msg = f"[operator < {op_name} > error] {type(exc).__name__}: {exc}"
    err = EnforceNotMet(msg)
    err.__cause__ = exc
    return err
