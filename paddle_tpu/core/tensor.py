"""paddle_tpu.Tensor — eager tensor wrapping a jax.Array.

Reference parity: the dygraph VarBase (paddle/fluid/imperative/layer.h) with
paddle's Tensor method surface (python/paddle/fluid/dygraph/math_op_patch.py and
python/paddle/tensor/*). Device memory, layout, and transfers are owned by
jax/PJRT; autograd is the tape in core/autograd.py.

`stop_gradient` defaults to True like paddle's dygraph VarBase; parameters are
created with stop_gradient=False.
"""
import weakref

import numpy as np
import jax
import jax.numpy as jnp

from . import autograd, dtypes


class Tensor:
    __slots__ = ('_data', 'stop_gradient', 'grad', '_node', 'name',
                 'persistable', 'is_distributed', '__weakref__', '__dict__')

    def __init__(self, data, dtype=None, stop_gradient=True, name=None):
        if isinstance(data, Tensor):
            data = data._data
        if dtype is not None:
            dtype = dtypes.convert_dtype(dtype)
        if isinstance(data, (jnp.ndarray, jax.Array)) or hasattr(data, 'aval'):
            self._data = data if dtype is None else data.astype(dtype)
        else:
            arr = np.asarray(data)
            if dtype is None and arr.dtype == np.float64:
                dtype = jnp.float32  # paddle default fp32
            self._data = jnp.asarray(arr, dtype=dtype)
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self.name = name
        self.persistable = False
        self.is_distributed = False

    # -- basic properties ---------------------------------------------------
    @property
    def data(self):
        d = self.__dict__
        if '_lazy_error' in d:
            raise RuntimeError(
                "this tensor's lazy fusion window failed to execute"
            ) from d['_lazy_error']
        if d.get('_lazy'):
            from . import lazy
            lazy.flush()                # materialize the fusion window
        return self._data

    @data.setter
    def data(self, value):
        self._data = value._data if isinstance(value, Tensor) else value

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def T(self):
        from .. import ops
        return ops.manip.transpose(self, list(range(self.ndim))[::-1])

    @property
    def place(self):
        try:
            return str(list(self._data.devices())[0])
        except Exception:
            return 'traced'

    def numel(self):
        return self.size

    # -- conversions --------------------------------------------------------
    def numpy(self):
        return np.asarray(self.data)

    def item(self):
        return self.data.item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def astype(self, dtype):
        from .. import ops
        return ops.manip.cast(self, dtype)

    cast = astype

    def cpu(self):
        return Tensor(jax.device_get(self.data), stop_gradient=self.stop_gradient)

    def pin_memory(self):
        return self

    def cuda(self, *a, **k):  # paddle API compat; TPU is the device
        return self

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        autograd.backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self):
        self.grad = None

    def detach(self):
        t = Tensor(self.data, stop_gradient=True)
        return t

    def clone(self):
        from .. import ops
        return ops.math.assign(self)

    def register_hook(self, hook):
        """Parity: Tensor.register_hook — called with the gradient when it
        reaches this tensor during backward; a non-None return replaces the
        gradient. Returns a removable handle."""
        if not hasattr(self, '_grad_hooks'):
            self._grad_hooks = {}
        hid = len(self._grad_hooks)
        self._grad_hooks[hid] = hook

        class _Handle:
            def __init__(self, owner, hid):
                self._owner, self._hid = owner, hid

            def remove(self):
                self._owner._grad_hooks.pop(self._hid, None)
        return _Handle(self, hid)

    # -- in-place mutation (eager only) -------------------------------------
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value.data
        self._data = jnp.asarray(value, dtype=self._data.dtype).reshape(self._data.shape)
        return self

    def copy_(self, other):
        return self.set_value(other)

    def fill_(self, value):
        self._data = jnp.full_like(self.data, value)
        return self

    def zero_(self):
        self._data = jnp.zeros_like(self.data)
        return self

    def scale_(self, scale):
        self._data = self.data * scale
        return self

    def add_(self, other):
        o = other.data if isinstance(other, Tensor) else other
        self._data = self.data + o
        return self

    def subtract_(self, other):
        o = other.data if isinstance(other, Tensor) else other
        self._data = self.data - o
        return self

    def multiply_(self, other):
        o = other.data if isinstance(other, Tensor) else other
        self._data = self.data * o
        return self

    def clip_(self, min=None, max=None):
        self._data = jnp.clip(self.data, min, max)
        return self

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, idx):
        from .. import ops
        return ops.manip.getitem(self, idx)

    def __setitem__(self, idx, value):
        v = value.data if isinstance(value, Tensor) else value
        self._data = self.data.at[idx].set(v)

    def __len__(self):
        if not self._data.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- repr ---------------------------------------------------------------
    def __repr__(self):
        try:
            body = repr(np.asarray(self.data))
        except Exception:
            body = f"<traced {self._data.shape} {self._data.dtype}>"
        return (f"Tensor(shape={self.shape}, dtype={dtypes.dtype_name(self.dtype)}, "
                f"stop_gradient={self.stop_gradient},\n       {body})")

    def __bool__(self):
        return bool(self.data)

    def __int__(self):
        # paddle semantics: any size-1 tensor converts
        return int(self.data.item())

    def __float__(self):
        return float(self.data.item())

    def __hash__(self):
        return id(self)


def _install_operators():
    """Patch arithmetic dunders onto Tensor (parity: math_op_patch.py)."""
    from .. import ops
    m = ops.math

    def binop(fn, swap=False):
        def impl(self, other):
            if swap:
                return fn(other, self)
            return fn(self, other)
        return impl

    Tensor.__add__ = binop(m.add)
    Tensor.__radd__ = binop(m.add, swap=True)
    Tensor.__sub__ = binop(m.subtract)
    Tensor.__rsub__ = binop(m.subtract, swap=True)
    Tensor.__mul__ = binop(m.multiply)
    Tensor.__rmul__ = binop(m.multiply, swap=True)
    Tensor.__truediv__ = binop(m.divide)
    Tensor.__rtruediv__ = binop(m.divide, swap=True)
    Tensor.__floordiv__ = binop(m.floor_divide)
    Tensor.__mod__ = binop(m.remainder)
    Tensor.__pow__ = binop(m.pow)
    Tensor.__rpow__ = binop(m.pow, swap=True)
    Tensor.__matmul__ = binop(m.matmul)
    Tensor.__neg__ = lambda self: m.scale(self, -1.0)
    Tensor.__abs__ = lambda self: m.abs(self)
    Tensor.__eq__ = binop(m.equal)
    Tensor.__ne__ = binop(m.not_equal)
    Tensor.__lt__ = binop(m.less_than)
    Tensor.__le__ = binop(m.less_equal)
    Tensor.__gt__ = binop(m.greater_than)
    Tensor.__ge__ = binop(m.greater_equal)
    Tensor.__invert__ = lambda self: m.logical_not(self)

    # Method surface (subset mirrored from python/paddle/tensor/__init__.py).
    method_table = {
        'add': m.add, 'subtract': m.subtract, 'multiply': m.multiply,
        'divide': m.divide, 'matmul': m.matmul, 'pow': m.pow, 'abs': m.abs,
        'exp': m.exp, 'log': m.log, 'sqrt': m.sqrt, 'rsqrt': m.rsqrt,
        'square': m.square, 'sin': m.sin, 'cos': m.cos, 'tanh': m.tanh,
        'sigmoid': m.sigmoid, 'floor': m.floor, 'ceil': m.ceil,
        'round': m.round, 'sign': m.sign, 'reciprocal': m.reciprocal,
        'sum': m.sum, 'mean': m.mean, 'max': m.max, 'min': m.min,
        'prod': m.prod, 'argmax': m.argmax, 'argmin': m.argmin,
        'argsort': m.argsort, 'sort': m.sort, 'topk': m.topk,
        'cumsum': m.cumsum, 'clip': m.clip, 'scale': m.scale,
        'maximum': m.maximum, 'minimum': m.minimum, 'equal': m.equal,
        'not_equal': m.not_equal, 'less_than': m.less_than,
        'less_equal': m.less_equal, 'greater_than': m.greater_than,
        'greater_equal': m.greater_equal, 'equal_all': m.equal_all,
        'allclose': m.allclose, 'isnan': m.isnan, 'isinf': m.isinf,
        'isfinite': m.isfinite, 'logical_and': m.logical_and,
        'logical_or': m.logical_or, 'logical_not': m.logical_not,
        'logical_xor': m.logical_xor, 'norm': m.norm, 'dot': m.dot,
        'dist': m.dist, 'floor_divide': m.floor_divide,
        'remainder': m.remainder, 'mod': m.remainder, 'kron': m.kron,
        'erf': m.erf, 'lgamma': m.lgamma, 'digamma': m.digamma,
        'trunc': m.trunc, 'log2': m.log2, 'log10': m.log10,
        'log1p': m.log1p, 'expm1': m.expm1, 'any': m.any, 'all': m.all,
        'mm': m.matmul, 'bmm': m.bmm, 'inner': m.inner, 'outer': m.outer,
        'median': m.median, 'mode': m.mode, 'nonzero': m.nonzero,
        'std': m.std, 'var': m.var, 'bitwise_and': m.bitwise_and,
        'bitwise_or': m.bitwise_or, 'bitwise_xor': m.bitwise_xor,
        'bitwise_not': m.bitwise_not,
    }
    mp = ops.manip
    method_table.update({
        'reshape': mp.reshape, 'transpose': mp.transpose,
        'squeeze': mp.squeeze, 'unsqueeze': mp.unsqueeze,
        'flatten': mp.flatten, 'split': mp.split, 'chunk': mp.chunk,
        'concat_with': None, 'tile': mp.tile, 'expand': mp.expand,
        'expand_as': mp.expand_as, 'flip': mp.flip, 'roll': mp.roll,
        'gather': mp.gather, 'gather_nd': mp.gather_nd,
        'scatter': mp.scatter, 'index_select': mp.index_select,
        'masked_select': mp.masked_select, 'slice': mp.slice,
        'unbind': mp.unbind, 'broadcast_to': mp.broadcast_to,
        'tril': mp.tril, 'triu': mp.triu, 'where_self': None,
        'unstack': mp.unstack, 'unique': mp.unique,
        'index_sample': mp.index_sample, 'diagonal': mp.diagonal,
    })
    for name, fn in method_table.items():
        if fn is not None:
            setattr(Tensor, name, fn)

    # paddle's inplace-suffixed variants: compute then overwrite storage
    # (inplace_rebind raises under an active autograd graph — see its doc)
    def make_inplace(f):
        def impl(self, *a, **k):
            inplace_rebind(self, f(self, *a, **k))
            return self
        return impl
    for name in ('exp', 'sqrt', 'rsqrt', 'reciprocal', 'tanh', 'sigmoid',
                 'abs', 'floor', 'ceil', 'round', 'clip', 'scale',
                 'reshape', 'squeeze', 'unsqueeze', 'flatten'):
        base = method_table.get(name) or getattr(Tensor, name, None)
        if base is not None and not hasattr(Tensor, name + '_'):
            setattr(Tensor, name + '_', make_inplace(base))


def inplace_rebind(x, out):
    """Shared tail of every `op_`-spelled in-place API: JAX buffers are
    immutable, so the new value is computed out-of-place and the input
    tensor's buffer is rebound to it. Returns `x` itself (reference
    parity: the in-place result IS the input variable), so chained
    in-place calls keep aliasing one tensor.

    Under autograd the alias is grafted into the tape: `x` takes over
    the op's output slot (later uses of x route cotangents through the
    op), and a snapshot tensor holding x's pre-op identity takes x's
    place both as the op's recorded input and as the old producer's
    output — so the chain x_old -> op -> x stays exact. Two loud-error
    cases match the reference's eager inplace rules: a grad-requiring
    LEAF can't be in-placed ("Leaf Var that doesn't stop gradient can't
    use inplace strategy"), and mutating a tensor some EARLIER op
    recorded for backward raises at backward() time via version
    counters (autograd.Node.input_versions).
    """
    if not isinstance(x, Tensor):
        return out
    node = getattr(out, '_node', None)
    if node is None:
        # nothing was traced (no_grad, or x doesn't require grad):
        # plain buffer swap, but still bump the version so any earlier
        # recording that DID capture x errors loudly at backward()
        x._data = out.data
        x._version = getattr(x, '_version', 0) + 1
        return x
    if x._node is None and not x.stop_gradient:
        raise RuntimeError(
            "a leaf Tensor that requires grad can't use the in-place "
            "strategy (reference: the eager inplace leaf check) — use "
            "the out-of-place spelling (drop the trailing '_'), or "
            "wrap the call in paddle.no_grad().")
    snap = Tensor(x._data, stop_gradient=x.stop_gradient)
    snap._node = x._node
    snap._version = getattr(x, '_version', 0)
    if snap._node is not None:
        # the old producer's output slot now belongs to the snapshot
        for i, ref in enumerate(snap._node.outputs):
            if ref() is x:
                snap._node.outputs[i] = weakref.ref(snap)
                break
    # the new op consumed the PRE-op value: its recorded input becomes
    # the snapshot (node.inputs holds strong refs, keeping snap alive)
    for i, t in enumerate(node.inputs):
        if t is x:
            node.inputs[i] = snap
    # and x becomes the op's output alias
    for i, ref in enumerate(node.outputs):
        if ref() is out:
            node.outputs[i] = weakref.ref(x)
            break
    x._data = out.data
    x._node = node
    x._version = snap._version + 1
    return x


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """Parity: paddle.to_tensor."""
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)
