"""Global RNG state.

Reference parity: paddle/fluid/framework/generator.h (DefaultCPUGenerator /
GetDefaultCUDAGenerator:118-126) keeps per-device seeded generators fanned out by
`paddle.seed`. The TPU-native design keeps ONE functional `jax.random` key plus a
monotonically increasing fold counter: every draw folds the counter into the base
key, so draws are reproducible given the seed yet distinct per call. The counter
is a Python int, so it is static under `jax.jit` tracing — a traced function that
draws K times always folds 0..K-1 relative to the key active at trace time, which
is exactly the semantics needed for functional train steps.

`rng_guard` temporarily swaps the base key — used by the functional bridge
(paddle_tpu.jit) to thread an explicit per-step key, and by the fleet RNG-state
tracker (reference: fleet/meta_parallel/parallel_layers/random.py:24) for
TP-consistent dropout.
"""
import contextlib
import jax


class _GeneratorState:
    """Lazy: the jax key materializes on first draw, NOT at import —
    importing paddle_tpu must not initialize the device backend (launcher /
    utility processes share hosts with the trainer, and a tunneled TPU
    admits one client)."""

    def __init__(self, seed=0):
        self._seed = seed
        self._key = None
        self.counter = 0

    @property
    def key(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)
        return self._key

    @key.setter
    def key(self, k):
        self._key = k

    def next_key(self):
        k = jax.random.fold_in(self.key, self.counter)
        self.counter += 1
        return k


_state = _GeneratorState(seed=0)


def seed(s):
    """Set the global RNG seed (parity: paddle.seed)."""
    global _state
    _state = _GeneratorState(int(s))
    return _state


def get_rng_state():
    return (_state.key, _state.counter)


def set_rng_state(state):
    global _state
    key, counter = state
    _state = _GeneratorState(0)
    _state.key = key
    _state.counter = counter


def next_key():
    """Draw a fresh PRNG key from the global stream."""
    return _state.next_key()


@contextlib.contextmanager
def rng_guard(key):
    """Temporarily replace the global key (e.g. with a traced key under jit)."""
    global _state
    saved = _state
    _state = _GeneratorState(0)
    _state.key = key
    try:
        yield
    finally:
        _state = saved
