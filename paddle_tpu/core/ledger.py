"""Step-time ledger & MFU observatory (ISSUE 16).

One reconciled account of where a training step's wall-clock goes,
assembled from the per-pillar signals the earlier PRs already publish:

  * host gap / residue / blocked  — core/async_step.HostGapMonitor
    (PR 13): rolling per-step means of the host time between dispatches.
  * exposed comm                  — core/bucketing.comm_snapshot
    (PR 10): the trace-time comm model's seconds NOT hidden under
    compute, per engine.
  * pipeline bubble               — spmd_pipeline.schedule_model
    (PR 14): modeled bubble_fraction of the device-busy span.
  * compute                       — the remainder.

Decomposition (per mean step, all seconds):

    wall    = HostGapMonitor step_interval_seconds (dispatch-to-dispatch)
    gap     = host_gap_seconds        (host gating the device)
    residue = host_residue_seconds    (unattributed host wall; surfaced
                                       separately, scheduler noise on
                                       shared CPU hosts)
    exposed = comm_overlap exposed_comm_seconds for this engine (modeled)
    bubble  = bubble_fraction * (wall - gap - residue - exposed)
              (pipeline engines only: the schedule's idle ticks eat the
               device-busy span, not the host span)
    compute = wall - gap - residue - exposed - bubble, clamped >= 0

The five components sum to `wall` by construction (reconciled_fraction
== 1.0) except when the modeled terms exceed the measured wall — then
compute clamps at 0 and reconciled_fraction > 1 flags the overrun
instead of hiding it.

On top sits analytic model-FLOPs accounting (Megatron arXiv:2104.04473;
recompute factors per arXiv:2205.05198):

    model_flops/step = 6 * n_params * tokens
                       + 12 * layers * hidden * seq_len * tokens
    (fwd+bwd; the attention term needs the arch hints — engines learn
    tokens/seq_len from batch shapes, n_params from their param trees,
    and layers/hidden via ledger.configure()).

    hardware_flops = model_flops * (1 + r/3) where r is the fraction of
    the forward re-executed in the backward under the active remat
    policy: none/dots -> 0 (dot outputs are saved; only cheap
    elementwise is re-run), attn_mlp_boundaries -> the attention-score
    share of the forward (QK^T and the probs*V contraction are re-run;
    the boundary-tagged matmul outputs are saved), full -> 1.

    model TFLOP/s = model_flops / wall / 1e12; MFU = model TFLOP/s /
    per-device peak (PEAK_TFLOPS_BF16, by TPU generation). On CPU
    dryruns there is no meaningful peak: mfu is None and the record
    carries absolute TFLOP/s only.

Everything lands as `ptpu_ledger_*` gauges (labeled by engine) and is
read back by `ledger_snapshot()` for `StepTelemetry.snapshot()['ledger']`,
bench records, and `tools/health_dump.py ledger`.

The StragglerDetector is the DivergenceSentinel of wall time: every
`check_every` dispatches (opt-in via PTPU_STRAGGLER_CHECK=1) each rank
allgathers its rolling mean step wall over the host-collective group;
ranks slower than `threshold` x the median get flagged, gauged, and
dumped as a `straggler_report` artifact through log_util + write_report.
"""
import os
import time

import numpy as np

__all__ = ['StepLedger', 'StragglerDetector', 'ledger_snapshot',
           'configure', 'model_flops_per_step', 'recompute_factor',
           'resolve_peak_tflops', 'PEAK_TFLOPS_BF16']


# ---------------------------------------------------------------------------
# per-device peak table (bf16/int8-dense peak TFLOP/s per chip, by TPU
# generation — docs/observability.md#step-time-ledger)
# ---------------------------------------------------------------------------
PEAK_TFLOPS_BF16 = (
    ('v6', 918.0),          # Trillium
    ('trillium', 918.0),
    ('v5p', 459.0),
    ('v5 lite', 197.0),     # device_kind 'TPU v5 lite'
    ('v5litepod', 197.0),
    ('v5e', 197.0),
    ('v4', 275.0),
    ('v3', 123.0),
    ('v2', 45.0),
)


def resolve_peak_tflops(device_kind=None):
    """Per-chip bf16 peak for the local accelerator, or None when it is
    not a TPU (CPU dryrun: absolute TFLOP/s only, no MFU)."""
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:
            return None
    k = str(device_kind).lower()
    if 'tpu' not in k and 'trillium' not in k:
        return None
    for sub, peak in PEAK_TFLOPS_BF16:
        if sub in k:
            return peak
    return None


# ---------------------------------------------------------------------------
# analytic FLOPs
# ---------------------------------------------------------------------------
def model_flops_per_step(n_params, tokens, layers=0, hidden=0,
                         seq_len=0, arch='gpt'):
    """(total_model_flops, attn_flops) per step, fwd+bwd.

    6*N*T counts every matmul touching a parameter (2 flops/MAC x
    fwd + 2x bwd); the attention-score term 12*l*h*L*T adds the
    parameter-free QK^T and probs*V contractions. GPT and BERT share
    the formula (bidirectional attention has the same contraction
    shape); `arch` is recorded, not branched on.
    """
    dense = 6.0 * float(n_params) * float(tokens)
    attn = 0.0
    if layers and hidden and seq_len:
        attn = 12.0 * float(layers) * float(hidden) \
            * float(seq_len) * float(tokens)
    return dense + attn, attn


def recompute_factor(policy, total_flops=0.0, attn_flops=0.0):
    """Fraction r of the forward pass re-executed in the backward under
    the resolved remat policy (arXiv:2205.05198: full recompute turns
    the 3-pass step into 4 passes -> hardware_flops = model * (1+r/3)).
    """
    if policy in (None, 'none', False):
        return 0.0
    if policy == 'dots':
        # dot outputs saveable: only elementwise re-runs, ~0 matmul flops
        return 0.0
    if policy == 'attn_mlp_boundaries':
        # boundary tags save every parameter matmul output; the
        # attention-score contractions between them are re-derived
        return (attn_flops / total_flops) if total_flops else 0.0
    # 'full' (and the pipeline 'recompute' memory mode): one extra fwd
    return 1.0


def count_params(tree):
    """Total element count over a pytree / dict of arrays or Tensors."""
    try:
        import jax
        n = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            data = getattr(leaf, 'data', leaf)
            n += int(getattr(data, 'size', 0) or 0)
        return n
    except Exception:
        return 0


# ---------------------------------------------------------------------------
# arch hints (bench / user code names what the engine cannot infer)
# ---------------------------------------------------------------------------
_arch_hints = {}


def configure(engine, **hints):
    """Attach arch hints (layers=, hidden=, seq_len=, arch=, n_params=,
    tokens_per_step=, peak_tflops=) to an engine's ledger by name —
    picked up at the next publish."""
    _arch_hints.setdefault(engine, {}).update(
        {k: v for k, v in hints.items() if v is not None})


class StepLedger:
    """Per-engine step-time account. Engines construct one beside their
    HostGapMonitor, call `observe_batch(shape)` in the dispatch hot path
    (shape metadata only — no sync), and `publish()` from flush()."""

    def __init__(self, engine, gap=None, params_fn=None, remat_policy=None,
                 arch='gpt', layers=0, hidden=0, seq_len=0,
                 bubble_fraction_fn=None):
        self.engine = engine
        self._gap = gap
        self._params_fn = params_fn
        self._n_params = None           # resolved lazily, once
        self.remat_policy = remat_policy
        self.arch = arch
        self.layers, self.hidden, self.seq_len = layers, hidden, seq_len
        self._bubble_fn = bubble_fraction_fn
        self.tokens_per_step = 0
        self.steps = 0
        self.straggler = StragglerDetector(engine=engine) \
            if os.environ.get('PTPU_STRAGGLER_CHECK') else None

    # -- hot path -----------------------------------------------------------
    def observe_batch(self, shape):
        """Record tokens/seq from a batch array's shape (metadata only)
        and run the opt-in periodic straggler check."""
        self.steps += 1
        try:
            if len(shape) >= 2:
                self.tokens_per_step = int(shape[0]) * int(shape[1])
                self.seq_len = self.seq_len or int(shape[1])
            elif len(shape) == 1:
                self.tokens_per_step = int(shape[0])
        except Exception:
            pass
        if self.straggler is not None:
            try:
                self.straggler.maybe_check(self.steps, self._gap)
            except Exception:
                pass

    # -- account ------------------------------------------------------------
    def _hints(self):
        h = dict(_arch_hints.get(self.engine, ()))
        return h

    def account(self):
        """The reconciled per-step account dict, or None before the gap
        monitor has a full step interval."""
        snap = self._gap.snapshot() if self._gap is not None else {}
        wall = float(snap.get('step_interval_seconds') or 0.0)
        if wall <= 0.0:
            return None
        h = self._hints()
        gap = min(float(snap.get('host_gap_seconds') or 0.0), wall)
        residue = min(float(snap.get('host_residue_seconds') or 0.0),
                      max(wall - gap, 0.0))
        exposed = min(self._exposed_comm_seconds(),
                      max(wall - gap - residue, 0.0))
        busy = max(wall - gap - residue - exposed, 0.0)
        bf = self._bubble_fraction()
        bubble = busy * bf if bf else 0.0
        compute = max(busy - bubble, 0.0)
        total = compute + exposed + bubble + gap + residue
        out = {
            'engine': self.engine,
            'steps': self.steps or int(snap.get('steps') or 0),
            'wall_seconds': wall,
            'components': {
                'compute': compute,
                'exposed_comm': exposed,
                'bubble': bubble,
                'host_gap': gap,
                'residue': residue,
            },
            'reconciled_fraction': (total / wall) if wall else 0.0,
            'blocked_wait_seconds':
                float(snap.get('blocked_wait_seconds') or 0.0),
        }
        out.update(self._flops_account(wall, h))
        return out

    def _exposed_comm_seconds(self):
        try:
            from . import bucketing as B
            ov = (B.comm_snapshot().get('comm_overlap') or {}).get(
                self.engine)
            if not ov:
                return 0.0
            return max(float(ov.get('exposed_comm_seconds') or 0.0), 0.0)
        except Exception:
            return 0.0

    def _bubble_fraction(self):
        if self._bubble_fn is None:
            return 0.0
        try:
            return max(float(self._bubble_fn() or 0.0), 0.0)
        except Exception:
            return 0.0

    def _flops_account(self, wall, h):
        n_params = h.get('n_params')
        if n_params is None:
            if self._n_params is None and self._params_fn is not None:
                try:
                    self._n_params = int(self._params_fn() or 0)
                except Exception:
                    self._n_params = 0
            n_params = self._n_params or 0
        tokens = int(h.get('tokens_per_step') or self.tokens_per_step or 0)
        layers = int(h.get('layers') or self.layers or 0)
        hidden = int(h.get('hidden') or self.hidden or 0)
        seq_len = int(h.get('seq_len') or self.seq_len or 0)
        arch = h.get('arch') or self.arch
        policy = h.get('remat_policy') or self.remat_policy
        total, attn = model_flops_per_step(
            n_params, tokens, layers=layers, hidden=hidden,
            seq_len=seq_len, arch=arch)
        r = recompute_factor(policy, total, attn)
        hardware = total * (1.0 + r / 3.0)
        model_tflops = total / wall / 1e12 if (total and wall) else 0.0
        hw_tflops = hardware / wall / 1e12 if (hardware and wall) else 0.0
        peak = h.get('peak_tflops', resolve_peak_tflops())
        mfu = (model_tflops / peak) if (peak and model_tflops) else None
        return {
            'arch': arch, 'n_params': int(n_params), 'tokens_per_step':
                tokens, 'remat_policy': policy or 'none',
            'flops': {'model_flops_per_step': total,
                      'attn_flops_per_step': attn,
                      'recompute_factor': r,
                      'hardware_flops_per_step': hardware},
            'model_tflops': model_tflops,
            'hardware_tflops': hw_tflops,
            'peak_tflops': peak,
            'mfu': mfu,
        }

    # -- publication (flush-time, never the hot path) -----------------------
    def publish(self):
        acct = self.account()
        if acct is None:
            return None
        try:
            from . import monitor as _m
            e = self.engine
            _m.gauge('ptpu_ledger_wall_seconds',
                     help='ledger: mean step wall (dispatch-to-dispatch)',
                     labelnames=('engine',)).set(acct['wall_seconds'],
                                                 engine=e)
            comp = _m.gauge(
                'ptpu_ledger_component_seconds',
                help='ledger: per-step seconds attributed to each '
                     'component (compute/exposed_comm/bubble/host_gap/'
                     'residue)',
                labelnames=('engine', 'component'))
            for name, v in acct['components'].items():
                comp.set(v, engine=e, component=name)
            _m.gauge('ptpu_ledger_reconciled_fraction',
                     help='ledger: sum(components)/wall (1.0 = fully '
                          'reconciled; >1 flags modeled terms exceeding '
                          'the measured wall)',
                     labelnames=('engine',)).set(
                         acct['reconciled_fraction'], engine=e)
            _m.gauge('ptpu_ledger_tokens_per_step',
                     help='ledger: tokens consumed per step (from batch '
                          'shapes)',
                     labelnames=('engine',)).set(
                         acct['tokens_per_step'], engine=e)
            _m.gauge('ptpu_ledger_model_tflops',
                     help='ledger: achieved model TFLOP/s (6NT + attn '
                          'term, recompute excluded)',
                     labelnames=('engine',)).set(acct['model_tflops'],
                                                 engine=e)
            _m.gauge('ptpu_ledger_hardware_tflops',
                     help='ledger: achieved hardware TFLOP/s (model * '
                          '(1+r/3) for remat recompute factor r)',
                     labelnames=('engine',)).set(acct['hardware_tflops'],
                                                 engine=e)
            _m.gauge('ptpu_ledger_recompute_factor',
                     help='ledger: fraction of the forward re-executed '
                          'in the backward under the active remat policy',
                     labelnames=('engine',)).set(
                         acct['flops']['recompute_factor'], engine=e)
            if acct['peak_tflops']:
                _m.gauge('ptpu_ledger_peak_tflops',
                         help='ledger: per-chip bf16 peak for the local '
                              'device generation',
                         labelnames=('engine',)).set(
                             acct['peak_tflops'], engine=e)
            if acct['mfu'] is not None:
                _m.gauge('ptpu_ledger_mfu',
                         help='ledger: model-FLOPs utilization vs the '
                              'per-device peak (absent on CPU dryruns)',
                         labelnames=('engine',)).set(acct['mfu'], engine=e)
        except Exception:
            pass
        return acct


def ledger_snapshot(engine=None):
    """StepTelemetry.snapshot()['ledger'] payload: every published
    engine's account read back from the ptpu_ledger_* gauges (None when
    no ledger has published)."""
    try:
        from . import monitor as _m
        reg = _m.metrics()
        wall = reg.get('ptpu_ledger_wall_seconds')
        if wall is None:
            return None
        engines = [labels[0] for labels in wall._series()] \
            if engine is None else [engine]

        def val(name, eng, component=None):
            m = reg.get(name)
            if m is None:
                return None
            want = (eng,) if component is None else (eng, component)
            for labels, child in m._series().items():
                if tuple(labels) == want:
                    return child.value()
            return None

        out = {}
        for eng in engines:
            w = val('ptpu_ledger_wall_seconds', eng)
            if w is None:
                continue
            out[eng] = {
                'wall_seconds': w,
                'components': {
                    c: val('ptpu_ledger_component_seconds', eng, c) or 0.0
                    for c in ('compute', 'exposed_comm', 'bubble',
                              'host_gap', 'residue')},
                'reconciled_fraction':
                    val('ptpu_ledger_reconciled_fraction', eng),
                'tokens_per_step':
                    int(val('ptpu_ledger_tokens_per_step', eng) or 0),
                'model_tflops': val('ptpu_ledger_model_tflops', eng),
                'hardware_tflops':
                    val('ptpu_ledger_hardware_tflops', eng),
                'recompute_factor':
                    val('ptpu_ledger_recompute_factor', eng),
                'peak_tflops': val('ptpu_ledger_peak_tflops', eng),
                'mfu': val('ptpu_ledger_mfu', eng),
            }
        return out or None
    except Exception:
        return None


def render_ledger(snap):
    """Human rendering of a ledger_snapshot() dict (shared with
    tools/health_dump.py ledger)."""
    out = ['== step-time ledger ' + '=' * 40]
    for eng, a in sorted((snap or {}).items()):
        wall = a.get('wall_seconds') or 0.0
        out.append(f"engine: {eng}   wall {wall * 1e3:.3f} ms/step   "
                   f"reconciled {(a.get('reconciled_fraction') or 0):.3f}")
        comps = a.get('components') or {}
        for name in ('compute', 'exposed_comm', 'bubble', 'host_gap',
                     'residue'):
            v = comps.get(name) or 0.0
            pct = (v / wall * 100.0) if wall else 0.0
            out.append(f"  {name:<13} {v * 1e3:>10.3f} ms  {pct:5.1f}%")
        mt = a.get('model_tflops')
        if mt:
            line = (f"  model {mt:.3f} TFLOP/s  hardware "
                    f"{(a.get('hardware_tflops') or 0):.3f} TFLOP/s  "
                    f"(recompute r={(a.get('recompute_factor') or 0):.2f})")
            if a.get('mfu') is not None:
                line += (f"  MFU {a['mfu'] * 100:.1f}% of "
                         f"{a.get('peak_tflops')} TFLOP/s peak")
            out.append(line)
    return '\n'.join(out)


# ---------------------------------------------------------------------------
# cross-rank straggler detection (DivergenceSentinel for wall time)
# ---------------------------------------------------------------------------
class StragglerDetector:
    """Periodic allgather of per-rank step-wall fingerprints over the
    host-collective group; ranks slower than `threshold` x the median
    get flagged, gauged, and dumped as a `straggler_report` artifact.

    Knobs (env): PTPU_STRAGGLER_CHECK=1 enables the periodic check from
    the engines' dispatch path; PTPU_STRAGGLER_EVERY (default 50) sets
    the cadence in dispatches — it must divide identically on every
    rank (the allgather is collective); PTPU_STRAGGLER_THRESHOLD
    (default 1.25) the relative-to-median slowdown that flags a rank.
    """

    def __init__(self, engine='train', group=None, threshold=None,
                 check_every=None, dump_dir=None):
        self.engine = engine
        self.group = group
        self.threshold = float(
            threshold if threshold is not None
            else os.environ.get('PTPU_STRAGGLER_THRESHOLD', '1.25'))
        self.check_every = max(1, int(
            check_every if check_every is not None
            else os.environ.get('PTPU_STRAGGLER_EVERY', '50')))
        self.dump_dir = dump_dir
        self.checks = 0
        self.events = 0
        self.report = None
        self.report_path = None

    def _group(self):
        if self.group is not None:
            return self.group
        try:
            from ..distributed import host_collectives as HC
            return HC.host_group()
        except Exception:
            return None

    def maybe_check(self, step, gap_monitor):
        if step % self.check_every != 0:
            return None
        wall = 0.0
        if gap_monitor is not None:
            snap = gap_monitor.snapshot()
            wall = float(snap.get('step_interval_seconds') or 0.0)
        return self.check(step, wall)

    def check(self, step, wall_seconds):
        """Collective: every rank in the host group must call this with
        the same `step`. Returns the straggler report dict on this
        rank's view of a flagged round, else None."""
        g = self._group()
        if g is None or g.world_size <= 1:
            return None
        from . import monitor as _m
        self.checks += 1
        _m.counter('ptpu_straggler_checks_total',
                   help='cross-rank step-wall allgathers').inc(1)
        fp = np.asarray([float(wall_seconds)], np.float64)
        walls = [float(np.asarray(w).reshape(-1)[0])
                 for w in g.all_gather(fp)]
        median = float(np.median([w for w in walls if w > 0.0] or [0.0]))
        if median <= 0.0:
            return None
        rel = {r: walls[r] / median for r in range(g.world_size)}
        _m.gauge('ptpu_straggler_relative_wall',
                 help='this rank step wall / group median at the last '
                      'straggler check',
                 labelnames=('rank',)).set(rel[g.rank], rank=str(g.rank))
        offending = sorted(r for r, v in rel.items()
                           if v > self.threshold)
        _m.gauge('ptpu_straggler_flagged',
                 help='1 while this rank was flagged slower than '
                      'threshold x median at the last check',
                 labelnames=('rank',)).set(
                     1.0 if g.rank in offending else 0.0,
                     rank=str(g.rank))
        if not offending:
            return None
        self.events += 1
        _m.counter('ptpu_straggler_events_total',
                   help='straggler rounds detected (some rank above '
                        'threshold)').inc(1)
        report = {
            'kind': 'straggler_report', 'time': time.time(),
            'engine': self.engine, 'rank': g.rank,
            'world_size': g.world_size, 'step': step,
            'threshold': self.threshold,
            'median_wall_seconds': median,
            'ranks': {str(r): walls[r] for r in range(g.world_size)},
            'relative_wall': {str(r): rel[r]
                              for r in range(g.world_size)},
            'offending_ranks': offending,
        }
        self.report = report
        from . import numerics as _num
        self.report_path = _num.write_report(
            report, None if self.dump_dir is None else os.path.join(
                self.dump_dir,
                f'straggler_report.rank{g.rank}.{os.getpid()}.json'))
        try:
            from ..distributed import flight_recorder as fr
            rec = fr.recorder()
            seq = rec.record_enqueue('straggler_detected', group=g.gid,
                                     mode='ledger')
            rec.record_complete(seq, ok=True)
        except Exception:
            pass
        try:
            from ..distributed.fleet.utils import log_util
            log_util.log_json(
                'straggler_detected', level='warning', step=step,
                offending_ranks=offending, median_wall_seconds=median,
                threshold=self.threshold, report_path=self.report_path)
        except Exception:
            pass
        return report


def render_straggler_report(report):
    """Human rendering of a straggler_report dict (shared with
    tools/health_dump.py ledger)."""
    out = ['== straggler report ' + '=' * 40]
    out.append(f"step: {report.get('step')}   world_size: "
               f"{report.get('world_size')}   threshold: "
               f"{report.get('threshold')}x median "
               f"({(report.get('median_wall_seconds') or 0) * 1e3:.3f} ms)")
    rel = report.get('relative_wall') or {}
    ranks = report.get('ranks') or {}
    flagged = set(report.get('offending_ranks') or ())
    for r in sorted(ranks, key=int):
        mark = '  << STRAGGLER' if int(r) in flagged else ''
        out.append(f"  rank {r}: {float(ranks[r]) * 1e3:>10.3f} ms  "
                   f"({float(rel.get(r, 0)):.2f}x median){mark}")
    return '\n'.join(out)
