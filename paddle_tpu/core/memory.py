"""Device-memory forensics — phase accounting + structured OOM reports.

Reference parity role: memory/stats.h + the allocator's
DeviceMemoryStats surface (STAT_gpu_mem alloc/peak counters) and the
`RESOURCE_EXHAUSTED` enrichment in memory/allocation (the reference
prints an allocator state table on OOM). On TPU the allocator itself is
XLA/PJRT's BFC (SURVEY N10) — this module owns the part the framework
can still see: `device.memory_stats()` snapshots, live-buffer census via
`jax.live_arrays()`, and the per-phase attribution the raw allocator
cannot give.

Three layers:

  * `MemoryAccountant.phase(name)` — bracket compile/execute/step/init
    sites; samples bytes-in-use at entry/exit, tracks per-phase
    high-water marks and deltas, publishes `ptpu_mem_*` monitor gauges,
    and attributes newly-live buffers to the phase that allocated them
    (origin spans for the OOM report).
  * `oom_report()` — a JSON-ready post-mortem: device limits, per-phase
    high-water table, recent phase timeline, top live buffers by size
    with their origin phase, and a suggested culprit phase.
  * `oom_guard(site)` — wraps hot paths (executor execute, engine
    steps); on `RESOURCE_EXHAUSTED` it writes the report to the log dir
    and raises `DeviceOOMError` carrying the rendered report instead of
    a bare backend traceback.

Bytes sampling is cheap (one `memory_stats()` dict read); the
live-buffer census walks `jax.live_arrays()` and is taken only at
explicit `sample(count_buffers=True)` calls, phase exits of *census
phases*, and report time — never per executor dispatch.
"""
import collections
import contextlib
import json
import os
import threading
import time
import weakref

__all__ = [
    'MemoryAccountant', 'accountant', 'phase', 'sample', 'live_buffers',
    'live_buffer_count', 'oom_report', 'render_oom_report', 'oom_guard',
    'is_oom_error', 'DeviceOOMError', 'reset', 'record_compiled_memory',
    'activation_bytes',
]

_TIMELINE_CAP = 256
_CENSUS_PHASES = frozenset((
    'engine.init', 'engine.shutdown', 'pipeline.build', 'bench.leg'))


def _env_rank():
    try:
        return int(os.environ.get('PADDLE_TRAINER_ID', '0') or 0)
    except ValueError:
        return 0


def default_report_dir():
    """Where diagnostics artifacts (OOM reports, watchdog dumps) land."""
    return (os.environ.get('FLEET_LOG_DIR')
            or os.environ.get('PADDLE_LOG_DIR') or '/tmp')


def _device():
    try:
        import jax
        return jax.local_devices()[0]
    except Exception:
        return None


def _device_stats():
    """(bytes_in_use, peak, limit) from the backend, or Nones when the
    backend does not expose memory_stats (CPU)."""
    dev = _device()
    if dev is None or not hasattr(dev, 'memory_stats'):
        return None, None, None
    try:
        stats = dev.memory_stats() or {}
    except Exception:
        return None, None, None
    return (stats.get('bytes_in_use'), stats.get('peak_bytes_in_use'),
            stats.get('bytes_limit'))


def _arr_nbytes(a):
    try:
        return int(a.nbytes)
    except Exception:
        try:
            import numpy as np
            n = 1
            for d in a.shape:
                n *= int(d)
            return n * np.dtype(a.dtype).itemsize
        except Exception:
            return 0


def device_nbytes(a):
    """Bytes `a` actually occupies across its addressable devices —
    the census view that distinguishes a REPLICATED array (ndev x
    logical bytes) from a sharded one (1 x). `a.nbytes` is the global
    LOGICAL size either way, which hides exactly the resident-set win
    the deferred-gather engines buy (docs/performance.md#comm-overlap),
    so the overlap acceptance tests measure with this."""
    try:
        return int(sum(int(s.data.nbytes) for s in a.addressable_shards))
    except Exception:
        return _arr_nbytes(a)


class DeviceOOMError(RuntimeError):
    """RESOURCE_EXHAUSTED enriched with the forensics report. `.report`
    holds the JSON-ready dict; str() renders the human table."""

    def __init__(self, message, report=None, report_path=None):
        super().__init__(message)
        self.report = report or {}
        self.report_path = report_path


def is_oom_error(exc):
    """Backend-agnostic RESOURCE_EXHAUSTED detection (jaxlib raises
    XlaRuntimeError whose repr carries the grpc status name)."""
    if exc is None:
        return False
    r = repr(exc)
    return ('RESOURCE_EXHAUSTED' in r or 'Out of memory' in r
            or 'out of memory' in r)


class MemoryAccountant:
    """Per-process device-memory bookkeeping (thread-safe singleton)."""

    def __init__(self):
        self._lock = threading.RLock()
        self.reset()

    def reset(self):
        with self._lock:
            self._phases = collections.OrderedDict()
            self._timeline = collections.deque(maxlen=_TIMELINE_CAP)
            self._stack = []            # active phase names (thread-shared
                                        # hot paths are main-thread only)
            self._origins = {}          # id(live array) -> phase name
            self._py_peak = 0           # census-derived fallback peak
            self._activation = collections.OrderedDict()  # site ->
                                        # compiled-program buffer stats

    # -- sampling ------------------------------------------------------------
    def sample(self, count_buffers=False):
        """One snapshot: {'bytes_in_use','peak_bytes_in_use','bytes_limit',
        'live_buffers','live_bytes'}. The buffer census (live_buffers /
        live_bytes and the CPU-backend bytes fallback) only runs when
        `count_buffers` — it walks every live jax array."""
        in_use, peak, limit = _device_stats()
        out = {'bytes_in_use': in_use, 'peak_bytes_in_use': peak,
               'bytes_limit': limit, 'live_buffers': None,
               'live_bytes': None,
               # per-site compiled-program activation (temp-buffer)
               # bytes — XLA's buffer-assignment view of what the step
               # keeps resident BETWEEN forward and backward, which the
               # live-array census cannot see (those buffers live inside
               # the executable). Filled by record_compiled_memory().
               'activation_bytes': self.activation_bytes()}
        # the census walk is opt-in even when the backend has no
        # memory_stats (CPU): per-dispatch phases must stay O(1)
        if count_buffers:
            try:
                import jax
                arrs = jax.live_arrays()
            except Exception:
                arrs = []
            nbytes = sum(_arr_nbytes(a) for a in arrs)
            out['live_buffers'] = len(arrs)
            out['live_bytes'] = nbytes
            # replication-aware twin: what the buffers occupy across
            # the addressable devices (live_bytes counts logical size)
            out['live_device_bytes'] = sum(device_nbytes(a)
                                           for a in arrs)
            if in_use is None:
                out['bytes_in_use'] = nbytes
                with self._lock:
                    self._py_peak = max(self._py_peak, nbytes)
                    out['peak_bytes_in_use'] = self._py_peak
        return out

    # -- compiled-program activation bytes (ISSUE 12) ------------------------
    def record_compiled_memory(self, site, compiled):
        """Record a compiled executable's buffer-assignment stats under
        `site` (engines call this right after AOT compile). The
        interesting number is temp_size_in_bytes: the scratch/residual
        buffers XLA keeps live inside the program — i.e. the step's
        resident ACTIVATION bytes, the quantity remat policies and
        sequence-parallel sharding shrink. Published as the
        ptpu_mem_activation_bytes gauge; returns the stats dict (or
        None when the backend exposes no memory analysis)."""
        try:
            ma = compiled.memory_analysis()
            stats = {
                'activation_bytes': int(ma.temp_size_in_bytes),
                'argument_bytes': int(ma.argument_size_in_bytes),
                'output_bytes': int(ma.output_size_in_bytes),
            }
        except Exception:
            return None
        with self._lock:
            self._activation[site] = stats
        try:
            from . import monitor as _m
            _m.gauge(
                'ptpu_mem_activation_bytes',
                help='compiled-program temp (activation/workspace) '
                     'bytes from XLA buffer assignment, by compile site',
                labelnames=('site',)).set(stats['activation_bytes'],
                                          site=site)
        except Exception:
            pass
        return stats

    def activation_bytes(self):
        """{site: temp bytes} of every recorded compiled program."""
        with self._lock:
            return {k: v['activation_bytes']
                    for k, v in self._activation.items()}

    def compiled_memory(self):
        """Full per-site buffer-assignment stats."""
        with self._lock:
            return {k: dict(v) for k, v in self._activation.items()}

    def live_buffers(self, top=None, with_origin=True):
        """[(nbytes, shape, dtype, origin_phase)] sorted largest-first."""
        try:
            import jax
            arrs = jax.live_arrays()
        except Exception:
            arrs = []
        rows = []
        with self._lock:
            origins = dict(self._origins) if with_origin else {}
        for a in arrs:
            rows.append((_arr_nbytes(a), tuple(getattr(a, 'shape', ())),
                         str(getattr(a, 'dtype', '?')),
                         self._origin_of(a, origins)))
        rows.sort(key=lambda r: -r[0])
        return rows[:top] if top else rows

    @staticmethod
    def _origin_of(a, origins):
        """Validated origin lookup: the entry's weakref must still point
        at THIS object — CPython recycles id()s, and a stale entry would
        blame a long-gone phase for a brand-new buffer."""
        ent = origins.get(id(a))
        if ent is None:
            return None
        phase_name, ref = ent
        if ref is not None and ref() is not a:
            return None
        return phase_name

    def live_buffer_count(self):
        try:
            import jax
            return len(jax.live_arrays())
        except Exception:
            return 0

    def _live_ids(self):
        try:
            import jax
            return {id(a) for a in jax.live_arrays()}
        except Exception:
            return set()

    def _attribute_new(self, phase_name, pre_ids):
        """Tag arrays that became live BETWEEN this phase's entry and
        exit (pre_ids is the entry census) and prune origins of freed
        arrays. Attributing every so-far-untagged array instead would
        blame the next census phase for buffers allocated long before
        it (e.g. another engine's per-step param replacements). Entries
        hold a weakref so an id() recycled onto a new array is detected
        and re-tagged rather than inheriting the stale phase."""
        try:
            import jax
            arrs = jax.live_arrays()
        except Exception:
            return
        def _ref(a):
            try:
                return weakref.ref(a)
            except TypeError:
                return None

        live_ids = set()
        with self._lock:
            for a in arrs:
                i = id(a)
                live_ids.add(i)
                ent = self._origins.get(i)
                stale = ent is not None and ent[1] is not None \
                    and ent[1]() is not a
                if stale:
                    # id recycled onto a new array: re-tag with the
                    # phase in which the new array was first seen
                    self._origins[i] = (phase_name, _ref(a))
                elif ent is None and i not in pre_ids:
                    self._origins[i] = (phase_name, _ref(a))
            for dead in set(self._origins) - live_ids:
                del self._origins[dead]

    # -- phases --------------------------------------------------------------
    @contextlib.contextmanager
    def phase(self, name, census=None):
        """Bracket a memory-relevant region. `census=True` forces the
        live-buffer walk at the boundary (defaults to True only for the
        coarse lifecycle phases, so per-step sites stay cheap)."""
        census = (name in _CENSUS_PHASES) if census is None else census
        pre_ids = self._live_ids() if census else set()
        enter = self.sample(count_buffers=census)
        t0 = time.time()
        self._stack.append(name)
        try:
            yield self
        finally:
            self._stack.pop()
            exit_ = self.sample(count_buffers=census)
            if census:
                self._attribute_new(name, pre_ids)
            self._record(name, enter, exit_, t0)

    def current_phase(self):
        return self._stack[-1] if self._stack else None

    def _record(self, name, enter, exit_, t0):
        e_in = enter.get('bytes_in_use') or 0
        x_in = exit_.get('bytes_in_use') or 0
        with self._lock:
            ph = self._phases.get(name)
            if ph is None:
                ph = self._phases[name] = {
                    'calls': 0, 'bytes_enter': 0, 'bytes_exit': 0,
                    'high_water': 0, 'max_delta': 0, 'last_delta': 0,
                    'live_buffers': None, 'seconds': 0.0}
            ph['calls'] += 1
            ph['bytes_enter'] = e_in
            ph['bytes_exit'] = x_in
            # high water from THIS phase's boundary samples — the
            # backend's peak_bytes_in_use is a process-lifetime monotonic
            # peak, and folding it in would smear the global maximum onto
            # every phase recorded after it (wrong suspect attribution)
            ph['high_water'] = max(ph['high_water'], e_in, x_in)
            ph['last_delta'] = x_in - e_in
            ph['max_delta'] = max(ph['max_delta'], x_in - e_in)
            ph['seconds'] += time.time() - t0
            if exit_.get('live_buffers') is not None:
                ph['live_buffers'] = exit_['live_buffers']
            self._timeline.append({
                'ts': t0, 'phase': name, 'bytes_enter': e_in,
                'bytes_exit': x_in, 'delta': x_in - e_in,
                'live_buffers': exit_.get('live_buffers')})
        self._publish(name, x_in, exit_.get('live_buffers'))

    def _publish(self, name, in_use, nbuf):
        from . import monitor as _m
        g = _m.gauge
        g('ptpu_mem_bytes_in_use',
          help='device bytes in use at the last phase boundary',
          labelnames=('phase',)).set(in_use, phase=name)
        g('ptpu_mem_high_water_bytes',
          help='per-phase device-memory high-water mark',
          labelnames=('phase',)).set(
              self._phases[name]['high_water'], phase=name)
        if nbuf is not None:
            g('ptpu_mem_live_buffers',
              help='live device buffer count (census phases)').set(nbuf)

    def phases(self):
        with self._lock:
            return {k: dict(v) for k, v in self._phases.items()}

    def timeline(self):
        with self._lock:
            return list(self._timeline)

    # -- OOM report ----------------------------------------------------------
    def oom_report(self, exc=None, top=20):
        snap = self.sample(count_buffers=True)
        phases = self.phases()
        suspect = None
        if phases:
            # attribute by what a phase NETTED (max_delta), not by
            # boundary usage: when memory accumulates monotonically every
            # later phase sees higher bytes-in-use than the phase that
            # actually allocated the bulk of it
            suspect = max(phases.items(),
                          key=lambda kv: (kv[1]['max_delta'],
                                          kv[1]['high_water']))[0]
        bufs = [{'bytes': b, 'shape': list(s), 'dtype': d,
                 'origin_phase': o}
                for b, s, d, o in self.live_buffers(top=top)]
        dev = _device()
        report = {
            'kind': 'oom_report',
            'time': time.time(),
            'error': repr(exc)[:2000] if exc is not None else None,
            'device': str(dev) if dev is not None else None,
            'rank': _env_rank(),
            'bytes_in_use': snap['bytes_in_use'],
            'peak_bytes_in_use': snap['peak_bytes_in_use'],
            'bytes_limit': snap['bytes_limit'],
            'live_buffer_count': snap['live_buffers'],
            'live_bytes': snap['live_bytes'],
            'top_buffers': bufs,
            'phases': phases,
            'timeline': self.timeline(),
            'suspect_phase': suspect,
        }
        return report


_accountant = MemoryAccountant()


def accountant():
    return _accountant


def phase(name, census=None):
    return _accountant.phase(name, census=census)


def sample(count_buffers=False):
    return _accountant.sample(count_buffers=count_buffers)


def live_buffers(top=None):
    return _accountant.live_buffers(top=top)


def live_buffer_count():
    return _accountant.live_buffer_count()


def oom_report(exc=None, top=20):
    return _accountant.oom_report(exc=exc, top=top)


def reset():
    _accountant.reset()


def record_compiled_memory(site, compiled):
    return _accountant.record_compiled_memory(site, compiled)


def activation_bytes():
    return _accountant.activation_bytes()


def _fmt_bytes(n):
    if n is None:
        return '?'
    for unit in ('B', 'KiB', 'MiB', 'GiB', 'TiB'):
        if abs(n) < 1024 or unit == 'TiB':
            return f'{n:.1f}{unit}' if unit != 'B' else f'{int(n)}B'
        n /= 1024.0
    return str(n)


def render_oom_report(report):
    """Human-readable table of an oom_report() dict (shared by the
    DeviceOOMError message and tools/health_dump.py)."""
    out = ['== device OOM report ' + '=' * 39]
    out.append(f"device: {report.get('device')}   "
               f"rank: {report.get('rank')}")
    out.append(
        f"in_use: {_fmt_bytes(report.get('bytes_in_use'))}   "
        f"peak: {_fmt_bytes(report.get('peak_bytes_in_use'))}   "
        f"limit: {_fmt_bytes(report.get('bytes_limit'))}   "
        f"live buffers: {report.get('live_buffer_count')}")
    if report.get('suspect_phase'):
        ph = report['phases'].get(report['suspect_phase'], {})
        out.append(f"suspect phase: {report['suspect_phase']} "
                   f"(high-water {_fmt_bytes(ph.get('high_water'))}, "
                   f"max step delta {_fmt_bytes(ph.get('max_delta'))})")
    if report.get('phases'):
        out.append('-- per-phase high water ' + '-' * 36)
        out.append(f"{'phase':<24} {'calls':>6} {'high_water':>12} "
                   f"{'last_delta':>12} {'exit':>12}")
        rows = sorted(report['phases'].items(),
                      key=lambda kv: -kv[1].get('high_water', 0))
        for name, ph in rows:
            out.append(
                f"{name[:24]:<24} {ph.get('calls', 0):>6} "
                f"{_fmt_bytes(ph.get('high_water')):>12} "
                f"{_fmt_bytes(ph.get('last_delta')):>12} "
                f"{_fmt_bytes(ph.get('bytes_exit')):>12}")
    if report.get('top_buffers'):
        out.append('-- top live buffers ' + '-' * 40)
        out.append(f"{'bytes':>12}  {'dtype':<10} {'origin':<18} shape")
        for b in report['top_buffers'][:20]:
            out.append(f"{_fmt_bytes(b['bytes']):>12}  "
                       f"{b['dtype']:<10} "
                       f"{str(b.get('origin_phase') or '?'):<18} "
                       f"{tuple(b['shape'])}")
    if report.get('timeline'):
        out.append('-- recent phase timeline ' + '-' * 35)
        for ev in report['timeline'][-12:]:
            out.append(f"  {ev['phase']:<24} "
                       f"delta {_fmt_bytes(ev['delta']):>10}  "
                       f"exit {_fmt_bytes(ev['bytes_exit']):>10}")
    return '\n'.join(out)


def write_report(report, path=None):
    path = path or os.path.join(
        default_report_dir(),
        f"oom_report.rank{report.get('rank', 0)}.{os.getpid()}.json")
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, 'w') as f:
            json.dump(report, f)
        return path
    except OSError:
        return None


@contextlib.contextmanager
def oom_guard(site, report_path=None):
    """Convert a backend RESOURCE_EXHAUSTED escaping `site` into a
    DeviceOOMError carrying the forensics report; the JSON report is
    also written under the log dir for tools/health_dump.py."""
    try:
        yield
    except DeviceOOMError:
        raise                    # already enriched by an inner guard
    except Exception as e:       # noqa: BLE001 — filtered by is_oom_error
        if not is_oom_error(e):
            raise
        report = _accountant.oom_report(exc=e)
        report['site'] = site
        path = write_report(report, report_path)
        try:
            from ..distributed.fleet.utils import log_util
            log_util.log_json('device_oom', level='error', site=site,
                              report_path=path,
                              bytes_in_use=report.get('bytes_in_use'),
                              suspect_phase=report.get('suspect_phase'))
        except Exception:
            pass
        msg = (f"RESOURCE_EXHAUSTED in {site}"
               + (f" (full report: {path})" if path else '') + '\n'
               + render_oom_report(report))
        raise DeviceOOMError(msg, report=report, report_path=path) from e
