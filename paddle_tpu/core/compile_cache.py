"""Persistent XLA compilation cache (ISSUE 4 satellite).

Enables JAX's on-disk compilation cache behind `PTPU_COMPILE_CACHE_DIR`
and surfaces its traffic as `ptpu_compile_cache_*` metrics beside the
executor's in-process fingerprint-cache counters
(STAT_executor_cache_hit/miss): at GPT scale one warm cache turns the
minutes-long first dispatch into a disk read, and the gauges make the
saving visible in StepTelemetry / bench records / health_dump.

jax 0.4.x emits monitoring events for the cache
(`/jax/compilation_cache/compile_requests_use_cache`, `.../cache_hits`,
and the `.../compile_time_saved_sec` duration); there is no miss event,
so misses are derived as requests - hits.
"""
import os
import threading

_lock = threading.Lock()
_installed = False
_enabled_dir = None


def _install_listeners():
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    from jax import monitoring
    from . import monitor as _m

    def on_event(event, **kwargs):
        if event == '/jax/compilation_cache/compile_requests_use_cache':
            _m.counter('ptpu_compile_cache_requests_total',
                       help='XLA compiles that consulted the persistent '
                            'cache').inc(1)
        elif event == '/jax/compilation_cache/cache_hits':
            _m.counter('ptpu_compile_cache_hits_total',
                       help='persistent compilation cache hits').inc(1)

    def on_duration(event, duration, **kwargs):
        if event == '/jax/compilation_cache/compile_time_saved_sec':
            _m.counter('ptpu_compile_cache_seconds_saved_total',
                       help='compile seconds avoided via the persistent '
                            'cache').inc(max(float(duration), 0.0))

    monitoring.register_event_listener(on_event)
    monitoring.register_event_duration_secs_listener(on_duration)


def enable(cache_dir, min_compile_seconds=0.0):
    """Point jax at an on-disk compilation cache and install the
    metric listeners. `min_compile_seconds=0` caches every program
    (jax's default of 1s would skip the small ones tests compile)."""
    global _enabled_dir
    import jax
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update('jax_compilation_cache_dir', str(cache_dir))
    try:
        jax.config.update('jax_persistent_cache_min_compile_time_secs',
                          float(min_compile_seconds))
        jax.config.update('jax_persistent_cache_min_entry_size_bytes', -1)
    except Exception:
        pass   # older jax: defaults still cache the big programs
    _install_listeners()
    _enabled_dir = str(cache_dir)
    return True


def enable_from_env():
    """Called at `import paddle_tpu`; no-op unless
    PTPU_COMPILE_CACHE_DIR is set. A bad dir or malformed min-seconds
    must not kill every `import paddle_tpu` over an optional perf
    feature — warn and run uncached instead."""
    d = os.environ.get('PTPU_COMPILE_CACHE_DIR')
    if not d:
        return False
    try:
        mins = float(os.environ.get('PTPU_COMPILE_CACHE_MIN_COMPILE_SECS',
                                    0.0) or 0.0)
        return enable(d, min_compile_seconds=mins)
    except Exception as e:   # noqa: BLE001
        import warnings
        warnings.warn(
            f'PTPU_COMPILE_CACHE_DIR={d!r}: persistent compile cache '
            f'disabled ({e!r})', RuntimeWarning)
        return False


def enabled():
    return _enabled_dir is not None


def snapshot():
    """JSON-ready cache-traffic view (StepTelemetry / bench /
    health_dump)."""
    from . import monitor as _m

    def total(name):
        m = _m.metrics().get(name)
        if m is None:
            return 0.0
        return sum(c.value() for c in m._series().values())
    requests = int(total('ptpu_compile_cache_requests_total'))
    hits = int(total('ptpu_compile_cache_hits_total'))
    return {
        'enabled': enabled(),
        'dir': _enabled_dir,
        'requests': requests,
        'hits': hits,
        'misses': max(requests - hits, 0),
        'seconds_saved': round(
            total('ptpu_compile_cache_seconds_saved_total'), 3),
    }
