"""ctypes bindings to the C++ native runtime (csrc/).

Reference parity: the pybind layer (paddle/fluid/pybind — N33) for the
runtime-services subset that stays native in the TPU rebuild: data feed
(N19), TCP store rendezvous (N8/N9), sparse PS table (N30), host profiler
(N4). Builds csrc/ on demand with make (g++ only — no pybind11 dependency;
plain C ABI + ctypes).
"""
import ctypes
import os
import subprocess

import numpy as np

_LIB = None
_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), 'csrc')
_SO = os.path.join(_CSRC, 'libpaddle_tpu_native.so')


def load_native(required=False):
    """Load (building if needed) the native library. Returns None when
    unavailable and not required."""
    global _LIB
    if _LIB is not None:
        return _LIB
    if not os.path.exists(_SO):
        try:
            subprocess.run(['make', '-C', _CSRC], check=True,
                           capture_output=True)
        except Exception as e:
            if required:
                raise RuntimeError(f"native build failed: {e}")
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:
        if required:
            raise
        return None

    # datafeed
    lib.ptpu_datafeed_create.restype = ctypes.c_void_p
    lib.ptpu_datafeed_create.argtypes = [
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.ptpu_datafeed_set_files.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_int]
    lib.ptpu_datafeed_start.argtypes = [ctypes.c_void_p]
    lib.ptpu_datafeed_next.restype = ctypes.c_int
    lib.ptpu_datafeed_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                       ctypes.c_void_p]
    lib.ptpu_datafeed_load_shuffle.argtypes = [ctypes.c_void_p,
                                               ctypes.c_uint64]
    lib.ptpu_datafeed_next_mem.restype = ctypes.c_int
    lib.ptpu_datafeed_next_mem.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                           ctypes.c_void_p]
    lib.ptpu_datafeed_rewind.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                         ctypes.c_uint64]
    lib.ptpu_datafeed_memory_size.restype = ctypes.c_int64
    lib.ptpu_datafeed_memory_size.argtypes = [ctypes.c_void_p]
    lib.ptpu_datafeed_destroy.argtypes = [ctypes.c_void_p]

    # tcp store
    lib.ptpu_store_server_start.restype = ctypes.c_void_p
    lib.ptpu_store_server_start.argtypes = [ctypes.c_int]
    lib.ptpu_store_server_port.restype = ctypes.c_int
    lib.ptpu_store_server_port.argtypes = [ctypes.c_void_p]
    lib.ptpu_store_server_stop.argtypes = [ctypes.c_void_p]
    lib.ptpu_store_client_connect.restype = ctypes.c_void_p
    lib.ptpu_store_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                              ctypes.c_int]
    lib.ptpu_store_set.restype = ctypes.c_int
    lib.ptpu_store_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_char_p, ctypes.c_int]
    lib.ptpu_store_get.restype = ctypes.c_int
    lib.ptpu_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_char_p, ctypes.c_int,
                                   ctypes.c_int]
    lib.ptpu_store_add.restype = ctypes.c_int64
    lib.ptpu_store_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int64]
    lib.ptpu_store_barrier.restype = ctypes.c_int
    lib.ptpu_store_barrier.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_uint32]
    lib.ptpu_store_client_close.argtypes = [ctypes.c_void_p]

    # sparse table
    lib.ptpu_table_create.restype = ctypes.c_void_p
    lib.ptpu_table_create.argtypes = [ctypes.c_int, ctypes.c_int,
                                      ctypes.c_int, ctypes.c_float,
                                      ctypes.c_uint64]
    lib.ptpu_table_create2.restype = ctypes.c_void_p
    lib.ptpu_table_create2.argtypes = [ctypes.c_int, ctypes.c_int,
                                       ctypes.c_int, ctypes.c_float,
                                       ctypes.c_uint64, ctypes.c_float,
                                       ctypes.c_float, ctypes.c_float]
    lib.ptpu_ssd_table_create.restype = ctypes.c_void_p
    lib.ptpu_ssd_table_create.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_float,
        ctypes.c_uint64, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ctypes.c_int64, ctypes.c_char_p]
    lib.ptpu_ssd_mem_rows.restype = ctypes.c_int64
    lib.ptpu_ssd_mem_rows.argtypes = [ctypes.c_void_p]
    lib.ptpu_ssd_total_rows.restype = ctypes.c_int64
    lib.ptpu_ssd_total_rows.argtypes = [ctypes.c_void_p]
    lib.ptpu_ssd_flush.argtypes = [ctypes.c_void_p]
    lib.ptpu_ssd_recover.restype = ctypes.c_int
    lib.ptpu_ssd_recover.argtypes = [ctypes.c_void_p]
    lib.ptpu_ssd_save.restype = ctypes.c_int
    lib.ptpu_ssd_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ptpu_ssd_load.restype = ctypes.c_int
    lib.ptpu_ssd_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ptpu_table_pull.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_int, ctypes.c_void_p]
    lib.ptpu_table_push.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_int, ctypes.c_void_p,
                                    ctypes.c_float]
    lib.ptpu_table_set.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                   ctypes.c_int, ctypes.c_void_p]
    lib.ptpu_table_size.restype = ctypes.c_int64
    lib.ptpu_table_size.argtypes = [ctypes.c_void_p]
    lib.ptpu_table_shrink.restype = ctypes.c_int64
    lib.ptpu_table_shrink.argtypes = [ctypes.c_void_p, ctypes.c_float]
    lib.ptpu_table_save.restype = ctypes.c_int
    lib.ptpu_table_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ptpu_table_load.restype = ctypes.c_int
    lib.ptpu_table_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ptpu_table_destroy.argtypes = [ctypes.c_void_p]

    # dense table
    lib.ptpu_dense_create.restype = ctypes.c_void_p
    lib.ptpu_dense_create.argtypes = [ctypes.c_int64, ctypes.c_int]
    lib.ptpu_dense_set.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.ptpu_dense_pull.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.ptpu_dense_push.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_float]
    lib.ptpu_dense_size.restype = ctypes.c_int64
    lib.ptpu_dense_size.argtypes = [ctypes.c_void_p]
    lib.ptpu_dense_save.restype = ctypes.c_int
    lib.ptpu_dense_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ptpu_dense_load.restype = ctypes.c_int
    lib.ptpu_dense_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ptpu_dense_destroy.argtypes = [ctypes.c_void_p]

    # profiler
    lib.ptpu_profiler_enable.argtypes = [ctypes.c_int]
    lib.ptpu_profiler_now.restype = ctypes.c_uint64
    lib.ptpu_profiler_record.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                         ctypes.c_uint64]
    lib.ptpu_profiler_count.restype = ctypes.c_int64
    lib.ptpu_profiler_summary.restype = ctypes.c_int
    lib.ptpu_profiler_summary.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.ptpu_profiler_export.restype = ctypes.c_int
    lib.ptpu_profiler_export.argtypes = [ctypes.c_char_p]
    try:      # post-v2 symbols: tolerate a stale prebuilt .so
        lib.ptpu_profiler_dropped.restype = ctypes.c_uint64
        lib.ptpu_profiler_set_capacity.argtypes = [ctypes.c_uint64]
    except AttributeError:
        pass

    _LIB = lib
    return lib


class NativeDataFeed:
    """Parity: framework/data_feed.cc MultiSlotDataFeed through C++."""

    def __init__(self, slots, batch_size, num_threads=2,
                 channel_capacity=4096):
        """slots: list of (width, kind) with kind in {'float','int64'}."""
        self.lib = load_native(required=True)
        widths = (ctypes.c_int * len(slots))(*[w for w, _ in slots])
        isf = (ctypes.c_int * len(slots))(
            *[1 if k == 'float' else 0 for _, k in slots])
        self.h = self.lib.ptpu_datafeed_create(
            widths, isf, len(slots), batch_size, num_threads,
            channel_capacity)
        self.batch_size = batch_size
        self.fwidth = sum(w for w, k in slots if k == 'float')
        self.iwidth = sum(w for w, k in slots if k == 'int64')

    def set_filelist(self, files):
        arr = (ctypes.c_char_p * len(files))(
            *[f.encode() for f in files])
        self.lib.ptpu_datafeed_set_files(self.h, arr, len(files))

    def start(self):
        self.lib.ptpu_datafeed_start(self.h)

    def _buffers(self):
        f = np.empty((self.batch_size, self.fwidth), np.float32) \
            if self.fwidth else None
        i = np.empty((self.batch_size, self.iwidth), np.int64) \
            if self.iwidth else None
        return f, i

    def __iter__(self):
        while True:
            f, i = self._buffers()
            n = self.lib.ptpu_datafeed_next(
                self.h,
                f.ctypes.data_as(ctypes.c_void_p) if f is not None else None,
                i.ctypes.data_as(ctypes.c_void_p) if i is not None else None)
            if n == 0:
                return
            yield (f[:n] if f is not None else None,
                   i[:n] if i is not None else None)

    def load_into_memory(self, seed=0):
        self.lib.ptpu_datafeed_load_shuffle(self.h, seed)

    def memory_size(self):
        return self.lib.ptpu_datafeed_memory_size(self.h)

    def iter_memory(self):
        while True:
            f, i = self._buffers()
            n = self.lib.ptpu_datafeed_next_mem(
                self.h,
                f.ctypes.data_as(ctypes.c_void_p) if f is not None else None,
                i.ctypes.data_as(ctypes.c_void_p) if i is not None else None)
            if n == 0:
                return
            yield (f[:n] if f is not None else None,
                   i[:n] if i is not None else None)

    def rewind(self, reshuffle=False, seed=0):
        self.lib.ptpu_datafeed_rewind(self.h, 1 if reshuffle else 0, seed)

    def __del__(self):
        if getattr(self, 'h', None) and self.lib:
            self.lib.ptpu_datafeed_destroy(self.h)
            self.h = None


class TCPStore:
    """Parity: gen_comm_id_helper SocketServer + Gloo KV (N8/N9)."""

    def __init__(self, host='127.0.0.1', port=0, is_master=False,
                 timeout=60):
        self.lib = load_native(required=True)
        self.server = None
        if is_master:
            self.server = self.lib.ptpu_store_server_start(port)
            if not self.server:
                raise RuntimeError(f"TCPStore: bind failed on port {port}")
            port = self.lib.ptpu_store_server_port(self.server)
        self.port = port
        self.host = host
        self.client = self.lib.ptpu_store_client_connect(
            host.encode(), port, timeout)
        if not self.client:
            raise RuntimeError(f"TCPStore: connect to {host}:{port} failed")

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        ok = self.lib.ptpu_store_set(self.client, key.encode(), value,
                                     len(value))
        if not ok:
            raise RuntimeError("TCPStore.set failed")

    def get(self, key, wait=True):
        cap = 1 << 20
        buf = ctypes.create_string_buffer(cap)
        n = self.lib.ptpu_store_get(self.client, key.encode(), buf, cap,
                                    1 if wait else 0)
        if n < 0:
            return None
        return buf.raw[:n]

    def add(self, key, delta=1):
        return self.lib.ptpu_store_add(self.client, key.encode(), delta)

    def barrier(self, key, world_size):
        ok = self.lib.ptpu_store_barrier(self.client, key.encode(),
                                         world_size)
        if not ok:
            raise RuntimeError("TCPStore.barrier failed")

    def close(self):
        if getattr(self, 'client', None):
            self.lib.ptpu_store_client_close(self.client)
            self.client = None
        if getattr(self, 'server', None):
            self.lib.ptpu_store_server_stop(self.server)
            self.server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeSparseTable:
    """Parity: distributed/table CommonSparseTable + heterPS hashtable."""

    SGD = 0
    ADAGRAD = 1
    ADAM = 2
    _OPTS = {'sgd': SGD, 'adagrad': ADAGRAD, 'adam': ADAM}

    def __init__(self, dim, num_shards=16, optimizer='adagrad',
                 init_range=0.05, seed=0, beta1=0.9, beta2=0.999,
                 eps=1e-8):
        self.lib = load_native(required=True)
        self.dim = dim
        opt = self._OPTS.get(optimizer, self.SGD)
        self.h = self.lib.ptpu_table_create2(dim, num_shards, opt,
                                             init_range, seed, beta1,
                                             beta2, eps)

    def pull(self, ids):
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        out = np.empty((len(ids), self.dim), np.float32)
        self.lib.ptpu_table_pull(
            self.h, ids.ctypes.data_as(ctypes.c_void_p), len(ids),
            out.ctypes.data_as(ctypes.c_void_p))
        return out

    def push(self, ids, grads, lr=0.01):
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        grads = np.ascontiguousarray(grads, np.float32).reshape(
            len(ids), self.dim)
        self.lib.ptpu_table_push(
            self.h, ids.ctypes.data_as(ctypes.c_void_p), len(ids),
            grads.ctypes.data_as(ctypes.c_void_p), lr)

    def set(self, ids, rows):
        """Assign embedding values (optimizer state untouched)."""
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        rows = np.ascontiguousarray(rows, np.float32).reshape(
            len(ids), self.dim)
        self.lib.ptpu_table_set(
            self.h, ids.ctypes.data_as(ctypes.c_void_p), len(ids),
            rows.ctypes.data_as(ctypes.c_void_p))

    def __len__(self):
        return self.lib.ptpu_table_size(self.h)

    def shrink(self, threshold):
        return self.lib.ptpu_table_shrink(self.h, threshold)

    def save(self, path):
        if not self.lib.ptpu_table_save(self.h, path.encode()):
            raise IOError(f"table save failed: {path}")

    def load(self, path):
        if not self.lib.ptpu_table_load(self.h, path.encode()):
            raise IOError(f"table load failed: {path}")

    def __del__(self):
        if getattr(self, 'h', None) and self.lib:
            self.lib.ptpu_table_destroy(self.h)
            self.h = None


class NativeSsdSparseTable(NativeSparseTable):
    """Parity: distributed/table/ssd_sparse_table.h — hot rows in memory
    under a row budget, cold rows spilled to per-shard append-only logs
    (the rocksdb analogue); Recover() rebuilds the index after a crash."""

    def __init__(self, dim, path, num_shards=16, optimizer='adagrad',
                 init_range=0.05, seed=0, beta1=0.9, beta2=0.999,
                 eps=1e-8, mem_budget_rows=1 << 20):
        import os as _os
        self.lib = load_native(required=True)
        self.dim = dim
        self.path = path
        _os.makedirs(path, exist_ok=True)
        opt = self._OPTS.get(optimizer, self.SGD)
        self.h = self.lib.ptpu_ssd_table_create(
            dim, num_shards, opt, init_range, seed, beta1, beta2, eps,
            mem_budget_rows, path.encode())

    def mem_rows(self):
        return self.lib.ptpu_ssd_mem_rows(self.h)

    def total_rows(self):
        return self.lib.ptpu_ssd_total_rows(self.h)

    def flush(self):
        """Spill all hot rows to the logs (checkpoint/shutdown)."""
        self.lib.ptpu_ssd_flush(self.h)

    def recover(self):
        """Rebuild the id→offset index from the logs after a restart."""
        if not self.lib.ptpu_ssd_recover(self.h):
            raise IOError(f"ssd table recover failed: {self.path}")

    def __len__(self):
        return self.total_rows()      # base Size() counts hot rows only

    def save(self, path):
        """Full snapshot incl. cold rows (streamed, never in RAM)."""
        if not self.lib.ptpu_ssd_save(self.h, path.encode()):
            raise IOError(f"ssd table save failed: {path}")

    def load(self, path):
        """Restore a snapshot straight into the spill logs."""
        if not self.lib.ptpu_ssd_load(self.h, path.encode()):
            raise IOError(f"ssd table load failed: {path}")


class NativeDenseTable:
    """Parity: distributed/table/common_dense_table.h — a fixed-size
    parameter block with the optimizer applied server-side."""

    def __init__(self, size, optimizer='sgd'):
        self.lib = load_native(required=True)
        self.size = int(size)
        opt = NativeSparseTable._OPTS.get(optimizer, 0)
        self.h = self.lib.ptpu_dense_create(self.size, opt)

    def set(self, values):
        v = np.ascontiguousarray(values, np.float32).reshape(-1)
        assert len(v) == self.size
        self.lib.ptpu_dense_set(self.h, v.ctypes.data_as(ctypes.c_void_p))

    def pull(self):
        out = np.empty(self.size, np.float32)
        self.lib.ptpu_dense_pull(self.h,
                                 out.ctypes.data_as(ctypes.c_void_p))
        return out

    def push(self, grad, lr=0.01):
        g = np.ascontiguousarray(grad, np.float32).reshape(-1)
        self.lib.ptpu_dense_push(self.h,
                                 g.ctypes.data_as(ctypes.c_void_p), lr)

    def save(self, path):
        if not self.lib.ptpu_dense_save(self.h, path.encode()):
            raise IOError(f"dense table save failed: {path}")

    def load(self, path):
        if not self.lib.ptpu_dense_load(self.h, path.encode()):
            raise IOError(f"dense table load failed: {path}")

    def __len__(self):
        return self.size

    def __del__(self):
        if getattr(self, 'h', None) and self.lib:
            self.lib.ptpu_dense_destroy(self.h)
            self.h = None
