"""Dtype registry mapping paddle-style dtype names to JAX dtypes.

Reference parity: paddle/fluid/framework/framework.proto VarType (:106) enumerates
the dtype vocabulary; python/paddle/fluid/data_feeder.py convert_dtype does the
string mapping. Here dtypes are plain numpy/jax dtypes with paddle-style aliases.
"""
import jax.numpy as jnp
import numpy as np

bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR2DTYPE = {
    'bool': bool_, 'uint8': uint8, 'int8': int8, 'int16': int16,
    'int32': int32, 'int64': int64, 'float16': float16, 'bfloat16': bfloat16,
    'float32': float32, 'float64': float64, 'complex64': complex64,
    'complex128': complex128,
}

_FLOATS = {jnp.dtype(d) for d in (float16, bfloat16, float32, float64)}


def convert_dtype(dtype):
    """Normalize a dtype spec (str / np.dtype / jnp dtype) to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _STR2DTYPE:
            raise TypeError(f"Unsupported dtype string: {dtype!r}")
        return jnp.dtype(_STR2DTYPE[dtype])
    return jnp.dtype(dtype)


def dtype_name(dtype):
    d = jnp.dtype(dtype)
    return d.name


def is_floating(dtype):
    return jnp.dtype(dtype) in _FLOATS or jnp.issubdtype(jnp.dtype(dtype), np.floating)


def is_integer(dtype):
    return jnp.issubdtype(jnp.dtype(dtype), np.integer)
