"""Runtime stats + metrics registry.

Reference parity: paddle/fluid/platform/monitor.h — StatRegistry over
named int64 stats (STAT_INT / DEFINE_INT_STATUS, e.g.
STAT_total_feasign_num_in_mem) surfaced through
core.get_int_stats(). Subsystems bump named counters; tooling reads a
snapshot.

TPU-native shape (observability v2): the legacy int/float StatRegistry
stays as-is (PS feasign counts, executor run counts), and a typed
metrics layer grows beside it — Counter / Gauge / Histogram with label
support, a Prometheus text-exposition renderer, a JSON snapshot API and
an embeddable /metrics HTTP endpoint. The profiler's step-telemetry
reporter and the hot-path instrumentation (executor, collectives,
dataloader, jit) all publish here.
"""
import json
import threading
import time

# ---------------------------------------------------------------------------
# monotonic time source for staleness stamps + metric history (ISSUE 18)
# ---------------------------------------------------------------------------
# Injectable so alert/staleness tests run on a deterministic clock:
# every Counter/Gauge/Histogram observation stamps `last_update` from
# here, and MetricHistory/AlertManager default to the same source.
_time_fn = time.monotonic


def set_time_fn(fn):
    """Swap the monotonic clock behind staleness stamps and history
    sampling (None restores time.monotonic). Returns the previous fn
    so tests can restore it."""
    global _time_fn
    prev = _time_fn
    _time_fn = fn or time.monotonic
    return prev


def now():
    return _time_fn()


# ---------------------------------------------------------------------------
# legacy int/float stats (platform/monitor.h parity) — API unchanged
# ---------------------------------------------------------------------------
class Stat:
    __slots__ = ('name', '_value', '_lock')

    def __init__(self, name, value=0):
        self.name = name
        self._value = value
        self._lock = threading.Lock()

    def add(self, delta=1):
        with self._lock:
            self._value += delta
            return self._value

    def set(self, value):
        with self._lock:
            self._value = value

    def get(self):
        with self._lock:
            return self._value


class StatRegistry:
    """Parity: platform/monitor.h StatRegistry (singleton per value
    type; here one registry holds both int and float stats)."""

    def __init__(self):
        self._stats = {}
        self._lock = threading.Lock()

    def stat(self, name):
        with self._lock:
            s = self._stats.get(name)
            if s is None:
                s = self._stats[name] = Stat(name)
            return s

    def add(self, name, delta=1):
        return self.stat(name).add(delta)

    def set(self, name, value):
        self.stat(name).set(value)

    def get(self, name, default=0):
        with self._lock:
            s = self._stats.get(name)
        return s.get() if s is not None else default

    def snapshot(self):
        with self._lock:
            stats = list(self._stats.values())
        return {s.name: s.get() for s in stats}

    def reset(self):
        with self._lock:
            self._stats.clear()


_registry = StatRegistry()


def registry():
    return _registry


def stat_add(name, delta=1):
    return _registry.add(name, delta)


def stat_set(name, value):
    _registry.set(name, value)


def get_int_stats():
    """Parity: core.get_int_stats — integer-valued snapshot."""
    return {k: int(v) for k, v in _registry.snapshot().items()
            if isinstance(v, (int, bool))}


def get_stats():
    return _registry.snapshot()


# ---------------------------------------------------------------------------
# typed metrics: Counter / Gauge / Histogram with labels
# ---------------------------------------------------------------------------
DEFAULT_BUCKETS = (.0001, .0005, .001, .005, .01, .025, .05, .1, .25, .5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0, float('inf'))


def _label_key(labelnames, labels):
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} != declared {sorted(labelnames)}")
    return tuple(str(labels[n]) for n in labelnames)


class Metric:
    """One named metric; label-less use goes through the () label set."""

    kind = 'untyped'

    def __init__(self, name, help='', labelnames=()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children = {}
        self._lock = threading.Lock()

    def _child(self, labels):
        key = _label_key(self.labelnames, labels)
        with self._lock:
            c = self._children.get(key)
            if c is None:
                c = self._children[key] = self._new_child()
            return c

    def labels(self, **labels):
        return self._child(labels)

    def _series(self):
        with self._lock:
            return dict(self._children)


class _CounterChild:
    __slots__ = ('_value', '_lock', 'last_update')

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()
        self.last_update = None     # monotonic stamp of the last publish

    def inc(self, value=1):
        if value < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += value
            self.last_update = _time_fn()
            return self._value

    def value(self):
        with self._lock:
            return self._value

    def age_s(self, now_=None):
        """Seconds since the last observation (None if never
        published) — the staleness signal alert rules and health_dump
        read to flag a section whose source engine went quiet."""
        with self._lock:
            if self.last_update is None:
                return None
            return (now_ if now_ is not None else _time_fn()) \
                - self.last_update


class Counter(Metric):
    kind = 'counter'
    _new_child = staticmethod(_CounterChild)

    def inc(self, value=1, **labels):
        return self._child(labels).inc(value)

    def value(self, **labels):
        return self._child(labels).value()


class _GaugeChild(_CounterChild):
    def inc(self, value=1):
        with self._lock:
            self._value += value
            self.last_update = _time_fn()
            return self._value

    def dec(self, value=1):
        return self.inc(-value)

    def set(self, value):
        with self._lock:
            self._value = float(value)
            self.last_update = _time_fn()


class Gauge(Metric):
    kind = 'gauge'
    _new_child = staticmethod(_GaugeChild)

    def set(self, value, **labels):
        self._child(labels).set(value)

    def inc(self, value=1, **labels):
        return self._child(labels).inc(value)

    def dec(self, value=1, **labels):
        return self._child(labels).dec(value)

    def value(self, **labels):
        return self._child(labels).value()


class _HistogramChild:
    __slots__ = ('buckets', 'counts', 'sum', 'count', '_lock',
                 'last_update')

    def __init__(self, buckets):
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()
        self.last_update = None

    def observe(self, value):
        value = float(value)
        with self._lock:
            self.sum += value
            self.count += 1
            self.last_update = _time_fn()
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self.counts[i] += 1

    def age_s(self, now_=None):
        with self._lock:
            if self.last_update is None:
                return None
            return (now_ if now_ is not None else _time_fn()) \
                - self.last_update

    def value(self):
        with self._lock:
            return {'sum': self.sum, 'count': self.count,
                    'buckets': {str(b): c for b, c in
                                zip(self.buckets, self.counts)}}

    def percentile(self, q):
        """Bucket-interpolated quantile estimate (Prometheus
        histogram_quantile semantics): find the bucket holding the
        q-th observation and interpolate linearly inside it, assuming
        uniform spread. The +Inf bucket degrades to its lower bound —
        an estimator can't see past the last finite boundary. None
        when the histogram is empty."""
        if not 0 <= q <= 100:
            raise ValueError(f"percentile {q} not in [0, 100]")
        with self._lock:
            total = self.count
            if total == 0:
                return None
            counts = list(self.counts)      # cumulative (le semantics)
        # q=0 must land in the first OCCUPIED bucket (rank 0 would match
        # any empty leading bucket and report its upper bound)
        rank = max(q / 100.0 * total, 1e-12)
        for i, c in enumerate(counts):
            if c >= rank:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                if hi == float('inf'):
                    return lo
                below = counts[i - 1] if i > 0 else 0
                in_bucket = c - below
                if in_bucket <= 0:
                    return hi
                return lo + (hi - lo) * (rank - below) / in_bucket
        return self.buckets[-2] if len(self.buckets) > 1 else 0.0

    def percentiles(self, qs=(50, 90, 99)):
        return {f'p{g}': self.percentile(g) for g in qs}


class Histogram(Metric):
    kind = 'histogram'

    def __init__(self, name, help='', labelnames=(), buckets=None):
        super().__init__(name, help, labelnames)
        b = sorted(float(x) for x in (buckets or DEFAULT_BUCKETS))
        if not b or b[-1] != float('inf'):
            b.append(float('inf'))
        self.buckets = tuple(b)

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, value, **labels):
        self._child(labels).observe(value)

    def value(self, **labels):
        return self._child(labels).value()

    def percentile(self, q, **labels):
        return self._child(labels).percentile(q)

    def percentiles(self, qs=(50, 90, 99), **labels):
        return self._child(labels).percentiles(qs)


class MetricsRegistry:
    """Get-or-create registry of typed metrics, renderable as Prometheus
    text exposition and as a JSON snapshot."""

    _KINDS = {'counter': Counter, 'gauge': Gauge, 'histogram': Histogram}

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()
        self.epoch = 0      # bumped on reset(); callers caching metric
                            # handles key their cache on this
        self.history = None     # MetricHistory once enable_history()

    def _get_or_create(self, cls, name, help, labelnames, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help=help,
                                              labelnames=labelnames,
                                              **kwargs)
                return m
        if not isinstance(m, cls):
            raise TypeError(f"metric {name!r} already registered as "
                            f"{m.kind}, not {cls.kind}")
        if tuple(labelnames) != m.labelnames:
            raise ValueError(
                f"metric {name!r} labelnames {m.labelnames} != "
                f"{tuple(labelnames)}")
        return m

    def counter(self, name, help='', labelnames=()):
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help='', labelnames=()):
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help='', labelnames=(), buckets=None):
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def metrics_list(self):
        """Stable copy of the registered metrics (history sampler's
        iteration surface — no torn dict under concurrent creates)."""
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def reset(self):
        """Drop every metric (staleness stamps die with the children),
        bump the epoch so cached handles invalidate, and clear the
        history rings — old samples must not bleed across an epoch."""
        with self._lock:
            self._metrics.clear()
            self.epoch += 1
        if self.history is not None:
            self.history.clear()

    # -- metric history (ISSUE 18) -------------------------------------------
    def enable_history(self, capacity=240, min_interval_s=0.0,
                       clock=None):
        """Opt-in per-series ring-buffer history. Idempotent: returns
        the existing MetricHistory when already enabled (capacity and
        clock of the first call win)."""
        if self.history is None:
            from . import timeseries
            self.history = timeseries.MetricHistory(
                self, capacity=capacity, min_interval_s=min_interval_s,
                clock=clock)
        return self.history

    def history_tick(self):
        """Piggyback hook for existing flush/publish cadences
        (serving metrics publish, profiler step telemetry): sample the
        rings + run attached alert evaluation, metadata-only, no-op
        until enable_history()."""
        if self.history is not None:
            self.history.tick()

    # -- renderers -----------------------------------------------------------
    @staticmethod
    def _fmt_labels(labelnames, key, extra=()):
        pairs = [f'{n}="{_escape(v)}"' for n, v in zip(labelnames, key)]
        pairs.extend(f'{n}="{_escape(v)}"' for n, v in extra)
        return '{' + ','.join(pairs) + '}' if pairs else ''

    def prometheus_text(self, include_stats=True, include_age=False):
        """Prometheus text exposition format (0.0.4), legacy STAT_*
        stats included as untyped gauges. `include_age` appends one
        `# age ...` comment line per sample (scrapers ignore unknown
        comments) carrying the per-series staleness stamp — the
        operator-facing twin of snapshot()'s `age_s`."""
        lines = []
        t = _time_fn()
        metrics = self.metrics_list()
        for m in metrics:
            if m.help:
                lines.append(f'# HELP {m.name} {m.help}')
            lines.append(f'# TYPE {m.name} {m.kind}')
            for key, child in sorted(m._series().items()):
                if m.kind == 'histogram':
                    v = child.value()
                    for b, c in v['buckets'].items():
                        b = '+Inf' if b == 'inf' else b
                        lbl = self._fmt_labels(m.labelnames, key,
                                               extra=[('le', b)])
                        lines.append(f'{m.name}_bucket{lbl} {c}')
                    lbl = self._fmt_labels(m.labelnames, key)
                    lines.append(f'{m.name}_sum{lbl} {_num(v["sum"])}')
                    lines.append(f'{m.name}_count{lbl} {v["count"]}')
                else:
                    lbl = self._fmt_labels(m.labelnames, key)
                    lines.append(f'{m.name}{lbl} {_num(child.value())}')
                if include_age:
                    age = child.age_s(t)
                    if age is not None:
                        lbl = self._fmt_labels(m.labelnames, key)
                        lines.append(
                            f'# age {m.name}{lbl} {age:.3f}')
        if include_stats:
            for name, v in sorted(_registry.snapshot().items()):
                safe = _sanitize(name)
                lines.append(f'# TYPE {safe} gauge')
                lines.append(f'{safe} {_num(v)}')
        return '\n'.join(lines) + '\n'

    def snapshot(self):
        """JSON-ready nested snapshot: {metric: {kind, series: [{labels,
        value, age_s}]}} plus the legacy stats dict; when history is
        enabled, a downsampled `series` export of the rings rides
        along (ISSUE 18)."""
        out = {}
        t = _time_fn()
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            series = []
            for key, child in sorted(m._series().items()):
                series.append({'labels': dict(zip(m.labelnames, key)),
                               'value': child.value(),
                               'age_s': child.age_s(t)})
            out[m.name] = {'kind': m.kind, 'series': series}
        snap = {'metrics': out, 'stats': _registry.snapshot()}
        if self.history is not None:
            snap['series'] = self.history.export()
        return snap

    def snapshot_json(self, **kwargs):
        return json.dumps(self.snapshot(), **kwargs)


def _escape(v):
    return str(v).replace('\\', r'\\').replace('"', r'\"').replace(
        '\n', r'\n')


def _num(v):
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _sanitize(name):
    return ''.join(c if c.isalnum() or c == '_' else '_' for c in name)


_metrics = MetricsRegistry()


def metrics():
    return _metrics


def counter(name, help='', labelnames=()):
    return _metrics.counter(name, help=help, labelnames=labelnames)


def gauge(name, help='', labelnames=()):
    return _metrics.gauge(name, help=help, labelnames=labelnames)


def histogram(name, help='', labelnames=(), buckets=None):
    return _metrics.histogram(name, help=help, labelnames=labelnames,
                              buckets=buckets)


def prometheus_text():
    return _metrics.prometheus_text()


def metrics_snapshot():
    return _metrics.snapshot()


# ---------------------------------------------------------------------------
# /metrics HTTP endpoint
# ---------------------------------------------------------------------------
class MetricsServer:
    """Tiny embeddable exporter: GET /metrics → Prometheus text, GET
    /metrics.json → JSON snapshot. Daemon-threaded; close() to stop."""

    def __init__(self, port=0, addr='127.0.0.1', registry=None):
        import http.server
        reg = registry or _metrics

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 (stdlib API name)
                if self.path.startswith('/metrics.json'):
                    body = reg.snapshot_json().encode()
                    ctype = 'application/json'
                elif self.path.startswith('/metrics'):
                    body = reg.prometheus_text().encode()
                    ctype = 'text/plain; version=0.0.4; charset=utf-8'
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header('Content-Type', ctype)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr spam
                pass

        self._httpd = http.server.ThreadingHTTPServer((addr, port), Handler)
        self.addr, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def start_metrics_server(port=0, addr='127.0.0.1'):
    return MetricsServer(port=port, addr=addr)
