"""Runtime stats registry.

Reference parity: paddle/fluid/platform/monitor.h — StatRegistry over
named int64 stats (STAT_INT / DEFINE_INT_STATUS, e.g.
STAT_total_feasign_num_in_mem) surfaced through
core.get_int_stats(). Subsystems bump named counters; tooling reads a
snapshot.

TPU-native shape: one thread-safe registry of int/float stats; the PS
service, DataLoader and Executor report through it (the reference's
monitored quantities are PS feasign counts and worker progress).
"""
import threading


class Stat:
    __slots__ = ('name', '_value', '_lock')

    def __init__(self, name, value=0):
        self.name = name
        self._value = value
        self._lock = threading.Lock()

    def add(self, delta=1):
        with self._lock:
            self._value += delta
            return self._value

    def set(self, value):
        with self._lock:
            self._value = value

    def get(self):
        with self._lock:
            return self._value


class StatRegistry:
    """Parity: platform/monitor.h StatRegistry (singleton per value
    type; here one registry holds both int and float stats)."""

    def __init__(self):
        self._stats = {}
        self._lock = threading.Lock()

    def stat(self, name):
        with self._lock:
            s = self._stats.get(name)
            if s is None:
                s = self._stats[name] = Stat(name)
            return s

    def add(self, name, delta=1):
        return self.stat(name).add(delta)

    def set(self, name, value):
        self.stat(name).set(value)

    def get(self, name, default=0):
        with self._lock:
            s = self._stats.get(name)
        return s.get() if s is not None else default

    def snapshot(self):
        with self._lock:
            stats = list(self._stats.values())
        return {s.name: s.get() for s in stats}

    def reset(self):
        with self._lock:
            self._stats.clear()


_registry = StatRegistry()


def registry():
    return _registry


def stat_add(name, delta=1):
    return _registry.add(name, delta)


def stat_set(name, value):
    _registry.set(name, value)


def get_int_stats():
    """Parity: core.get_int_stats — integer-valued snapshot."""
    return {k: int(v) for k, v in _registry.snapshot().items()
            if isinstance(v, (int, bool))}


def get_stats():
    return _registry.snapshot()
