"""Numerics observatory — fused tensor stats, NaN/Inf localization,
cross-rank divergence sentinel.

Reference parity role: the `FLAGS_check_nan_inf` debugger
(framework/details/nan_inf_utils_detail.cc:299 — per-kernel tensor scan
naming the offending op) plus the tensor-stat printing of
`check_numerics` tooling, redesigned for TPU execution where a blocking
host sync per op output (the seed's eager guard, core/autograd.py) is
the one thing a production step cannot afford and where the hot path is
a single compiled XLA program the eager guard never sees.

Three layers:

  * **Fused `TensorStats`** — one reduction pass per tensor producing a
    fixed `float32[N_STATS]` vector (nonfinite/zero/subnormal counts,
    finite min/max/mean/rms, l2 norm, numel). `stats_vec` is traceable
    (used as jit taps inside compiled steps); `collect()` batches any
    number of tensors into ONE host sync.
  * **Eager guard** — `FLAGS_check_nan_inf` rewritten on device-side
    flag accumulation: each op ORs a tiny `any(~isfinite)` scalar into a
    running device flag and journals `(op, fn, inputs)`; `flush()` (the
    optimizer step boundary) performs the single host sync, and only on
    a trip replays the journal to localize the FIRST op that produced a
    nonfinite output from finite inputs — raised as a structured
    `NumericsError` with a JSON artifact (the `DeviceOOMError` report
    shape from core/memory.py). `FLAGS_check_nan_inf_deferred=1` opts
    into the one-sync-per-step mode; the default keeps the legacy
    raise-at-the-op semantics (one FUSED flag sync per op instead of
    the seed's one per output, now with full stats in the report).
  * **Jit taps + divergence sentinel** — compiled train steps
    (hybrid_engine / spmd_pipeline / jit.TrainStep) thread a stats
    pytree as extra outputs; `process_jit_taps()` fetches it in one
    sync, publishes `ptpu_num_*` gauges, and raises on nonfinite grads
    naming the offending parameter. `DivergenceSentinel` allgathers a
    per-step fingerprint (grad global-norm + param checksum) across
    data-parallel ranks and reports the first divergent step and the
    offending ranks through log_util + the flight recorder.
"""
import contextlib
import functools
import json
import os
import threading
import time

import numpy as np
import jax
import jax.numpy as jnp

from . import dtypes

__all__ = [
    'N_STATS', 'STAT_FIELDS', 'TensorStats', 'stats_vec', 'tensor_stats',
    'collect', 'NumericsError', 'guard', 'flush', 'reset', 'step_guard',
    'jit_taps', 'taps_spec', 'process_jit_taps', 'publish_stats',
    'DivergenceSentinel', 'render_numerics_report',
    'render_divergence_report', 'write_report', 'enabled', 'taps_enabled',
]

# ---------------------------------------------------------------------------
# fused tensor statistics
# ---------------------------------------------------------------------------
STAT_FIELDS = ('nan_count', 'inf_count', 'zero_count', 'subnormal_count',
               'min', 'max', 'mean', 'rms', 'l2_norm', 'numel')
N_STATS = len(STAT_FIELDS)


def stats_vec(x):
    """Traceable fused reduction: `float32[N_STATS]` for one array.

    Counts are exact up to 2**24 elements (float32 integer range —
    beyond that they saturate in ULPs, which still distinguishes zero
    from nonzero, the decision the guards make). min/max/mean/rms/l2
    are over the FINITE elements so one NaN doesn't erase the rest of
    the distribution; the nonfinite population is reported by its own
    counters. Empty tensors produce (0,...,+inf,-inf,0,0,0,0).
    """
    x = jnp.asarray(x)
    n = int(np.prod(x.shape)) if x.ndim else 1
    if n == 0:
        return jnp.asarray([0, 0, 0, 0, np.inf, -np.inf, 0, 0, 0, 0],
                           jnp.float32)
    if dtypes.is_floating(x.dtype):
        # jnp.finfo (ml_dtypes-backed) also understands bfloat16
        tiny = float(jnp.finfo(x.dtype).tiny)
    else:
        tiny = 0.0
    x32 = x.astype(jnp.float32)
    isnan = jnp.isnan(x32)
    isinf = jnp.isinf(x32)
    finite = ~(isnan | isinf)
    f32 = jnp.float32
    nan_c = jnp.sum(isnan, dtype=f32)
    inf_c = jnp.sum(isinf, dtype=f32)
    ax = jnp.abs(x32)
    if tiny:
        # zero derived as (|x| < tiny) - subnormals: XLA backends with
        # FTZ/DAZ semantics may compare a subnormal equal to zero, which
        # would otherwise double-count it in both buckets
        sub_c = jnp.sum((ax > 0) & (ax < tiny), dtype=f32)
        zero_c = jnp.sum(ax < tiny, dtype=f32) - sub_c
    else:
        sub_c = jnp.asarray(0.0, f32)
        zero_c = jnp.sum(x32 == 0, dtype=f32)
    fin_n = jnp.maximum(jnp.sum(finite, dtype=f32), 1.0)
    xf = jnp.where(finite, x32, 0.0)
    mn = jnp.min(jnp.where(finite, x32, jnp.inf))
    mx = jnp.max(jnp.where(finite, x32, -jnp.inf))
    mean = jnp.sum(xf) / fin_n
    sq = jnp.sum(xf * xf)
    rms = jnp.sqrt(sq / fin_n)
    l2 = jnp.sqrt(sq)
    return jnp.stack([nan_c, inf_c, zero_c, sub_c, mn, mx, mean, rms, l2,
                      jnp.asarray(float(n), f32)])


@functools.lru_cache(maxsize=1)
def _stats_jit():
    # one fused XLA kernel per (shape, dtype) signature
    return jax.jit(stats_vec)


class TensorStats:
    """Host-side view of one stats vector."""

    __slots__ = tuple(STAT_FIELDS) + ('shape', 'dtype')

    def __init__(self, vec, shape=None, dtype=None):
        vec = np.asarray(vec, np.float64)
        for i, f in enumerate(STAT_FIELDS):
            setattr(self, f, float(vec[i]))
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = str(dtype) if dtype is not None else None

    @property
    def nonfinite_count(self):
        return self.nan_count + self.inf_count

    def as_dict(self):
        d = {f: getattr(self, f) for f in STAT_FIELDS}
        d['shape'] = list(self.shape) if self.shape is not None else None
        d['dtype'] = self.dtype
        return d

    def __repr__(self):
        return (f"TensorStats(nan={int(self.nan_count)} "
                f"inf={int(self.inf_count)} zero={int(self.zero_count)} "
                f"sub={int(self.subnormal_count)} min={self.min:.4g} "
                f"max={self.max:.4g} mean={self.mean:.4g} "
                f"rms={self.rms:.4g} l2={self.l2_norm:.4g} "
                f"n={int(self.numel)})")


# every host sync the observatory performs funnels through this hook so
# tests can count them (the "one extra sync per step" budget)
def _host_fetch(tree):
    return jax.device_get(tree)


def _as_array(x):
    """Tensor -> its device array; everything else through asarray
    (NOT getattr(x, 'data'): numpy's .data is a memoryview)."""
    from .tensor import Tensor
    if isinstance(x, Tensor):
        return x.data
    return jnp.asarray(x)


def tensor_stats(x):
    """Stats for one array/Tensor (one kernel, one sync)."""
    arr = _as_array(x)
    return TensorStats(_host_fetch(_stats_jit()(arr)),
                       shape=arr.shape, dtype=arr.dtype)


def collect(named):
    """{name: array/Tensor} -> {name: TensorStats} — one kernel per
    tensor dispatched asynchronously, then ONE host sync for the
    whole batch."""
    arrs = {k: _as_array(v) for k, v in named.items()}
    vecs = {k: _stats_jit()(a) for k, a in arrs.items()}
    host = _host_fetch(vecs)
    return {k: TensorStats(host[k], shape=arrs[k].shape,
                           dtype=arrs[k].dtype) for k in arrs}


# ---------------------------------------------------------------------------
# structured error + artifacts
# ---------------------------------------------------------------------------
class NumericsError(FloatingPointError):
    """Nonfinite value caught by the observatory. `.report` holds the
    JSON-ready artifact (mirrors DeviceOOMError / oom_report);
    subclasses FloatingPointError for seed-era `except` clauses."""

    def __init__(self, message, report=None, report_path=None):
        super().__init__(message)
        self.report = report or {}
        self.report_path = report_path


def _env_rank():
    try:
        return int(os.environ.get('PADDLE_TRAINER_ID', '0') or 0)
    except ValueError:
        return 0


def write_report(report, path=None):
    """Persist a numerics/divergence artifact under the log dir (the
    path health_dump renders)."""
    from .memory import default_report_dir
    name = (report.get('kind')
            if report.get('kind') in ('divergence_report',
                                      'straggler_report')
            else 'numerics_report')
    path = path or os.path.join(
        default_report_dir(),
        f"{name}.rank{report.get('rank', 0)}.{os.getpid()}.json")
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, 'w') as f:
            json.dump(report, f)
        return path
    except OSError:
        return None


def _fmt_stats_line(stats):
    if not stats:
        return '?'
    return (f"nan={int(stats.get('nan_count', 0))} "
            f"inf={int(stats.get('inf_count', 0))} "
            f"zero={int(stats.get('zero_count', 0))} "
            f"sub={int(stats.get('subnormal_count', 0))} "
            f"min={stats.get('min', 0):.4g} max={stats.get('max', 0):.4g} "
            f"mean={stats.get('mean', 0):.4g} rms={stats.get('rms', 0):.4g} "
            f"l2={stats.get('l2_norm', 0):.4g}")


def render_numerics_report(report):
    """Human rendering of a numerics_report dict (shared with
    tools/health_dump.py numerics)."""
    out = ['== numerics report ' + '=' * 41]
    out.append(f"site: {report.get('site')}   rank: {report.get('rank')}"
               + (f"   step: {report.get('step')}"
                  if report.get('step') is not None else ''))
    if report.get('op'):
        o = report.get('output') or {}
        out.append(f"first nonfinite op: {report['op']} "
                   f"(output {report.get('output_index', 0)}, "
                   f"dtype {o.get('dtype')}, shape {tuple(o.get('shape') or ())})")
        out.append('  output: ' + _fmt_stats_line(o.get('stats')))
        for i, inp in enumerate(report.get('inputs') or ()):
            out.append(f"  input[{inp.get('index', i)}] "
                       f"{inp.get('dtype')} {tuple(inp.get('shape') or ())}: "
                       + _fmt_stats_line(inp.get('stats')))
    if report.get('tensors'):
        out.append('-- nonfinite tensors ' + '-' * 39)
        for t in report['tensors']:
            marker = ' <-- first' if t.get('name') == \
                report.get('first_bad') else ''
            out.append(f"  {t.get('kind', '?'):<6} {t.get('name')}: "
                       + _fmt_stats_line(t.get('stats')) + marker)
    if report.get('journal_dropped'):
        out.append(f"(journal dropped {report['journal_dropped']} oldest "
                   "ops — origin may predate the window)")
    if report.get('message'):
        out.append(report['message'])
    return '\n'.join(out)


def render_divergence_report(report):
    out = ['== cross-rank divergence report ' + '=' * 28]
    out.append(f"first divergent step: {report.get('first_divergent_step')}"
               f"   detector rank: {report.get('rank')}   world size: "
               f"{report.get('world_size')}")
    out.append(f"offending ranks: {report.get('offending_ranks')} "
               f"(consensus of {report.get('consensus_ranks')})")
    labels = report.get('fingerprint_labels') or ()
    out.append('-- per-rank fingerprints ' + '-' * 35)
    for r, fp in sorted((report.get('ranks') or {}).items(),
                        key=lambda kv: int(kv[0])):
        mark = ' <-- divergent' if int(r) in \
            (report.get('offending_ranks') or ()) else ''
        pairs = ' '.join(f'{l}={v:.9g}' for l, v in zip(labels, fp))
        out.append(f"  rank {r}: {pairs}{mark}")
    return '\n'.join(out)


# ---------------------------------------------------------------------------
# eager guard (FLAGS_check_nan_inf v2)
# ---------------------------------------------------------------------------
class EagerNumericsGuard:
    """Device-side nonfinite-flag accumulation over eager ops.

    `observe()` is the run_op hot path: one fused `any(~isfinite)`
    scalar per op ORed into a running device flag (no host sync) and a
    journal entry `(op, fn, kwargs, inputs, out_meta)` kept for replay.
    `flush()` does the single per-step sync; on a trip the journal is
    replayed in order (ops are pure jax closures, so the replay is
    bit-deterministic) and the FIRST op whose output is nonfinite names
    the origin; its input stats distinguish "op produced the NaN" from
    "op inherited it".
    """

    def __init__(self, max_journal=None):
        self._lock = threading.Lock()
        self.max_journal = max_journal
        self.reset()

    def _cap(self):
        if self.max_journal is not None:
            return self.max_journal
        from .flags import flag
        v = flag('FLAGS_check_nan_inf_max_journal', 4096)
        # 0 is a legitimate bound (flag accumulation without replay) —
        # only None falls back to the default
        return int(4096 if v is None else v)

    def reset(self):
        with self._lock:
            self._flag = None        # device bool scalar
            self._journal = []       # (seq, name, fn, kwargs, arrs, meta)
            self._dropped = 0
            self._seq = 0

    def pending_ops(self):
        with self._lock:
            return len(self._journal)

    def has_pending(self):
        """True when a flush has anything to check — the accumulated
        device flag counts even with an empty journal (journal cap 0 =
        flag accumulation without replay)."""
        with self._lock:
            return self._flag is not None or bool(self._journal)

    # -- hot path ------------------------------------------------------------
    def observe(self, name, fn, static_kwargs, arrs, outs):
        flt = [(i, o) for i, o in enumerate(outs)
               if dtypes.is_floating(getattr(o, 'dtype', None))]
        if not flt:
            return
        bad = functools.reduce(
            jnp.logical_or,
            [jnp.any(~jnp.isfinite(o)) for _, o in flt])
        from .flags import flag
        if not flag('FLAGS_check_nan_inf_deferred', False):
            # legacy semantics: sync and raise at the offending op
            if bool(bad):
                raise self._error_at_op(
                    name, static_kwargs, arrs, outs, mode='eager-immediate')
            return
        with self._lock:
            self._flag = bad if self._flag is None else self._flag | bad
            self._seq += 1
            self._journal.append(
                (self._seq, name, fn, dict(static_kwargs or {}),
                 tuple(arrs),
                 [(tuple(o.shape), str(o.dtype)) for o in outs]))
            if len(self._journal) > self._cap():
                self._journal.pop(0)
                self._dropped += 1

    # -- step boundary -------------------------------------------------------
    def flush(self, site='eager', step=None):
        """One host sync; raises NumericsError when the step tripped.
        Returns None (clean) — the journal is dropped either way."""
        with self._lock:
            dev_flag = self._flag
            journal = self._journal
            dropped = self._dropped
            self._flag = None
            self._journal = []
            self._dropped = 0
        if dev_flag is None:
            return None
        tripped = bool(_host_fetch(dev_flag))
        if not tripped:
            return None
        raise self._localize(journal, dropped, site=site, step=step)

    # -- failure path --------------------------------------------------------
    def _localize(self, journal, dropped, site='eager', step=None):
        """Replay the journaled ops in order; the first nonfinite output
        is the origin."""
        for seq, name, fn, kwargs, arrs, meta in journal:
            try:
                outs = fn(*arrs, **kwargs)
            except Exception:
                continue
            outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
            flt = [(i, o) for i, o in enumerate(outs)
                   if dtypes.is_floating(getattr(o, 'dtype', None))]
            if not flt:
                continue
            st = collect({f'out{i}': o for i, o in flt})
            bad = [(i, st[f'out{i}']) for i, _ in flt
                   if st[f'out{i}'].nonfinite_count > 0]
            if bad:
                return self._error_at_op(
                    name, kwargs, arrs, outs, mode='eager-deferred',
                    site=site, step=step, dropped=dropped,
                    bad_index=bad[0][0], bad_stats=bad[0][1], seq=seq)
        report = {
            'kind': 'numerics_report', 'time': time.time(),
            'rank': _env_rank(), 'site': site, 'step': step,
            'mode': 'eager-deferred', 'op': None,
            'journal_dropped': dropped,
            'message': ('nonfinite flag tripped but the replay found no '
                        'nonfinite output — the originating op likely '
                        'predates the journal window'),
        }
        path = write_report(report)
        self._log(report, path)
        return NumericsError(
            'NaN or Inf detected this step (FLAGS_check_nan_inf); origin '
            'outside the op journal window\n' + render_numerics_report(report),
            report=report, report_path=path)

    def _error_at_op(self, name, kwargs, arrs, outs, mode, site='eager',
                     step=None, dropped=0, bad_index=None, bad_stats=None,
                     seq=None):
        outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
        if bad_index is None:
            st = collect({
                f'out{i}': o for i, o in enumerate(outs)
                if dtypes.is_floating(getattr(o, 'dtype', None))})
            for key, s in st.items():
                if s.nonfinite_count > 0:
                    bad_index, bad_stats = int(key[3:]), s
                    break
            if bad_index is None:       # flag raced; treat output 0
                bad_index = 0
                bad_stats = tensor_stats(outs[0])
        in_named = {f'in{i}': a for i, a in enumerate(arrs)
                    if dtypes.is_floating(getattr(a, 'dtype', None))}
        in_stats = collect(in_named) if in_named else {}
        inputs = []
        for i, a in enumerate(arrs):
            key = f'in{i}'
            if key in in_stats:
                inputs.append({'index': i, 'shape': list(a.shape),
                               'dtype': str(a.dtype),
                               'stats': in_stats[key].as_dict()})
        out = outs[bad_index]
        report = {
            'kind': 'numerics_report', 'time': time.time(),
            'rank': _env_rank(), 'site': site, 'step': step, 'mode': mode,
            'op': name, 'op_seq': seq, 'output_index': bad_index,
            'output': {'shape': list(out.shape), 'dtype': str(out.dtype),
                       'stats': bad_stats.as_dict()},
            'inputs': inputs,
            'op_kwargs': {k: repr(v)[:80] for k, v in (kwargs or {}).items()},
            'journal_dropped': dropped,
        }
        path = write_report(report)
        self._log(report, path)
        _metric_trip(site)
        return NumericsError(
            f"NaN or Inf found in output {bad_index} of op '{name}' "
            f"(FLAGS_check_nan_inf)"
            + (f" (full report: {path})" if path else '') + '\n'
            + render_numerics_report(report),
            report=report, report_path=path)

    @staticmethod
    def _log(report, path):
        try:
            from ..distributed.fleet.utils import log_util
            log_util.log_json(
                'numerics_trip', level='error', op=report.get('op'),
                site=report.get('site'), report_path=path)
        except Exception:
            pass


_guard = EagerNumericsGuard()


def guard():
    return _guard


def flush(site='eager', step=None):
    """Step-boundary check for the eager guard (one host sync). Raises
    NumericsError when the step produced a nonfinite value."""
    return _guard.flush(site=site, step=step)


def reset():
    _guard.reset()


@contextlib.contextmanager
def step_guard(site='eager', step=None):
    """Bracket one eager train step; flushes (and so checks) at exit.
    A body that raises resets the guard instead — a half-step's flag
    and journal must not leak into (and be blamed on) the next step."""
    try:
        yield _guard
    except BaseException:
        _guard.reset()
        raise
    _guard.flush(site=site, step=step)


def enabled():
    from .flags import flag
    return bool(flag('FLAGS_check_nan_inf'))


def taps_enabled():
    """Stat taps are threaded through compiled steps when either the
    NaN guard or the always-on stats flag asks for them."""
    from .flags import flag
    return bool(flag('FLAGS_check_nan_inf') or flag('FLAGS_tensor_stats'))


# ---------------------------------------------------------------------------
# jit taps (compiled-step numerics)
# ---------------------------------------------------------------------------
def jit_taps(grads, params=None, extra_norm_sq=None):
    """Traceable: build the taps pytree inside a compiled step.

    grads/params: flat {name: array} dicts. `extra_norm_sq` lets the
    engine supply a mesh-reduced global grad-norm^2 (psum over 'mp'/'pp'
    for sharded trees); default is the local sum of squares.
    """
    gvecs = {n: stats_vec(g) for n, g in (grads or {}).items()}
    pvecs = {n: stats_vec(p) for n, p in (params or {}).items()}
    if extra_norm_sq is None:
        extra_norm_sq = jnp.asarray(0.0, jnp.float32)
        for n, g in (grads or {}).items():
            extra_norm_sq = extra_norm_sq + jnp.sum(
                g.astype(jnp.float32) ** 2)
    return {'grads': gvecs, 'params': pvecs,
            'grad_norm_sq': extra_norm_sq.astype(jnp.float32)}


def taps_spec(taps):
    """Replicated PartitionSpec tree matching a jit_taps pytree (for
    shard_map out_specs)."""
    from jax.sharding import PartitionSpec as P
    return jax.tree_util.tree_map(lambda _: P(), taps)


def _metric_trip(site):
    try:
        from . import monitor as _m
        _m.counter('ptpu_num_nonfinite_steps_total',
                   help='steps on which the numerics guard tripped',
                   labelnames=('site',)).inc(1, site=site)
    except Exception:
        pass


def publish_stats(named_stats, kind='grad', global_norm=None):
    """Publish {name: TensorStats} as ptpu_num_* monitor series."""
    from . import monitor as _m
    g_norm = _m.gauge('ptpu_num_grad_norm',
                      help='per-tensor pre-clip gradient l2 norm',
                      labelnames=('param',))
    g_rms = _m.gauge('ptpu_num_tensor_rms',
                     help='per-tensor rms (grads and params)',
                     labelnames=('kind', 'param'))
    c_nf = _m.counter('ptpu_num_nonfinite_total',
                      help='nonfinite elements observed',
                      labelnames=('kind',))
    c_sub = _m.counter('ptpu_num_subnormal_total',
                       help='subnormal elements observed',
                       labelnames=('kind',))
    nonfinite = 0.0
    subnormal = 0.0
    for name, st in named_stats.items():
        if kind == 'grad':
            g_norm.set(st.l2_norm, param=name)
        g_rms.set(st.rms, kind=kind, param=name)
        nonfinite += st.nonfinite_count
        subnormal += st.subnormal_count
    if nonfinite:
        c_nf.inc(nonfinite, kind=kind)
    if subnormal:
        c_sub.inc(subnormal, kind=kind)
    if global_norm is not None:
        _m.gauge('ptpu_num_grad_norm_global',
                 help='global (all-parameter) gradient l2 norm').set(
                     global_norm)
        _m.histogram('ptpu_num_grad_norm_hist',
                     help='distribution of per-step global grad norms',
                     buckets=(.001, .01, .1, .3, 1., 3., 10., 30., 100.,
                              1000.)).observe(global_norm)
    _m.counter('ptpu_num_checks_total',
               help='numerics stat collections performed').inc(1)


def process_jit_taps(taps, site='jit', step=None, meta=None):
    """Host side of the compiled-step taps: ONE sync for the whole
    pytree, gauge publication, and (when FLAGS_check_nan_inf) a
    NumericsError naming the offending tensors.

    Returns {'grads': {name: TensorStats}, 'params': {...},
    'grad_norm': float}.
    """
    host = _host_fetch(taps)
    meta = meta or {}
    out = {'grads': {}, 'params': {}}
    for kind in ('grads', 'params'):
        for n, vec in (host.get(kind) or {}).items():
            m = meta.get(kind, {}).get(n, (None, None))
            out[kind][n] = TensorStats(vec, shape=m[0], dtype=m[1])
    gn = float(np.sqrt(max(float(host.get('grad_norm_sq', 0.0)), 0.0)))
    out['grad_norm'] = gn
    publish_stats(out['grads'], kind='grad', global_norm=gn)
    if out['params']:
        publish_stats(out['params'], kind='param')
    if enabled():
        bad = [('grad', n, st) for n, st in out['grads'].items()
               if st.nonfinite_count > 0]
        bad += [('param', n, st) for n, st in out['params'].items()
                if st.nonfinite_count > 0]
        # the per-tensor taps are shard-LOCAL under mp/pp (out_specs
        # P() surfaces device 0's shard), but grad_norm_sq is mesh-
        # reduced — a NaN confined to a non-local shard poisons it, so
        # it is the check that cannot be evaded by sharding
        gn_bad = not np.isfinite(gn)
        if bad or gn_bad:
            first_bad = bad[0][1] if bad else '<global grad norm>'
            tensors = [{'kind': k, 'name': n, 'stats': st.as_dict()}
                       for k, n, st in bad]
            report = {
                'kind': 'numerics_report', 'time': time.time(),
                'rank': _env_rank(), 'site': site, 'step': step,
                'mode': 'jit', 'op': None, 'tensors': tensors,
                'first_bad': first_bad, 'grad_norm': gn,
            }
            if not bad:
                report['message'] = (
                    'the mesh-reduced global grad norm is nonfinite but '
                    'no locally-visible tensor is — the NaN/Inf lives on '
                    'another model-parallel shard or pipeline stage')
            path = write_report(report)
            EagerNumericsGuard._log(report, path)
            _metric_trip(site)
            what = (f"first nonfinite tensor is {bad[0][0]} "
                    f"'{bad[0][1]}'" if bad else
                    f"global grad norm is {gn}")
            raise NumericsError(
                f"NaN or Inf in compiled step at {site}"
                + (f" step {step}" if step is not None else '')
                + f": {what} (FLAGS_check_nan_inf)"
                + (f" (full report: {path})" if path else '') + '\n'
                + render_numerics_report(report),
                report=report, report_path=path)
    return out


# ---------------------------------------------------------------------------
# cross-rank divergence sentinel
# ---------------------------------------------------------------------------
FINGERPRINT_LABELS = ('grad_norm', 'param_sum', 'param_l2')


def _is_tensor_leaf(x):
    from .tensor import Tensor
    return isinstance(x, Tensor)


@functools.lru_cache(maxsize=1)
def _checksum_jit():
    def _cks(leaves):
        s = jnp.asarray(0.0, jnp.float32)
        sq = jnp.asarray(0.0, jnp.float32)
        for leaf in leaves:
            l32 = leaf.astype(jnp.float32)
            s = s + jnp.sum(l32)
            sq = sq + jnp.sum(l32 * l32)
        return s, jnp.sqrt(sq)
    return jax.jit(_cks)


class DivergenceSentinel:
    """Cheap per-step cross-replica consistency check.

    Data-parallel replicas run the SAME compiled program over reduced
    grads, so their parameters (and grad global norms) must stay
    bit-identical; any drift (a flaky chip, a desynced RNG, a missed
    broadcast after restore) silently corrupts training. Each step the
    sentinel allgathers a 3-float fingerprint over the host-collective
    group (journaled by the flight recorder like every host
    collective), compares within `rtol`, and on the FIRST mismatch
    writes a divergence report naming the offending ranks — the
    consensus is the largest agreeing group (ties break toward rank
    0's value).
    """

    def __init__(self, group=None, rtol=0.0, atol=0.0, dump_dir=None,
                 check_every=1):
        self.group = group
        self.rtol = float(rtol)
        self.atol = float(atol)
        self.dump_dir = dump_dir
        self.check_every = max(1, int(check_every))
        self.first_divergent_step = None
        self.report = None
        self.report_path = None
        self.checks = 0

    def _group(self):
        if self.group is not None:
            return self.group
        try:
            from ..distributed import host_collectives as HC
            return HC.host_group()
        except Exception:
            return None

    def fingerprint(self, grad_norm=None, params=None):
        """3-float fingerprint; `params` is a {name: array/Tensor} dict
        (or any pytree) checksummed in one fused kernel + one sync."""
        s = l2 = 0.0
        if params is not None:
            leaves = [_as_array(p) for p in
                      jax.tree_util.tree_leaves(
                          params, is_leaf=_is_tensor_leaf)]
            leaves = tuple(l for l in leaves
                           if dtypes.is_floating(getattr(l, 'dtype', None)))
            if leaves:
                sv, l2v = _host_fetch(_checksum_jit()(leaves))
                s, l2 = float(sv), float(l2v)
        gn = 0.0 if grad_norm is None else float(grad_norm)
        return np.asarray([gn, s, l2], np.float64)

    def check(self, step, grad_norm=None, params=None, fingerprint=None):
        """Returns the divergence report dict on a (first) mismatch,
        else None. No-op without an initialized multi-rank host group."""
        g = self._group()
        if g is None or g.world_size <= 1:
            return None
        if step % self.check_every != 0:
            return None
        fp = self.fingerprint(grad_norm=grad_norm, params=params) \
            if fingerprint is None else np.asarray(fingerprint, np.float64)
        self.checks += 1
        from . import monitor as _m
        _m.counter('ptpu_num_divergence_checks_total',
                   help='cross-rank fingerprint allgathers').inc(1)
        all_fps = g.all_gather(fp)       # journaled by the recorder
        consensus, offending = self._vote(all_fps)
        if not offending:
            return None
        if self.first_divergent_step is None:
            self.first_divergent_step = step
        report = {
            'kind': 'divergence_report', 'time': time.time(),
            'rank': g.rank, 'world_size': g.world_size, 'step': step,
            'first_divergent_step': self.first_divergent_step,
            'fingerprint_labels': list(FINGERPRINT_LABELS),
            'ranks': {str(r): [float(v) for v in f]
                      for r, f in enumerate(all_fps)},
            'offending_ranks': offending,
            'consensus_ranks': consensus,
            'rtol': self.rtol, 'atol': self.atol,
        }
        self.report = report
        self.report_path = write_report(
            report, None if self.dump_dir is None else os.path.join(
                self.dump_dir,
                f'divergence_report.rank{g.rank}.{os.getpid()}.json'))
        _m.counter('ptpu_num_divergence_total',
                   help='cross-rank divergence events detected').inc(1)
        try:
            from ..distributed import flight_recorder as fr
            rec = fr.recorder()
            seq = rec.record_enqueue('divergence_detected', group=g.gid,
                                     mode='numerics')
            rec.record_complete(seq, ok=False)
        except Exception:
            pass
        try:
            from ..distributed.fleet.utils import log_util
            log_util.log_json(
                'divergence_detected', level='error', step=step,
                first_divergent_step=self.first_divergent_step,
                offending_ranks=offending,
                report_path=self.report_path)
        except Exception:
            pass
        return report

    def _vote(self, all_fps):
        """Largest agreeing group is the consensus; ties break toward
        the group containing rank 0."""
        n = len(all_fps)
        groups = []          # list[(member ranks)]
        for r in range(n):
            placed = False
            for grp in groups:
                # equal_nan: every rank hitting the SAME nonfinite step
                # is agreement (a numerics problem, not divergence)
                if np.allclose(all_fps[grp[0]], all_fps[r],
                               rtol=self.rtol, atol=self.atol,
                               equal_nan=True):
                    grp.append(r)
                    placed = True
                    break
            if not placed:
                groups.append([r])
        if len(groups) <= 1:
            return list(range(n)), []
        groups.sort(key=lambda grp: (-len(grp), grp[0]))
        consensus = groups[0]
        offending = sorted(r for grp in groups[1:] for r in grp)
        return consensus, offending


# ---------------------------------------------------------------------------
# telemetry snapshot (StepTelemetry / bench.py)
# ---------------------------------------------------------------------------
def snapshot():
    """JSON-ready numerics telemetry read back from the monitor registry
    (zeros when the observatory never ran)."""
    from . import monitor as _m
    reg = _m.metrics()

    def _total(name):
        m = reg.get(name)
        if m is None:
            return 0.0
        return sum(c.value() for c in m._series().values())

    def _gauge(name):
        m = reg.get(name)
        if m is None:
            return None
        series = m._series()
        if not series:
            return None
        return next(iter(series.values())).value()

    return {
        'grad_norm_global': _gauge('ptpu_num_grad_norm_global'),
        'nonfinite_total': _total('ptpu_num_nonfinite_total'),
        'nonfinite_steps': _total('ptpu_num_nonfinite_steps_total'),
        'checks_total': _total('ptpu_num_checks_total'),
        'divergence_checks': _total('ptpu_num_divergence_checks_total'),
        'divergence_events': _total('ptpu_num_divergence_total'),
        'amp_skipped_steps': _total('ptpu_amp_skipped_steps_total'),
        'amp_loss_scale': _gauge('ptpu_amp_loss_scale'),
    }
