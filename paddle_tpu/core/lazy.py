"""Lazy op-fusion window — batch eager ops into ONE XLA dispatch.

Reference parity: the role of the generated `core.ops.*` fast paths
(pybind/op_function_generator.cc:519) — cutting per-op Python/dispatch
overhead on the eager path. On a tunneled TPU each eager op costs a
full round trip (~8 ms measured, PARITY.md); inside a

    with paddle.lazy_guard():
        ...   # N eager ops
    y.numpy()

window the ops record symbolically (shapes via jax.eval_shape) and
execute as one jitted program at the first materialization (window
exit, `.numpy()`, `float()`, printing) — N round trips become 1.
Windows with the same op structure + shapes reuse the compiled program
(structural cache), so a repeated ad-hoc loop pays one compile.

Scope: a fusion window is a NO-GRAD region (the tape needs concrete
residuals); entering it disables grad recording for the window.
"""
import contextlib

import jax
import jax.numpy as jnp


class _LazyState:
    __slots__ = ('nodes', 'tensors', 'avals', 'consts', 'const_order')

    def __init__(self):
        self.nodes = []        # (name, fn, in_refs, kwargs, out_ids)
        self.tensors = {}      # out_id -> Tensor (lazy, awaiting data)
        self.avals = {}        # out_id -> ShapeDtypeStruct
        self.consts = {}       # const_id -> concrete array
        self.const_order = []


_STATE = None
_COMPILE_CACHE = {}
_CACHE_MAX = 256        # bound: value-bearing closures key by identity
                        # (can't share safely) and would otherwise grow
                        # one permanent entry per window


def active():
    return _STATE is not None


def record(name, fn, tensor_args, kwargs):
    """The run_op lazy hook: record the op symbolically, return lazy
    output Tensors carrying only shape/dtype."""
    from .tensor import Tensor
    st = _STATE
    in_refs = []
    in_avals = []
    for t in tensor_args:
        tid = id(t)
        if tid in st.tensors:                  # produced in this window
            in_refs.append(('v', tid))
            in_avals.append(st.avals[tid])
        else:                                  # concrete window input
            arr = t.data
            cid = id(arr)
            if cid not in st.consts:
                st.consts[cid] = arr
                st.const_order.append(cid)
            in_refs.append(('c', cid))
            in_avals.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))

    out_aval = jax.eval_shape(lambda *xs: fn(*xs, **kwargs), *in_avals)
    multi = isinstance(out_aval, (tuple, list))
    out_avals = list(out_aval) if multi else [out_aval]
    outs = []
    out_ids = []
    for av in out_avals:
        t = Tensor.__new__(Tensor)
        t._data = av                       # placeholder (shape/dtype ok)
        t.stop_gradient = True
        t.grad = None
        t._node = None
        t.name = None
        t.persistable = False
        t.is_distributed = False
        t._lazy = True
        outs.append(t)
        out_ids.append(id(t))
        st.tensors[id(t)] = t
        st.avals[id(t)] = av
    st.nodes.append((name, fn, tuple(in_refs), kwargs, tuple(out_ids)))
    return tuple(outs) if multi else outs[0]


def _val_fp(v):
    """Fingerprint one closed-over/default value; None = value-bearing
    (array) — the whole fn must fall back to identity keying."""
    if hasattr(v, 'shape') and hasattr(v, 'dtype'):
        return None
    if isinstance(v, (int, float, str, bool, bytes, type(None))):
        return ('lit', v)
    if isinstance(v, tuple):
        subs = tuple(_val_fp(x) for x in v)
        return None if any(s is None for s in subs) else ('tup', subs)
    if callable(v):
        return ('fn', _fn_key(v))
    return ('obj', id(v))


def _fn_key(fn):
    """Structural identity of an op fn. Many ops build a fresh closure
    per call over the same code object; keying on the code + a
    fingerprint of the closed-over cells AND default args (ops bake
    attributes as defaults) lets identical windows share the compiled
    program. Values holding arrays fall back to id(fn) — a cache hit
    would otherwise replay the OLD fn's baked-in array."""
    code = getattr(fn, '__code__', None)
    if code is None:
        return ('id', id(fn))
    parts = []
    for c in fn.__closure__ or ():
        try:
            v = c.cell_contents
        except ValueError:                      # empty cell
            parts.append(('empty',))
            continue
        fp = _val_fp(v)
        if fp is None:
            return ('id', id(fn))               # value-bearing closure
        parts.append(fp)
    for v in (fn.__defaults__ or ()):
        fp = _val_fp(v)
        if fp is None:
            return ('id', id(fn))
        parts.append(('def', fp))
    for k, v in sorted((fn.__kwdefaults__ or {}).items()):
        fp = _val_fp(v)
        if fp is None:
            return ('id', id(fn))
        parts.append(('kwdef', k, fp))
    return ('code', id(code), tuple(parts))


def _structural_key(st):
    """Cache key: op sequence + input shapes (NOT values)."""
    parts = []
    # canonical slot per const/value id
    slot = {cid: i for i, cid in enumerate(st.const_order)}
    vslot = {}
    for name, fn, in_refs, kwargs, out_ids in st.nodes:
        for oid in out_ids:
            vslot[oid] = len(vslot)
        ins = tuple((k, slot[r] if k == 'c' else vslot[r])
                    for k, r in in_refs)
        parts.append((name, _fn_key(fn), ins,
                      tuple(sorted((k, repr(v))
                                   for k, v in kwargs.items())),
                      len(out_ids)))
    shapes = tuple((tuple(st.consts[c].shape), str(st.consts[c].dtype))
                   for c in st.const_order)
    return (tuple(parts), shapes)


def flush():
    """Execute every recorded op as ONE jitted program and backfill the
    lazy tensors. The window (if still open) continues with fresh
    state."""
    global _STATE
    st = _STATE
    if st is None or not st.nodes:
        return
    out_ids_all = [oid for node in st.nodes for oid in node[4]]
    const_order = list(st.const_order)

    key = _structural_key(st)
    compiled = _COMPILE_CACHE.get(key)
    if compiled is None:
        # freeze the structure; a cache hit replays a DIFFERENT window
        # with the same structure, and results align positionally
        frozen = [(fn, in_refs, kwargs, out_ids)
                  for _, fn, in_refs, kwargs, out_ids in st.nodes]
        corder = tuple(const_order)

        def replay(consts):
            env = dict(zip(corder, consts))
            for fn, in_refs, kwargs, out_ids in frozen:
                args = [env[r] for _, r in in_refs]
                out = fn(*args, **kwargs)
                outs = list(out) if isinstance(out, (tuple, list)) \
                    else [out]
                for oid, o in zip(out_ids, outs):
                    env[oid] = o
            return [env[oid] for f in frozen for oid in f[3]]

        compiled = jax.jit(replay)
        if len(_COMPILE_CACHE) >= _CACHE_MAX:
            _COMPILE_CACHE.pop(next(iter(_COMPILE_CACHE)))
        _COMPILE_CACHE[key] = compiled

    # reset BEFORE backfilling so .data access does not re-enter
    _STATE = _LazyState()
    try:
        results = compiled([st.consts[c] for c in const_order])
    except Exception as e:
        # poison the window's tensors: reading them must error loudly,
        # not hand back a ShapeDtypeStruct placeholder
        for oid in out_ids_all:
            t = st.tensors[oid]
            t.__dict__.pop('_lazy', None)
            t._lazy_error = e
        raise
    for oid, arr in zip(out_ids_all, results):
        t = st.tensors[oid]
        t._data = arr
        if hasattr(t, '_lazy'):
            del t._lazy


@contextlib.contextmanager
def lazy_guard():
    """Fuse the eager ops issued inside this block into one XLA dispatch
    per materialization (no-grad region)."""
    from . import autograd
    global _STATE
    if _STATE is not None:
        yield                                  # nested: inert
        return
    _STATE = _LazyState()
    try:
        with autograd.no_grad():
            yield
            flush()
    finally:
        # materialize anything still pending even if the body raised
        try:
            flush()
        finally:
            _STATE = None
