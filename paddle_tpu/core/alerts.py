"""Declarative alert rules over metric history rings (ISSUE 18).

The watching half of the telemetry time axis: `AlertRule` declares a
predicate over one metric's history (threshold / windowed delta /
rate-of-change / cross-series spread / EWMA-relative drop / publish
staleness), with a `for_s` sustain bound and a hysteretic clear, and
`AlertManager` runs a set of rules against a `MetricHistory`
(core/timeseries.py) on every `tick()`.

State machine per rule (deterministic on the injected clock):

    ok --breach--> pending --sustained for_s--> firing
    firing --clear-condition held clear_for_s--> ok (resolved)

A rule with `clear_value` clears on a SEPARATE (easier) threshold
than it fired on — the hysteresis that keeps a signal oscillating
around the bound from flapping the alert.

Firing and resolving are events, not just state: each transition
emits a structured `log_util.log_json` record, a flight-recorder
journal entry (the PR-2 ring the hang reports dump), bumps
`ptpu_alert_fired_total` / `ptpu_alert_resolved_total` and flips
`ptpu_alert_active{rule,severity}`, and rewrites the capped
`alert_report` artifact when a report dir is configured — so a bench
leg, a health_dump, and a post-mortem all see the same record.

`default_rules()` is the engine-scope pack over signals PRs 6-17
already publish; `router_rules()` is the cluster-scope pack the
ClusterRouter evaluates over its federated registry — together the
complete input plane for the ROADMAP autoscaler.
"""
import json
import os
import threading

from . import monitor as _mon

SEVERITIES = ('info', 'warn', 'critical')

_OPS = {
    '>': lambda a, b: a > b,
    '>=': lambda a, b: a >= b,
    '<': lambda a, b: a < b,
    '<=': lambda a, b: a <= b,
}


class AlertRule:
    """One declarative watch over a metric's history.

    kind:
      threshold  last value `op` value (any series of the metric)
      delta      windowed increment >= value (counters: storms)
      rate       per-second slope `op` value over window_s
      spread     max(last) - min(last) across series >= value
                 (cluster imbalance; needs >= 2 series)
      ewma_drop  last < value * EWMA(tau_s) — relative regression
                 against the series' own trend (value is a fraction)
      staleness  publish-stamp age of every series > value seconds
                 (the source engine went quiet)
      predicate  fn(history, now) -> truthy breach value (escape
                 hatch for composite conditions)

    `for_s` is the sustain bound before firing; `clear_for_s`
    (default: for_s) how long the clear condition must hold;
    `clear_value` an optional hysteretic clear threshold.
    """

    def __init__(self, name, metric=None, kind='threshold', op='>',
                 value=None, for_s=0.0, clear_value=None,
                 clear_for_s=None, window_s=60.0, tau_s=30.0,
                 severity='warn', description='', labels=None,
                 predicate=None, min_points=2):
        if severity not in SEVERITIES:
            raise ValueError(f"severity {severity!r} not in "
                             f"{SEVERITIES}")
        if kind != 'predicate' and metric is None:
            raise ValueError(f"rule {name!r}: metric required")
        if kind == 'predicate' and predicate is None:
            raise ValueError(f"rule {name!r}: predicate fn required")
        if op not in _OPS:
            raise ValueError(f"rule {name!r}: unknown op {op!r}")
        self.name = name
        self.metric = metric
        self.kind = kind
        self.op = op
        self.value = value
        self.for_s = float(for_s)
        self.clear_value = clear_value
        self.clear_for_s = (float(clear_for_s) if clear_for_s
                            is not None else self.for_s)
        self.window_s = float(window_s)
        self.tau_s = float(tau_s)
        self.severity = severity
        self.description = description
        self.labels = dict(labels) if labels else None
        self.predicate = predicate
        self.min_points = int(min_points)

    # -- evaluation ----------------------------------------------------------
    def _series(self, history):
        rows = history.iter_series(self.metric)
        if self.labels is not None:
            want = tuple(str(v) for _k, v in
                         sorted(self.labels.items()))
            rows = [(k, p) for k, p in rows if k == want]
        return rows

    def check(self, history, now, threshold=None):
        """(breach, info) for the firing condition — or, with
        `threshold`, for an alternate bound (the manager passes
        `clear_value` here to test the hysteretic clear)."""
        if self.kind == 'predicate':
            v = self.predicate(history, now)
            return bool(v), {'value': v if not isinstance(v, bool)
                             else None, 'series': None}
        bound = self.value if threshold is None else threshold
        cmp = _OPS[self.op]
        worst = None
        if self.kind == 'threshold':
            for key, pts in self._series(history):
                if not pts:
                    continue
                v = pts[-1][1]
                if cmp(v, bound) and (
                        worst is None or abs(v) > abs(worst[1])):
                    worst = (key, v)
        elif self.kind == 'delta':
            for key, pts in self._series(history):
                if len(pts) < 2:
                    continue
                t0 = now - self.window_s
                base = None
                for pt, pv in pts:
                    if pt <= t0:
                        base = pv
                    else:
                        break
                if base is None:
                    base = pts[0][1]
                d = pts[-1][1] - base
                if d >= bound and (worst is None or d > worst[1]):
                    worst = (key, d)
        elif self.kind == 'rate':
            for key, pts in self._series(history):
                if len(pts) < self.min_points:
                    continue
                t0 = now - self.window_s
                base_t, base_v = pts[0]
                for pt, pv in pts:
                    if pt <= t0:
                        base_t, base_v = pt, pv
                    else:
                        break
                span = pts[-1][0] - base_t
                if span <= 0:
                    continue
                r = (pts[-1][1] - base_v) / span
                if cmp(r, bound) and (
                        worst is None or abs(r) > abs(worst[1])):
                    worst = (key, r)
        elif self.kind == 'spread':
            lasts = [(k, p[-1][1]) for k, p in self._series(history)
                     if p]
            if len(lasts) >= 2:
                vals = [v for _k, v in lasts]
                spread = max(vals) - min(vals)
                if spread >= bound:
                    hi = max(lasts, key=lambda kv: kv[1])
                    worst = (hi[0], spread)
        elif self.kind == 'ewma_drop':
            import math
            for key, pts in self._series(history):
                if len(pts) < max(self.min_points, 3):
                    continue
                acc = pts[0][1]
                for (ta, _va), (tb, vb) in zip(pts, pts[1:]):
                    dt = max(tb - ta, 0.0)
                    alpha = 1.0 - math.exp(
                        -dt / max(self.tau_s, 1e-9))
                    acc += alpha * (vb - acc)
                if acc <= 0:
                    continue
                frac = pts[-1][1] / acc
                if frac < bound and (
                        worst is None or frac < worst[1]):
                    worst = (key, frac)
        elif self.kind == 'staleness':
            m = history.registry.get(self.metric)
            if m is not None:
                ages = [(key, child.age_s(now))
                        for key, child in m._series().items()]
                ages = [(k, a) for k, a in ages if a is not None]
                if ages:
                    k, a = max(ages, key=lambda ka: ka[1])
                    if a > bound:
                        worst = (k, a)
        else:
            raise ValueError(f"unknown rule kind {self.kind!r}")
        if worst is None:
            return False, {'value': None, 'series': None}
        key, v = worst
        return True, {'value': v,
                      'series': list(key) if key else None}

    def clear_check(self, history, now):
        """True while the CLEAR condition holds (i.e. the firing
        condition — against clear_value when set — is false)."""
        if self.kind == 'predicate' or self.clear_value is None:
            breach, _ = self.check(history, now)
            return not breach
        breach, _ = self.check(history, now,
                               threshold=self.clear_value)
        return not breach

    def describe(self):
        return {'rule': self.name, 'metric': self.metric,
                'kind': self.kind, 'op': self.op, 'value': self.value,
                'for_s': self.for_s, 'clear_value': self.clear_value,
                'clear_for_s': self.clear_for_s,
                'window_s': self.window_s,
                'severity': self.severity,
                'description': self.description}


class _RuleState:
    __slots__ = ('state', 'pending_since', 'firing_since',
                 'clear_since', 'fired', 'last_value', 'last_series')

    def __init__(self):
        self.state = 'ok'
        self.pending_since = None
        self.firing_since = None
        self.clear_since = None
        self.fired = 0
        self.last_value = None
        self.last_series = None


class AlertManager:
    """Evaluate a rule set against a MetricHistory.

    Attaches itself to the history's tick loop (detach() to stop).
    Alert gauges/counters land in `registry` (default: the
    process-global monitor registry) so any scrape sees them even
    when the history runs over a private registry (the router's
    federated one).
    """

    MAX_EVENTS = 128

    def __init__(self, history, rules=None, clock=None, registry=None,
                 source='engine', report_dir=None, attach=True):
        self.history = history
        self.rules = list(rules if rules is not None
                          else default_rules())
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names: {names}")
        self._clock = clock or history._clock
        self.registry = registry or _mon.metrics()
        self.source = source
        self.report_dir = (report_dir
                           or os.environ.get('PTPU_SERVE_REPORT_DIR')
                           or os.environ.get('FLEET_LOG_DIR'))
        self.last_report_path = None
        self._states = {r.name: _RuleState() for r in self.rules}
        self._events = []
        self._evals = 0
        self._lock = threading.Lock()
        if attach:
            history.attach(self)

    def detach(self):
        self.history.detach(self)

    # -- the state machine ---------------------------------------------------
    def evaluate(self, now=None):
        """One pass over every rule; returns the list of transition
        events this pass produced."""
        t = self._clock() if now is None else now
        transitions = []
        with self._lock:
            self._evals += 1
            for rule in self.rules:
                st = self._states[rule.name]
                if st.state in ('ok', 'pending'):
                    breach, info = rule.check(self.history, t)
                    if breach:
                        st.last_value = info['value']
                        st.last_series = info['series']
                        if st.state == 'ok':
                            st.state = 'pending'
                            st.pending_since = t
                        if t - st.pending_since >= rule.for_s:
                            st.state = 'firing'
                            st.firing_since = t
                            st.clear_since = None
                            st.fired += 1
                            transitions.append(
                                self._event('fired', rule, st, t))
                    else:
                        st.state = 'ok'
                        st.pending_since = None
                else:   # firing: watch the hysteretic clear
                    if rule.clear_check(self.history, t):
                        if st.clear_since is None:
                            st.clear_since = t
                        if t - st.clear_since >= rule.clear_for_s:
                            st.state = 'ok'
                            st.pending_since = None
                            st.firing_since = None
                            st.clear_since = None
                            transitions.append(
                                self._event('resolved', rule, st, t))
                    else:
                        st.clear_since = None
                        breach, info = rule.check(self.history, t)
                        if breach:
                            st.last_value = info['value']
                            st.last_series = info['series']
        for ev in transitions:
            self._emit(ev)
        return transitions

    def _event(self, what, rule, st, t):
        return {'event': what, 'rule': rule.name,
                'severity': rule.severity, 't': t,
                'value': st.last_value, 'series': st.last_series,
                'metric': rule.metric, 'source': self.source,
                'description': rule.description}

    def _emit(self, ev):
        """Everything a transition owes the observatory: events ring,
        gauges, structured log, flight-recorder journal, artifact."""
        self._events.append(ev)
        del self._events[:-self.MAX_EVENTS]
        active = 1 if ev['event'] == 'fired' else 0
        self.registry.gauge(
            'ptpu_alert_active',
            help='1 while the rule is firing, 0 otherwise',
            labelnames=('rule', 'severity')).set(
            active, rule=ev['rule'], severity=ev['severity'])
        counter = ('ptpu_alert_fired_total' if ev['event'] == 'fired'
                   else 'ptpu_alert_resolved_total')
        self.registry.counter(
            counter,
            help=f'alert rules {ev["event"]} (lifetime)',
            labelnames=('rule', 'severity')).inc(
            rule=ev['rule'], severity=ev['severity'])
        try:
            from ..distributed import flight_recorder as _fr
            seq = _fr.recorder().record_enqueue(
                f'alert_{ev["event"]}:{ev["rule"]}')
            _fr.recorder().record_complete(seq)
        except Exception:                   # noqa: BLE001
            pass
        try:
            from ..distributed.fleet.utils.log_util import log_json
            log_json('alert_' + ev['event'],
                     level=('error' if ev['severity'] == 'critical'
                            and ev['event'] == 'fired' else 'info'),
                     msg=f"alert {ev['rule']} {ev['event']} "
                         f"({ev['severity']}): {ev['description']}",
                     **{k: v for k, v in ev.items()
                        if k not in ('event', 'description')})
        except Exception:                   # noqa: BLE001
            pass
        self._write_report()

    # -- views / artifact ----------------------------------------------------
    def active(self):
        with self._lock:
            return [{'rule': r.name, 'severity': r.severity,
                     'since': self._states[r.name].firing_since,
                     'value': self._states[r.name].last_value,
                     'series': self._states[r.name].last_series,
                     'description': r.description}
                    for r in self.rules
                    if self._states[r.name].state == 'firing']

    def snapshot(self):
        """JSON-ready view for health_dump alerts / bench records:
        per-rule state table + the capped transition ring."""
        with self._lock:
            rules = []
            for r in self.rules:
                st = self._states[r.name]
                rules.append(dict(r.describe(), state=st.state,
                                  fired=st.fired,
                                  last_value=st.last_value,
                                  last_series=st.last_series))
            return {'source': self.source, 'evals': self._evals,
                    'rules': rules, 'events': list(self._events)}

    def summary(self):
        """The compact block bench legs record: counts by severity so
        _check_legs can assert 'no critical alert fired' cheaply."""
        with self._lock:
            fired = {}
            for ev in self._events:
                if ev['event'] == 'fired':
                    fired[ev['severity']] = \
                        fired.get(ev['severity'], 0) + 1
            return {
                'rules': len(self.rules),
                'evals': self._evals,
                'fired_total': sum(fired.values()),
                'fired_critical': fired.get('critical', 0),
                'fired_by_severity': fired,
                'active': [r.name for r in self.rules
                           if self._states[r.name].state == 'firing'],
            }

    def report(self):
        """The alert_report artifact doc (capped): every transition in
        the ring plus the current rule table."""
        return {'kind': 'alert_report', 'source': self.source,
                'max_events': self.MAX_EVENTS, **self.snapshot()}

    def _write_report(self):
        if not self.report_dir:
            return
        try:
            os.makedirs(self.report_dir, exist_ok=True)
            path = os.path.join(self.report_dir,
                                f'alert_report.{self.source}.json')
            with open(path, 'w') as f:
                json.dump(self.report(), f, indent=1, default=str)
            self.last_report_path = path
        except OSError:
            pass


# ---------------------------------------------------------------------------
# built-in rule packs
# ---------------------------------------------------------------------------
def default_rules(host_bound=0.6, pool_high=0.97, pool_clear=0.8,
                  tps_drop_frac=0.5, degrade_stage=2,
                  goodput_floor=0.8, stale_s=30.0, for_s=2.0):
    """Engine-scope pack over the signals PRs 6-17 already publish.
    Thresholds are keyword-tunable; the defaults are documented in
    docs/observability.md#time-series--alerts."""
    return [
        AlertRule('host_bound',
                  metric='ptpu_serve_ledger_host_bound_fraction',
                  op='>', value=host_bound, for_s=for_s,
                  severity='warn',
                  description='decode iterations dominated by host '
                              'gaps — the multi-token-dispatch '
                              'ROADMAP item is being paid for'),
        AlertRule('kv_pool_pressure',
                  metric='ptpu_serve_kv_page_utilization',
                  op='>=', value=pool_high, clear_value=pool_clear,
                  for_s=for_s, severity='critical',
                  description='KV pool occupancy ~1: admissions '
                              'spill/preempt; degrade ladder or '
                              'host-tier spill imminent'),
        AlertRule('decode_tps_drop', kind='ewma_drop',
                  metric='ptpu_serve_decode_tokens_per_sec',
                  value=tps_drop_frac, tau_s=30.0, for_s=for_s,
                  severity='warn',
                  description='decode tokens/sec fell below '
                              f'{tps_drop_frac:.0%} of its own EWMA '
                              'trend'),
        AlertRule('degrade_stage',
                  metric='ptpu_serve_degrade_stage',
                  op='>=', value=float(degrade_stage),
                  clear_value=float(degrade_stage) - 1.0,
                  for_s=for_s, severity='critical',
                  description='graceful-degradation ladder at '
                              'prefill-shrink or weighted-eviction '
                              'stage, sustained'),
        AlertRule('goodput_drop',
                  metric='ptpu_serve_goodput_fraction',
                  op='<', value=goodput_floor, for_s=for_s,
                  severity='warn',
                  description='delivered/emitted token fraction '
                              'below floor — aborts, preemption '
                              'recompute or spec overdraft dominate'),
        AlertRule('straggler_events', kind='delta',
                  metric='ptpu_straggler_events_total',
                  value=1.0, window_s=60.0, for_s=0.0,
                  severity='warn',
                  description='a rank exceeded the straggler '
                              'relative-wall bound in the window'),
        AlertRule('metrics_stale', kind='staleness',
                  metric='ptpu_serve_decode_tokens_per_sec',
                  value=stale_s, for_s=0.0, severity='info',
                  description='the serving engine stopped publishing '
                              '— stats below this age are a dead '
                              'signal'),
    ]


def router_rules(beat_stale_s=5.0, pool_high=0.95, pool_clear=0.75,
                 pool_for_s=1.0, imbalance=0.5, drains_per_min=2.0,
                 resubmits_per_min=8.0, spills_per_min=30.0):
    """Cluster-scope pack the router evaluates over its federated
    registry (every series carries a `replica` label there). The
    heartbeat bound should sit WELL UNDER the router's own
    hang_timeout_s so the alert precedes the drain."""
    return [
        AlertRule('replica_heartbeat_stale',
                  metric='ptpu_cluster_replica_beat_age_seconds',
                  op='>', value=beat_stale_s,
                  clear_value=beat_stale_s / 2.0,
                  for_s=0.0, severity='critical',
                  description='a replica step-loop heartbeat went '
                              'stale — precedes the watchdog drain'),
        AlertRule('cluster_pool_pressure',
                  metric='ptpu_serve_kv_page_utilization',
                  op='>=', value=pool_high, clear_value=pool_clear,
                  for_s=pool_for_s, severity='critical',
                  description='a replica KV pool is saturated under '
                              'load (spills/preemptions follow) — '
                              'the autoscaler grow signal'),
        AlertRule('occupancy_imbalance', kind='spread',
                  metric='ptpu_cluster_replica_occupancy',
                  value=imbalance, for_s=5.0, severity='warn',
                  description='decode-slot occupancy spread across '
                              'replicas — affinity skew or a slow '
                              'replica'),
        AlertRule('drain_storm', kind='delta',
                  metric='ptpu_route_drains_total',
                  value=drains_per_min, window_s=60.0, for_s=0.0,
                  severity='critical',
                  description='multiple replicas drained within a '
                              'minute — correlated failure, not one '
                              'bad host'),
        AlertRule('resubmit_storm', kind='delta',
                  metric='ptpu_route_resubmits_total',
                  value=resubmits_per_min, window_s=60.0, for_s=0.0,
                  severity='warn',
                  description='drain resubmissions moving significant '
                              'in-flight work between replicas'),
        AlertRule('spill_rate', kind='delta',
                  metric='ptpu_route_spills_total',
                  value=spills_per_min, window_s=60.0, for_s=0.0,
                  severity='warn',
                  description='affinity placements diverted by '
                              'backpressure — prefix locality is '
                              'being destroyed by load'),
    ]
