"""Metric history rings — the telemetry time axis (ISSUE 18).

PRs 1-17 gave every subsystem point-in-time `ptpu_*` gauges; this
module retains their HISTORY. `MetricHistory` is an opt-in sampler
over a `MetricsRegistry` (monitor.MetricsRegistry.enable_history):
each `sample()` appends one `(t, value)` point per counter/gauge
series (histograms contribute their `_count`/`_sum` streams) into a
bounded per-series ring, so memory is O(series x capacity) and a
sampler left running forever never grows.

Cadence: callers piggyback `tick()` on the cadences that already
exist — the serving engine's publish interval, the profiler's
step-telemetry flush, the cluster router's status refresh — plus an
optional low-rate background thread (`start_background`) for idle
processes. Sampling is METADATA-ONLY: it reads host-side floats the
publishers already materialized, adds zero device work and zero host
syncs on hot paths (asserted by the PR-6 sync-budget harness in
tests/test_timeseries.py).

Derived views (`rate`, `delta`, `ewma`, `window`, `sustained`) are
what the alert-rules engine (core/alerts.py) and the future
autoscaler consume: sustained-pressure windows, rate-of-change, and
trend baselines. `export()` is the downsampled JSON block bench
records carry; `sparkline()` renders a ring for health_dump.

The clock is injectable (defaults to monitor's, which tests swap via
monitor.set_time_fn) so fire -> sustain -> clear walks are
deterministic.
"""
import collections
import threading

from . import monitor as _mon

_SPARK_BARS = '▁▂▃▄▅▆▇█'


def series_key(name, labels=()):
    """Canonical string key for one series: `name` or
    `name{k="v",...}` with labels sorted by name — stable across
    processes, parseable by the health_dump renderer."""
    if not labels:
        return name
    inner = ','.join(f'{k}="{v}"' for k, v in sorted(labels))
    return name + '{' + inner + '}'


def sparkline(values, width=24):
    """Unicode sparkline of a value sequence, downsampled to `width`
    columns (empty string for no data; flat series render mid-bar)."""
    vals = [float(v) for v in values]
    if not vals:
        return ''
    if len(vals) > width:
        stride = len(vals) / float(width)
        vals = [vals[min(int(i * stride), len(vals) - 1)]
                for i in range(width - 1)] + [vals[-1]]
    lo, hi = min(vals), max(vals)
    if hi - lo <= 1e-12:
        return _SPARK_BARS[3] * len(vals)
    span = hi - lo
    return ''.join(
        _SPARK_BARS[min(int((v - lo) / span * 8), 7)] for v in vals)


class _Ring:
    """One bounded (t, value) history. Appends and reads are guarded
    by the owning MetricHistory's lock."""

    __slots__ = ('points', 'kind')

    def __init__(self, capacity, kind):
        self.points = collections.deque(maxlen=capacity)
        self.kind = kind


class MetricHistory:
    """Per-series ring-buffer history over one MetricsRegistry.

    `sample()` walks the registry; `tick()` is the piggyback entry
    (rate-limited by `min_interval_s`, then runs attached
    AlertManagers). All views take (name, labels=None); with labels
    None a single-series metric resolves implicitly and a multi-series
    one must be addressed by its labels dict.
    """

    def __init__(self, registry, capacity=240, min_interval_s=0.0,
                 clock=None):
        self.registry = registry
        self.capacity = int(capacity)
        if self.capacity < 2:
            raise ValueError("history needs capacity >= 2")
        self.min_interval_s = float(min_interval_s)
        self._clock = clock or _mon.now
        self._lock = threading.Lock()
        self._rings = {}            # (name, labelkey) -> _Ring
        self._epoch = registry.epoch
        self._samples = 0
        self._last_sample_t = None
        self._managers = []         # AlertManagers run by tick()
        self._bg = None
        self._bg_stop = None

    # -- sampling ------------------------------------------------------------
    def sample(self, now=None):
        """Record one point per series. O(live series); reads only the
        host-side values the publishers already wrote."""
        t = self._clock() if now is None else now
        if self.registry.epoch != self._epoch:
            self.clear()
        rows = []                   # gather outside our lock
        for m in self.registry.metrics_list():
            for key, child in m._series().items():
                if m.kind == 'histogram':
                    v = child.value()
                    rows.append(((m.name + '_count', key), 'counter',
                                 float(v['count'])))
                    rows.append(((m.name + '_sum', key), 'counter',
                                 float(v['sum'])))
                else:
                    rows.append(((m.name, key), m.kind,
                                 float(child.value())))
        with self._lock:
            for (name, key), kind, v in rows:
                ring = self._rings.get((name, key))
                if ring is None:
                    ring = self._rings[(name, key)] = _Ring(
                        self.capacity, kind)
                ring.points.append((t, v))
            self._samples += 1
            self._last_sample_t = t
            n_series = len(self._rings)
            n_points = sum(len(r.points) for r in self._rings.values())
        # self-observability (next sample picks these up): how much
        # the time axis itself costs
        self.registry.counter(
            'ptpu_ts_samples_total',
            help='history sampler passes over the registry').inc()
        self.registry.gauge(
            'ptpu_ts_series_tracked',
            help='series with a live history ring').set(n_series)
        self.registry.gauge(
            'ptpu_ts_points_retained',
            help='(t, value) points currently held across all '
                 'rings').set(n_points)
        self.registry.gauge(
            'ptpu_ts_ring_capacity',
            help='per-series ring capacity (memory bound = series x '
                 'capacity points)').set(self.capacity)
        return t

    def tick(self):
        """Rate-limited sample + alert evaluation — the piggyback
        entry for existing publish cadences. Returns the alert
        transitions this pass produced (empty when quiet)."""
        t = self._clock()
        if (self._last_sample_t is None
                or t - self._last_sample_t >= self.min_interval_s):
            self.sample(now=t)
        events = []
        for mgr in list(self._managers):
            events.extend(mgr.evaluate(now=t) or ())
        return events

    def attach(self, manager):
        if manager not in self._managers:
            self._managers.append(manager)

    def detach(self, manager):
        if manager in self._managers:
            self._managers.remove(manager)

    def clear(self):
        with self._lock:
            self._rings.clear()
        self._epoch = self.registry.epoch
        self._last_sample_t = None

    # -- background tick (idle processes without a publish cadence) ----------
    def start_background(self, interval_s=5.0):
        """Low-rate daemon tick for processes with no natural publish
        cadence. Idempotent; `stop_background()` joins it."""
        if self._bg is not None:
            return self._bg
        import time as _time
        self._bg_stop = threading.Event()

        def _loop():
            while not self._bg_stop.wait(interval_s):
                try:
                    self.tick()
                except Exception:           # noqa: BLE001
                    pass                    # observability never kills

        self._bg = threading.Thread(target=_loop, name='metric-history',
                                    daemon=True)
        self._bg.start()
        return self._bg

    def stop_background(self):
        if self._bg is None:
            return
        self._bg_stop.set()
        self._bg.join(timeout=5)
        self._bg = None
        self._bg_stop = None

    # -- series access -------------------------------------------------------
    def series_names(self):
        with self._lock:
            return sorted({name for name, _k in self._rings})

    def label_keys(self, name):
        with self._lock:
            return sorted(k for n, k in self._rings if n == name)

    def points(self, name, labels=None):
        """The (t, value) list for one series (oldest first); [] when
        the series has no ring yet."""
        ring = self._resolve(name, labels)
        if ring is None:
            return []
        with self._lock:
            return list(ring.points)

    def iter_series(self, name):
        """[(raw_label_key_tuple, points), ...] for every series of
        `name` — the rules engine evaluates label-agnostic rules over
        all of a metric's series (worst series wins)."""
        with self._lock:
            return [(k, list(r.points))
                    for (n, k), r in sorted(self._rings.items())
                    if n == name]

    def _resolve(self, name, labels):
        with self._lock:
            if labels is not None:
                key = tuple(str(v) for _k, v in sorted(labels.items()))
                return self._rings.get((name, key))
            hits = [(k, r) for (n, k), r in self._rings.items()
                    if n == name]
        if not hits:
            return None
        if len(hits) > 1:
            raise ValueError(
                f"{name} has {len(hits)} labeled series — pass "
                f"labels= (keys: {[k for k, _r in hits]})")
        return hits[0][1]

    # -- derived views -------------------------------------------------------
    def last(self, name, labels=None):
        pts = self.points(name, labels)
        return pts[-1][1] if pts else None

    def delta(self, name, window_s, labels=None, now=None):
        """value(now) - value(entering the trailing window). None
        until two points exist. For counters this is the windowed
        increment; for gauges the net movement."""
        pts = self.points(name, labels)
        if len(pts) < 2:
            return None
        t = (self._clock() if now is None else now)
        t0 = t - float(window_s)
        base = None
        for pt, pv in pts:
            if pt <= t0:
                base = pv
            else:
                break
        if base is None:
            base = pts[0][1]
        return pts[-1][1] - base

    def rate(self, name, window_s, labels=None, now=None):
        """Per-second slope over the trailing window (delta over the
        ACTUAL covered span, not the nominal window). None until two
        points exist or the span is zero."""
        pts = self.points(name, labels)
        if len(pts) < 2:
            return None
        t = (self._clock() if now is None else now)
        t0 = t - float(window_s)
        base_t, base_v = pts[0]
        for pt, pv in pts:
            if pt <= t0:
                base_t, base_v = pt, pv
            else:
                break
        span = pts[-1][0] - base_t
        if span <= 0:
            return None
        return (pts[-1][1] - base_v) / span

    def ewma(self, name, tau_s, labels=None):
        """Time-weighted exponential moving average over the whole
        ring (alpha per step = 1 - exp(-dt/tau)): the trend baseline
        the decode-throughput-drop rule compares against."""
        import math
        pts = self.points(name, labels)
        if not pts:
            return None
        acc = pts[0][1]
        for (t0, _v0), (t1, v1) in zip(pts, pts[1:]):
            dt = max(t1 - t0, 0.0)
            alpha = 1.0 - math.exp(-dt / max(float(tau_s), 1e-9))
            acc += alpha * (v1 - acc)
        return acc

    def window(self, name, window_s, labels=None, now=None):
        """mean/min/max/n over the trailing window (None-able)."""
        pts = self.points(name, labels)
        t = (self._clock() if now is None else now)
        t0 = t - float(window_s)
        vals = [v for pt, v in pts if pt >= t0]
        if not vals:
            return {'mean': None, 'min': None, 'max': None, 'n': 0}
        return {'mean': sum(vals) / len(vals), 'min': min(vals),
                'max': max(vals), 'n': len(vals)}

    def sustained(self, name, pred, for_s, labels=None, now=None):
        """True iff `pred(value)` held for the ENTIRE trailing `for_s`
        window: every sample inside the window satisfies it, the value
        held entering the window satisfies it, and the ring actually
        covers the window (no vacuous truth on a series younger than
        the sustain bound)."""
        pts = self.points(name, labels)
        if not pts:
            return False
        t = (self._clock() if now is None else now)
        t0 = t - float(for_s)
        entering = None
        covered = False
        for pt, pv in pts:
            if pt <= t0:
                entering = pv
                covered = True
            elif not pred(pv):
                return False
        if not covered:
            return False
        return pred(entering)

    def age_s(self, name, labels=None, now=None):
        """Seconds since this series was last SAMPLED (ring view; the
        registry's per-child `age_s` is the publish-side stamp)."""
        pts = self.points(name, labels)
        if not pts:
            return None
        return (self._clock() if now is None else now) - pts[-1][0]

    # -- export / rendering --------------------------------------------------
    def export(self, max_points=32, names=None):
        """Downsampled JSON-ready dump: {series_key: {kind, t: [...],
        v: [...], last, min, max}} — the block bench legs record and
        health_dump sparklines render. Timestamps are relative to the
        newest sample (small, diff-friendly numbers)."""
        with self._lock:
            items = sorted(self._rings.items())
            snap = [((n, k), r.kind, list(r.points)) for (n, k), r
                    in items]
        label_names = self._export_label_names()
        out = {}
        for (name, key), kind, pts in snap:
            if names is not None and name not in names:
                continue
            if not pts:
                continue
            if len(pts) > max_points:
                stride = len(pts) / float(max_points)
                pts = [pts[min(int(i * stride), len(pts) - 1)]
                       for i in range(max_points - 1)] + [pts[-1]]
            t_end = pts[-1][0]
            vals = [v for _t, v in pts]
            lnames = label_names.get(name, ())
            out[series_key(name, tuple(zip(lnames, key)))] = {
                'kind': kind,
                't': [round(t - t_end, 3) for t, _v in pts],
                'v': [round(v, 6) for v in vals],
                'last': vals[-1], 'min': min(vals), 'max': max(vals),
            }
        return out

    def _export_label_names(self):
        """metric name -> labelnames, for rendering label keys in
        export(). Histogram-derived `_count`/`_sum` series inherit the
        parent metric's labelnames."""
        names = {}
        for m in self.registry.metrics_list():
            names[m.name] = m.labelnames
            if m.kind == 'histogram':
                names[m.name + '_count'] = m.labelnames
                names[m.name + '_sum'] = m.labelnames
        return names

    def sparkline(self, name, labels=None, width=24):
        return sparkline([v for _t, v in self.points(name, labels)],
                         width=width)

    def snapshot(self):
        """Sampler health view (health_dump / bench): counts only,
        never the raw rings."""
        with self._lock:
            return {
                'capacity': self.capacity,
                'samples': self._samples,
                'series': len(self._rings),
                'points': sum(len(r.points)
                              for r in self._rings.values()),
                'last_sample_t': self._last_sample_t,
            }
