"""Async step pipeline — windowed dispatch + host-gap observability.

The training loops were host-synchronous: one `device_put` of the batch,
one host-computed LR scalar, and one blocking loss fetch per step, so the
accelerator idled in the host gap between dispatches. This module holds
the pieces every compiled engine shares to close that seam (the
host↔device twin of the ISSUE-10 comm/compute overlap):

  * `AsyncResult` — what `engine.train_step(...)` returns: the
    device-resident fp32 loss (and, when present, the found-inf flag and
    numerics taps) with NO host fetch. Deferred per-step work — taps
    processing, GradScaler found-inf accounting — runs at `wait()`, the
    window-drain point, never in the dispatch hot path.
  * `DispatchWindow` — a bounded in-flight queue (`PTPU_DISPATCH_WINDOW`,
    default 2): the host runs ahead by at most k dispatched steps; the
    (k+1)-th dispatch drains the oldest, which in steady state is
    already done on device. `flush()` drains everything — the engines
    call it from `state_dict`/`sync_model` so checkpoints always see
    every dispatched step applied.
  * `HostGapMonitor` — per-step dispatch/ready timestamps (surfaced as
    `step::dispatch` spans through the PR-1 profiler) yielding the
    `ptpu_host_gap_seconds` / `ptpu_host_dispatch_depth` gauges and a
    `host_bound_fraction` (mean host gap / mean step interval) so a
    bench round can tell compute-bound from host-bound.

fp32 invariant: the windowed loop dispatches the SAME executable with
the same key/lr/batch sequence as the synchronous loop, so the loss
sequence is bit-identical — the window changes when the host looks, not
what the device computes.

Knobs (docs/performance.md#async-dispatch):
  PTPU_DISPATCH_WINDOW  max in-flight dispatched steps (default 2)
  PTPU_DEVICE_PREFETCH  DeviceLoader prefetch depth (default 2)
  PTPU_DEVICE_LR        opt-in on-device LR schedules (default off)
"""
import collections
import os
import threading
import time


DEFAULT_DISPATCH_WINDOW = 2
DEFAULT_PREFETCH_DEPTH = 2


def _env_int(name, default):
    v = os.environ.get(name)
    if v is None or v == '':
        return default
    try:
        return int(v)
    except ValueError:
        return default


def resolve_dispatch_window(window=None):
    """In-flight dispatch window: kwarg -> PTPU_DISPATCH_WINDOW -> 2.
    Clamped to >= 1 (window 1 == drain every step == the synchronous
    discipline with the fetch still deferred to the drain point)."""
    if window is None:
        window = _env_int('PTPU_DISPATCH_WINDOW', DEFAULT_DISPATCH_WINDOW)
    return max(int(window), 1)


def resolve_prefetch_depth(depth=None):
    """DeviceLoader double/triple-buffer depth: kwarg ->
    PTPU_DEVICE_PREFETCH -> 2. Clamped to >= 1."""
    if depth is None:
        depth = _env_int('PTPU_DEVICE_PREFETCH', DEFAULT_PREFETCH_DEPTH)
    return max(int(depth), 1)


def resolve_device_lr(flag=None):
    """On-device LR schedule knob: kwarg -> PTPU_DEVICE_LR -> False.

    Opt-in: the device step counter advances once per compiled step, so
    it only mirrors the host scheduler when the training loop drives
    `scheduler.step()` once per train step (the standard GPT loop) —
    epoch-driven schedules (hapi's LRSchedulerCallback default) must
    keep the host feed."""
    if flag is not None:
        return bool(flag)
    v = os.environ.get('PTPU_DEVICE_LR')
    if v is None or v == '':
        return False
    return v.lower() in ('1', 'true', 'yes')


# ---------------------------------------------------------------------------
# host-gap observability
# ---------------------------------------------------------------------------
_monitors = {}          # site -> HostGapMonitor (latest per site wins)
_monitors_lock = threading.Lock()

# blocked-on-progress time reported by code that doesn't know which
# engine dispatches next on this thread (DeviceLoader's consumer-side
# queue wait: the batch transfer is in flight on the producer thread —
# surfaced separately as a prefetch stall, not as host gap). The next
# dispatch_begin on the same thread consumes it.
_tls = threading.local()


def note_external_blocked(seconds):
    _tls.blocked = getattr(_tls, 'blocked', 0.0) + max(float(seconds),
                                                       0.0)


def _take_external_blocked():
    v = getattr(_tls, 'blocked', 0.0)
    _tls.blocked = 0.0
    return v


class HostGapMonitor:
    """Rolling per-step dispatch timestamps for one engine site.

    The inter-dispatch span (dispatch_end(i) → dispatch_begin(i+1))
    decomposes into three attributed parts:

    * GATING time (`host_gap_seconds`): blocking waits on the NEWEST
      dispatched step — the synchronous discipline's fetch. Nothing is
      queued behind that step, so the device runs dry for the wait's
      tail plus all host work after it; this is exactly the
      serialization windowed dispatch eliminates, and it is measured
      from attributed call durations, so it stays deterministic even
      on a shared/1-core host where wall residue is scheduler noise.
    * BLOCKED time (`blocked_wait_seconds`): waits on OLDER steps (the
      windowed drain — newer steps remain enqueued as runway) and
      DeviceLoader queue waits (the transfer is in flight on the
      producer thread; surfaced separately as prefetch stalls). The
      device is busy throughout — not host gap.
    * RESIDUE (`host_residue_seconds`): the unattributed wall
      remainder — genuine per-step host work (batch feeds, python
      overhead) on a quiet multi-core host; on a shared single core it
      also absorbs OS starvation while XLA compute threads run, so
      hardware rounds read it and CPU dryruns lean on the gating term.

    step_i  = dispatch_begin(i+1) - dispatch_begin(i): the wall interval
              between submissions.
    host_bound_fraction = sum(gating) / sum(step intervals) over the
    rolling window — ~1.0 means every step serializes behind a host
    fetch (host-bound discipline), ~0.0 means the host always has the
    next step enqueued before the device needs it.
    """

    def __init__(self, site, window=64, clock=time.perf_counter):
        self.site = site
        self._clock = clock
        self._gaps = collections.deque(maxlen=window)       # gating
        self._residues = collections.deque(maxlen=window)
        self._intervals = collections.deque(maxlen=window)
        self._depths = collections.deque(maxlen=window)
        self._blocked = collections.deque(maxlen=window)
        self._blocked_since_end = 0.0
        self._gating_since_end = 0.0
        self._last_begin = None
        self._last_end = None
        self.steps = 0
        self.drained = 0
        self.dispatched_total = 0   # monotonic — AsyncResults key off it
        with _monitors_lock:
            _monitors[site] = self

    def reset(self):
        self._gaps.clear()
        self._residues.clear()
        self._intervals.clear()
        self._depths.clear()
        self._blocked.clear()
        self._blocked_since_end = 0.0
        self._gating_since_end = 0.0
        self._last_begin = None
        self._last_end = None
        self.steps = 0
        self.drained = 0

    def dispatch_begin(self):
        now = self._clock()
        blocked = self._blocked_since_end + _take_external_blocked()
        gating = self._gating_since_end
        if self._last_end is not None:
            raw = max(now - self._last_end, 0.0)
            self._gaps.append(gating)
            self._residues.append(max(raw - gating - blocked, 0.0))
            self._blocked.append(blocked)
        if self._last_begin is not None:
            self._intervals.append(max(now - self._last_begin, 0.0))
        self._last_begin = now
        return now

    def dispatch_end(self, depth=1):
        self._last_end = self._clock()
        self._blocked_since_end = 0.0
        self._gating_since_end = 0.0
        self._depths.append(int(depth))
        self.steps += 1
        self.dispatched_total += 1

    def note_blocked(self, seconds):
        """The host just spent `seconds` blocked on device progress the
        device had queued runway behind (windowed drain) — busy device,
        not host gap."""
        self._blocked_since_end += max(float(seconds), 0.0)

    def note_gating(self, seconds):
        """The host just spent `seconds` blocked on the NEWEST
        dispatched step (synchronous-discipline fetch): the device's
        queue is empty behind it — starvation exposure, counted as
        host gap."""
        self._gating_since_end += max(float(seconds), 0.0)

    def drain_point(self):
        """An explicit drain barrier (engine.flush / trial end): the
        waits it performed are deliberate, not inter-step host gap —
        consume the pending attributions so they can't leak into the
        NEXT dispatch's gap sample."""
        self._gating_since_end = 0.0
        self._blocked_since_end = 0.0
        _take_external_blocked()

    def step_ready(self):
        self.drained += 1

    # -- derived --------------------------------------------------------------
    def host_gap_seconds(self):
        return (sum(self._gaps) / len(self._gaps)) if self._gaps else 0.0

    def host_bound_fraction(self):
        total = sum(self._intervals)
        if not total:
            return None
        gaps = list(self._gaps)[-len(self._intervals):]
        return min(sum(gaps) / total, 1.0)

    def snapshot(self):
        depths = list(self._depths)
        return {
            'steps': self.steps,
            'drained': self.drained,
            'host_gap_seconds': self.host_gap_seconds(),
            'host_gap_seconds_max': max(self._gaps) if self._gaps else 0.0,
            'host_residue_seconds':
                (sum(self._residues) / len(self._residues))
                if self._residues else 0.0,
            'blocked_wait_seconds':
                (sum(self._blocked) / len(self._blocked))
                if self._blocked else 0.0,
            'step_interval_seconds':
                (sum(self._intervals) / len(self._intervals))
                if self._intervals else 0.0,
            'host_bound_fraction': self.host_bound_fraction(),
            'dispatch_depth_mean':
                (sum(depths) / len(depths)) if depths else 0.0,
            'dispatch_depth_max': max(depths) if depths else 0,
        }

    def publish(self):
        """Push the rolling view into core.monitor (the engines call
        this from flush(), never from the dispatch hot path)."""
        from . import monitor as _m
        snap = self.snapshot()
        _m.gauge('ptpu_host_gap_seconds',
                 help='rolling mean host gap between step dispatches',
                 labelnames=('site',)).set(snap['host_gap_seconds'],
                                           site=self.site)
        _m.gauge('ptpu_host_dispatch_depth',
                 help='rolling mean in-flight dispatched steps',
                 labelnames=('site',)).set(snap['dispatch_depth_mean'],
                                           site=self.site)
        if snap['host_bound_fraction'] is not None:
            _m.gauge('ptpu_host_bound_fraction',
                     help='host gap / step interval over the rolling '
                          'window (1.0 = host-bound)',
                     labelnames=('site',)).set(
                         snap['host_bound_fraction'], site=self.site)
        return snap


# ---------------------------------------------------------------------------
# prefetch totals (DeviceLoader reports here; StepTelemetry reads)
# ---------------------------------------------------------------------------
_prefetch = {'loaders': 0, 'batches': 0, 'stalls': 0, 'h2d_bytes': 0,
             'depth': None, 'ring_reuses': 0}
_prefetch_lock = threading.Lock()


def note_prefetch(loaders=0, batches=0, stalls=0, h2d_bytes=0,
                  depth=None, ring_reuses=0):
    with _prefetch_lock:
        _prefetch['loaders'] += loaders
        _prefetch['batches'] += batches
        _prefetch['stalls'] += stalls
        _prefetch['h2d_bytes'] += h2d_bytes
        _prefetch['ring_reuses'] += ring_reuses
        if depth is not None:
            _prefetch['depth'] = depth


def reset_prefetch_totals():
    with _prefetch_lock:
        _prefetch.update(loaders=0, batches=0, stalls=0, h2d_bytes=0,
                         depth=None, ring_reuses=0)


def unregister_monitor(monitor):
    """Drop a shut-down engine's monitor from the registry (only if it
    is still the registered one for its site) so telemetry stops
    reporting a dead engine's rolling stats."""
    with _monitors_lock:
        if _monitors.get(monitor.site) is monitor:
            del _monitors[monitor.site]


def host_snapshot():
    """The StepTelemetry.snapshot()['host'] payload: per-site dispatch
    gap/depth views + aggregated DeviceLoader prefetch totals. None-ish
    (empty sites, zero counters) when no async loop ran."""
    with _monitors_lock:
        sites = {site: mon.snapshot() for site, mon in _monitors.items()}
    with _prefetch_lock:
        prefetch = dict(_prefetch)
    return {'sites': sites, 'prefetch': prefetch}


# ---------------------------------------------------------------------------
# async step results + bounded window
# ---------------------------------------------------------------------------
class AsyncResult:
    """One dispatched train step: device-resident loss, no host fetch.

    `wait()` blocks until the device finished this step (NOT a
    transfer) and runs the deferred drain work (numerics taps /
    GradScaler accounting) exactly once, in drain order. `result()`
    performs the one host fetch — routed through the numerics
    observatory's `_host_fetch` hook so the sync-count harness sees it.
    """

    __slots__ = ('loss', 'found_inf', 'step', '_taps', '_on_drain',
                 '_monitor', '_drained', '_counted', '_host_loss',
                 '_seq')

    def __init__(self, loss, step, found_inf=None, taps=None,
                 on_drain=None, monitor=None):
        self.loss = loss
        self.found_inf = found_inf
        self.step = step
        self._taps = taps
        self._on_drain = on_drain
        self._monitor = monitor
        self._drained = False
        self._counted = False
        self._host_loss = None
        # dispatch sequence snapshot: while this is still the NEWEST
        # dispatched step, a blocking wait on it is the synchronous
        # discipline (no queued runway) and counts as host gap
        self._seq = monitor.dispatched_total if monitor is not None \
            else None

    @property
    def taps(self):
        return self._taps

    def done(self):
        return self._drained

    def wait(self):
        if self._drained:
            return self
        t0 = time.perf_counter()
        try:
            self.loss.block_until_ready()
        except AttributeError:
            pass
        if self._monitor is not None and not self._counted:
            self._counted = True
            dt = time.perf_counter() - t0
            if self._seq != self._monitor.dispatched_total:
                # waiting on an OLD step while newer ones sit queued
                # behind it: the device has runway — blocked, not gap
                self._monitor.note_blocked(dt)
            else:
                # the synchronous discipline: nothing queued behind —
                # this wait (and the host work after it) starves the
                # device, so it counts as host gap
                self._monitor.note_gating(dt)
            self._monitor.step_ready()
        # run the deferred drain work BEFORE latching: if it raises
        # (deferred NumericsError from the taps check), a later
        # wait()/flush() retries it instead of silently dropping the
        # rest of the step's accounting (e.g. the scaler update)
        cb = self._on_drain
        if cb is not None:
            cb(self)
            self._on_drain = None
        self._drained = True
        return self

    def result(self):
        """Host fp32 loss — ONE host sync (at the caller's chosen drain
        point, e.g. trial end)."""
        if self._host_loss is None:
            self.wait()
            from . import numerics as _num
            import numpy as _np
            self._host_loss = float(_np.asarray(_num._host_fetch(self.loss)))
        return self._host_loss

    def __float__(self):
        return self.result()

    def tensor(self):
        """The loss as a Tensor (still device-resident)."""
        from .tensor import Tensor
        return Tensor(self.loss)

    def __repr__(self):
        state = 'drained' if self._drained else 'in-flight'
        return f'AsyncResult(step={self.step}, {state})'


class AsyncDispatchMixin:
    """The window-drain surface shared by the three compiled engines
    (each owns a `_inflight` DispatchWindow and a `_gap`
    HostGapMonitor)."""

    def flush(self):
        """Drain the in-flight dispatch window: deferred per-step work
        (taps processing, GradScaler accounting) and gauge publication
        happen here, never in the dispatch hot loop. The flush waits
        are a deliberate barrier — excluded from the next dispatch's
        host-gap sample."""
        drained = self._inflight.flush()
        self._gap.drain_point()
        self._gap.publish()
        led = getattr(self, '_ledger', None)
        if led is not None:
            try:
                led.publish()   # ledger rides the same drain point
            except Exception:
                pass
        return drained

    def host_gap_snapshot(self):
        return self._gap.snapshot()


class DispatchWindow:
    """Bounded FIFO of in-flight AsyncResults. `push` drains the oldest
    past `size` (steady state: waits on step i-k, which the device
    already finished while the host dispatched i-k+1..i). Drain order is
    submission order — the GradScaler/taps deferred work replays exactly
    the per-step sequence."""

    def __init__(self, size):
        self.size = max(int(size), 1)
        self._q = collections.deque()

    def __len__(self):
        return len(self._q)

    def push(self, result):
        self._q.append(result)
        while len(self._q) > self.size:
            # peek-then-pop: if the deferred drain work raises (e.g. a
            # deferred NumericsError), the step STAYS at the head so a
            # later flush() retries its remaining accounting
            self._q[0].wait()
            self._q.popleft()
        return result

    def flush(self):
        drained = []
        while self._q:
            self._q[0].wait()
            drained.append(self._q.popleft())
        return drained

    def clear(self):
        self._q.clear()
